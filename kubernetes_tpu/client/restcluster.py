"""ClusterStore-shaped client over the REST API: the scheduler's remote
half.

The scheduler stack (Scheduler + TPUBatchScheduler + plugins + recorder)
talks to ONE seam: a ClusterStore-shaped ``client``. In-process runs
hand it the store; this module hands it the network — list/watch over
chunked HTTP feeding the same event handlers (reference client-go:
Clientset + SharedInformerFactory + the scheduler's informer wiring in
``pkg/scheduler/eventhandlers.go``), binds through the Binding
subresource, status writes through ``pods/{name}/status``.

Wire discipline (reference ``test/integration/scheduler_perf/util.go:
61-68`` creates clients at QPS/Burst 5000):

- every call charges a client-side token bucket PER OBJECT — a bulk
  request of N pods costs N tokens, so batching never launders rate;
- pooled keep-alive connections with TCP_NODELAY per (client, lane)
  (one urllib-style connection per request stalls ~40 ms each under
  Nagle + delayed ACK; after a transport failure the pool pre-warms a
  replacement under the retry backoff so retries never reconnect cold);
- hot-path writes ship as bulk verbs: creates as ``{Kind}List``, binds
  as ``BindingList`` (POST /bindings), status writes as
  ``PodStatusList`` (POST /statuses, see ``batched_status_writes``);
- the binary codec (``apiserver/codec.py``, the protobuf analog) is
  negotiated for every payload; JSON remains the kubectl/debug wire.
  Watch streams arrive as server-coalesced chunks (a batch of
  per-event pickles per read), decoded and delivered batch-at-a-time.

Reads the scheduler consults once per cycle (services, replica sets,
PDBs, ...) are served from short-TTL caches — the informer-cache
consistency model of the reference, with the TTL standing in for watch
propagation delay.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.serialization import from_wire, to_wire
from kubernetes_tpu.apiserver import codec
from kubernetes_tpu.apiserver.rest import KIND_TO_PLURAL
from kubernetes_tpu.apiserver.store import ADDED, DELETED, MODIFIED, Event
from kubernetes_tpu.client.backoff import Backoff, CircuitBreaker, RetryBudget
from kubernetes_tpu.observability.tracer import (
    TRACE_HEADER,
    format_trace_header,
    get_tracer,
    parse_trace_header,
)

# kinds the scheduler's event handlers consume
# (eventhandlers.py handle(); reference addAllEventHandlers)
SCHEDULER_WATCH_KINDS = (
    "Pod", "Node", "Service", "PersistentVolume", "PersistentVolumeClaim",
    "StorageClass", "CSINode",
)


class TokenBucket:
    """Client-side rate limiter (reference client-go rate.Limiter)."""

    def __init__(self, qps: float, burst: Optional[float] = None):
        self.qps = float(qps)
        self.burst = float(burst if burst is not None else qps)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def charge(self, n: float = 1.0) -> None:
        """Block until n tokens are available, then consume them. A
        charge above the burst is taken in burst-sized installments —
        the bucket can never hold more than ``burst``, so a single-shot
        wait would spin forever (client-go's WaitN just errors there;
        paying the time instead keeps bulk verbs rate-equivalent to N
        singles)."""
        remaining = float(n)
        while remaining > 0:
            take = min(remaining, self.burst)
            while True:
                with self._lock:
                    now = time.monotonic()
                    self._tokens = min(
                        self.burst,
                        self._tokens + (now - self._last) * self.qps)
                    self._last = now
                    if self._tokens >= take:
                        self._tokens -= take
                        break
                    wait = (take - self._tokens) / self.qps
                time.sleep(min(wait, 0.05))
            remaining -= take


class StaleRouteError(RuntimeError):
    """A bulk verb hit a server that no longer owns part of its batch
    (topology epoch moved underneath the split). The caller re-splits
    against the refreshed topology and resends — raised only on the
    internal fan-out path, never surfaced to API callers."""


def _sever(conn) -> None:
    """Cross-thread stream teardown: shut the RAW socket down instead
    of ``conn.close()`` — closing an http.client connection while its
    owner thread is blocked in a read deadlocks on the buffered
    reader's lock; a socket shutdown just errors the read out."""
    if conn is None:
        return
    sock = getattr(conn, "sock", None)
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def elect_trace_uid(uids) -> Optional[str]:
    """The trace-id election every client performs identically: the
    first locally-sampled uid (deterministic crc32 sampling), or None
    when tracing is off / nothing sampled. Shared with the federation
    tier so a cross-cluster hop elects the SAME trace id the
    per-cluster client will stamp on the wire."""
    tracer = get_tracer()
    if not tracer.enabled:
        return None
    for u in uids:
        if u and tracer.sampled(u):
            return u
    return None


def elect_trace_context(uids) -> Optional[str]:
    """Outgoing ``X-Ktpu-Trace`` value for a request touching these
    trace-id candidates (the bulk discipline: ONE context per batch,
    parented to the innermost open span). See
    ``RestClusterClient._trace_ctx_for`` for the contract text."""
    tracer = get_tracer()
    if not tracer.enabled:
        return None
    uids = list(uids)
    sampled = [u for u in uids if u and tracer.sampled(u)]
    if not sampled:
        return None
    parent = tracer.current_span_id()
    if len(uids) > 1:
        if not tracer.annotate_current(trace_uids=sampled):
            tracer.event("client.batch", trace=sampled[0],
                         uids=sampled, n=len(uids))
    return format_trace_header(sampled[0], parent, True)


def _key_of(obj) -> tuple:
    return (getattr(obj.metadata, "namespace", ""), obj.metadata.name)


def _rv_of(obj) -> int:
    try:
        return int(obj.metadata.resource_version or 0)
    except (TypeError, ValueError):
        return 0


class _WatchHandle:
    def __init__(self, client: "RestClusterClient"):
        self._client = client

    def stop(self) -> None:
        self._client._stop_watches()


class _ConnPool:
    """Warm keep-alive connections for one (client, lane). Connections
    are checked out per request and returned on success; a transport
    failure discards the broken connection AND pre-warms a replacement
    during the retry backoff, so the retry itself never reconnects cold
    (reference: client-go's http.Transport connection pool per host)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 max_idle: int = 8):
        self._host = host
        self._port = port
        self._timeout = timeout
        self.max_idle = max_idle
        self._idle: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()

    def _connect(self) -> http.client.HTTPConnection:
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=self._timeout)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def acquire(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return self._connect()

    def release(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        try:
            conn.close()
        except OSError:
            pass

    @staticmethod
    def discard(conn: Optional[http.client.HTTPConnection]) -> None:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def prewarm(self, n: int = 1) -> None:
        """Best-effort: open fresh connections into the idle set (called
        under retry backoff so the sleep pays the handshake)."""
        for _ in range(n):
            try:
                conn = self._connect()
            except OSError:
                return
            with self._lock:
                if len(self._idle) < self.max_idle:
                    self._idle.append(conn)
                    conn = None
            if conn is not None:
                _ConnPool.discard(conn)
                return

    def close_all(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            _ConnPool.discard(conn)


class RestClusterClient:
    def __init__(
        self,
        base_url: str,
        token: str = "",
        qps: Optional[float] = None,
        burst: Optional[float] = None,
        binary: bool = True,
        watch_kinds: Tuple[str, ...] = SCHEDULER_WATCH_KINDS,
        cache_ttl: float = 1.0,
        max_retries: int = 5,
        retry_after_cap: float = 2.0,
        backoff: Optional[Backoff] = None,
        retry_budget: Optional[RetryBudget] = None,
        breaker_threshold: int = 5,
        retry_seed: Optional[int] = None,
        flow_id: str = "",
        partition_urls: Optional[List[str]] = None,
        codec_version: int = codec.CODEC_VERSION,
    ):
        # partition-aware mode (apiserver/partition.py): one apiserver
        # endpoint per store partition. Single-object calls route by the
        # shared crc32 partition function, lists fan in across the
        # partitions a kind can live in, bulk verbs split by partition
        # and fan out, and watch opens ONE stream per (kind, partition)
        # — the merged delivery preserves per-partition ordering, which
        # is all the store ever guaranteed. ``partition_urls=None``
        # (the default) is exactly the old single-endpoint client.
        urls = [u.rstrip("/") for u in (partition_urls or [base_url])]
        self.base_url = urls[0]
        self.partition_urls = urls
        self.partitions = len(urls)
        self._endpoints: List[Tuple[str, int]] = []
        for u in urls:
            rest = u.split("://", 1)[1]
            host, _, port = rest.partition(":")
            self._endpoints.append((host, int(port or 80)))
        self._host, self._port = self._endpoints[0]
        self.token = token
        # flow distinguisher refinement for the server's API Priority &
        # Fairness layer (X-Flow-Id): several logical tenants behind one
        # identity (the bench harness's anonymous loopback clients) get
        # their own fair-queued flows instead of sharing one. The server
        # honors it only from control-plane/loopback identities —
        # untrusted tenants cannot mint flows to dodge fair queuing.
        self.flow_id = flow_id
        self.binary = binary
        self.watch_kinds = watch_kinds
        self.cache_ttl = cache_ttl
        self.limiter = TokenBucket(qps, burst) if qps else None
        # keep-alive pools per (partition, lane) (mirroring the server's
        # readonly/mutating in-flight lanes): checked out per request,
        # pre-warmed on failure so retries ride an established connection
        self._pools: Dict[Tuple[int, str], _ConnPool] = {
            (p, lane): _ConnPool(host, port)
            for p, (host, port) in enumerate(self._endpoints)
            for lane in ("ro", "rw")
        }
        # lazy executors (_fan_pool, _bind_pool) are created under this
        # lock: fan-out workers can reach the bind pool concurrently,
        # and a lost check-then-create race would leak live threads
        self._pool_init_lock = threading.Lock()
        # active batched-status-write buffers per thread (see
        # batched_status_writes)
        self._status_buffers = threading.local()
        self._ttl_cache: Dict[str, tuple] = {}
        self._stopping = threading.Event()
        self._watch_threads: List[threading.Thread] = []
        # resilience stack: jittered exponential backoff between retries
        # (deterministic under retry_seed for chaos replay), a per-client
        # retry budget so a sick server costs bounded extra load, and a
        # circuit breaker whose listener the scheduler wires to degraded
        # mode (reference client-go's rest.Config backoff + the
        # apiserver's Retry-After contract)
        self.max_retries = int(max_retries)
        self.retry_after_cap = float(retry_after_cap)
        rng = random.Random(retry_seed) if retry_seed is not None else None
        self._backoff = backoff if backoff is not None else \
            Backoff(base=0.05, factor=2.0, cap=2.0, jitter=0.4, rng=rng)
        self._retry_budget = retry_budget if retry_budget is not None \
            else RetryBudget(budget=32.0, refill_per_second=4.0)
        self.breaker = CircuitBreaker(failure_threshold=breaker_threshold)
        # resourceVersion monotonicity watchdog: list RVs per kind must
        # never regress (a WAL-restored server that lost committed
        # revisions would show up here); violations are recorded, never
        # raised — the chaos suite asserts the list stays empty
        self._rv_lock = threading.Lock()
        self._last_rv: Dict[str, int] = {}
        self.rv_regressions: List[Tuple[str, int, int]] = []
        # -- elastic control plane (live resharding) -------------------
        # None = the static PR 9 router (everything above, unchanged).
        # ``enable_topology()`` fetches the server-side routing document
        # and switches this client to epoch-aware routing: single calls
        # route by slot owner, watches become restartable per-partition
        # streams with client-held reflector state, and an epoch change
        # re-routes everything WITHOUT relisting unmoved slices.
        self._topology = None              # PartitionTopology when live
        self.topology_epoch = 0
        self._topology_lock = threading.Lock()
        self._elastic_watching = False
        self._watch_fn: Optional[Callable] = None
        self._watch_batch_fn: Optional[Callable] = None
        # client-held per-(kind, partition) reflector state: what this
        # stream has shown the consumer — the client-side half of the
        # composite cursor a migration must preserve
        self._known_lock = threading.Lock()
        self._stream_known: Dict[Tuple[str, int], Dict[tuple, Any]] = {}
        self._stream_stops: Dict[Tuple[str, int], threading.Event] = {}
        self._stream_conns: Dict[Tuple[str, int], Any] = {}
        self._handoff_lock = threading.Lock()
        self.stream_relists: Dict[Tuple[str, int], int] = {}
        self.handoff_fetches = 0
        self._topology_stop = threading.Event()
        self._topology_thread: Optional[threading.Thread] = None
        # replumb bookkeeping: routing can learn an epoch on any thread
        # (the 429 fast path), but stream surgery belongs to replumb-
        # capable callers — track which epoch the streams have caught
        # up to, and which partition indices changed since, so the
        # catch-up is never lost to an early equal-epoch return
        self._replumb_epoch = 0
        self._pending_changed: set = set()
        # partitions that GAINED keyspace since the last re-plumb: a
        # write committed on the source inside the freeze window whose
        # event never reached the source stream before the flip is in
        # NO known map — only a reconcile fetch of the gaining
        # partition can recover it
        self._pending_gained: set = set()
        # -- wire-version pin (mixed-version skew guard) ---------------
        # the highest codec version this client speaks, stamped on every
        # request; the server echoes the pinned min(server, client).
        # ``negotiated_codec[p]`` records each partition's echo — a
        # restart seam that changes it counts as a re-negotiation, an
        # echo ABOVE our stamp (a server that ignored the pin) counts
        # as a failure. Both feed the upgrade harness's invariants.
        self.codec_version = int(codec_version)
        self._codec_lock = threading.Lock()
        self.negotiated_codec: Dict[int, int] = {}
        self._codec_pending_reneg: set = set()
        self.codec_renegotiations = 0
        self.codec_failures = 0
        # -- read tier (apiserver/readtier.py) -------------------------
        # per-partition replica endpoints from the topology doc's
        # ``replicas`` field: resource reads route to a STICKY healthy
        # replica — sticky, not per-request round-robin, because the
        # RV watchdog is per (kind, partition) and replicas trail the
        # owner by independent lags, so alternating replicas would
        # read as false RV regressions. The pick advances only when
        # the current replica fails or fences (TTL'd down-mark), and
        # the watchdog baseline resets at exactly that seam.
        self._replica_lock = threading.Lock()
        self._read_replicas: Dict[int, List[Tuple[str, int]]] = {}
        self._replica_pools: Dict[Tuple[int, int], _ConnPool] = {}
        self._replica_pick: Dict[int, int] = {}
        self._replica_down: Dict[Tuple[int, int], float] = {}
        self.replica_reads = 0
        self.replica_reroutes = 0

    def set_degraded_listener(
            self, listener: Callable[[bool], None]) -> None:
        """``listener(degraded)`` fires when the circuit breaker opens
        (transport to the apiserver is gone) and again when it closes.
        The scheduler uses this to pause binding and resume cleanly."""
        self.breaker.set_listener(listener)

    # -- transport -----------------------------------------------------
    def _drop_conn(self) -> None:
        """Close every pooled keep-alive connection (tests and the
        chaos harness sever live transports after a server kill)."""
        for pool in self._pools.values():
            pool.close_all()
        with self._replica_lock:
            pools = list(self._replica_pools.values())
        for pool in pools:
            pool.close_all()

    # -- read-tier routing ---------------------------------------------
    _REPLICA_DOWN_TTL = 2.0

    @staticmethod
    def _replica_eligible(method: str, path: str) -> bool:
        """Reads of resource paths ride replicas; control/meta paths
        always hit the owner — the topology document especially (a
        stale replica's doc could wedge routing), and the subscription
        stream by definition (it IS the owner's commit log)."""
        if method not in ("GET", "HEAD"):
            return False
        if not path.startswith("/api/v1/"):
            return False
        return not path.startswith(("/api/v1/partitiontopology",
                                    "/api/v1/subscription"))

    def set_read_replicas(self, replicas) -> None:
        """Install per-partition read-replica URLs directly
        ({partition: [url, ...]}) — harness wiring without a topology
        doc; the topology path lands here too via
        ``_install_routing_locked``."""
        self._set_read_replicas({
            int(p): tuple(us) for p, us in (replicas or {}).items()})

    def _set_read_replicas(self, replicas) -> None:
        with self._replica_lock:
            new: Dict[int, List[Tuple[str, int]]] = {}
            for p, urls in (replicas or {}).items():
                eps = []
                for u in urls:
                    rest = u.split("://", 1)[1]
                    host, _, port = rest.partition(":")
                    eps.append((host, int(port or 80)))
                if eps:
                    new[int(p)] = eps
            for p in set(self._read_replicas) | set(new):
                if self._read_replicas.get(p) == new.get(p):
                    continue
                # the set changed for this partition: rebuild its pools
                # and forget its down-marks/pick (indices renumbered)
                for idx in range(len(self._read_replicas.get(p) or ())):
                    pool = self._replica_pools.pop((p, idx), None)
                    if pool is not None:
                        pool.close_all()
                    self._replica_down.pop((p, idx), None)
                for idx, (host, port) in enumerate(new.get(p) or ()):
                    self._replica_pools[(p, idx)] = _ConnPool(host, port)
                self._replica_pick.pop(p, None)
            self._read_replicas = new

    def _reset_rv_baseline(self, partition: int) -> None:
        # replica switch seam: the successor may trail the predecessor,
        # so its list RVs are BEHIND — that is staleness (bounded by
        # the fence), not the regression the watchdog hunts
        with self._rv_lock:
            for key in [k for k in self._last_rv if k[1] == partition]:
                del self._last_rv[key]

    def _pick_replica(self, partition: int) -> Optional[int]:
        """Sticky healthy replica index for a partition, or None (no
        replicas advertised / all down → owner serves the read)."""
        switched = False
        with self._replica_lock:
            reps = self._read_replicas.get(partition)
            if not reps:
                return None
            n = len(reps)
            start = self._replica_pick.get(partition)
            if start is None:
                # first pick: spread distinct client instances across
                # the replica set instead of herding onto replica 0
                start = (id(self) >> 6) % n
                self._replica_pick[partition] = start
            start %= n
            now = time.monotonic()
            pick = None
            for k in range(n):
                idx = (start + k) % n
                if self._replica_down.get((partition, idx), 0.0) > now:
                    continue
                pick = idx
                break
            if pick is None:
                return None
            if pick != start:
                self._replica_pick[partition] = pick
                switched = True
        if switched:
            self._reset_rv_baseline(partition)
        return pick

    def _mark_replica_down(self, partition: int, idx: int) -> None:
        """TTL'd down-mark after a transport failure or fence 503: the
        next pick skips this replica (and the owner absorbs the reads
        if every sibling is down too)."""
        with self._replica_lock:
            self._replica_down[(partition, idx)] = \
                time.monotonic() + self._REPLICA_DOWN_TTL
            reps = self._read_replicas.get(partition) or []
            if reps and self._replica_pick.get(partition) == idx:
                self._replica_pick[partition] = (idx + 1) % len(reps)
            self.replica_reroutes += 1
        self._reset_rv_baseline(partition)

    def _read_pool(self, partition: int,
                   lane: str) -> Tuple["_ConnPool", Optional[int]]:
        """Connection pool for a replica-eligible read: the sticky
        healthy replica's pool, else the owner's ro pool."""
        idx = self._pick_replica(partition)
        if idx is not None:
            with self._replica_lock:
                pool = self._replica_pools.get((partition, idx))
            if pool is not None:
                self.replica_reads += 1
                return pool, idx
        return self._pools[(partition, lane)], None

    def _read_endpoint(self, partition: int
                       ) -> Tuple[str, int, Optional[int]]:
        """(host, port, replica_idx|None) for a watch stream — watch
        fan-out is the read tier's whole reason to exist, so streams
        ride replicas exactly like lists do."""
        idx = self._pick_replica(partition)
        if idx is not None:
            with self._replica_lock:
                reps = self._read_replicas.get(partition) or []
                if idx < len(reps):
                    host, port = reps[idx]
                    self.replica_reads += 1
                    return host, port, idx
        host, port = self._endpoints[partition]
        return host, port, None

    def _headers(self, body_binary: bool) -> Dict[str, str]:
        h: Dict[str, str] = {}
        if self.binary:
            h["Accept"] = codec.BINARY_CONTENT_TYPE
        h["Content-Type"] = codec.BINARY_CONTENT_TYPE if body_binary \
            else "application/json"
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        if self.flow_id:
            h["X-Flow-Id"] = self.flow_id
        h[codec.VERSION_HEADER] = str(self.codec_version)
        return h

    def _record_negotiated(self, partition: int, resp) -> None:
        """Record the server's echoed wire-version pin for one
        partition. First echo just registers; a CHANGED echo is a
        re-negotiation (the restart seam put a different-version server
        behind the URL); an echo above our own stamp means the server
        ignored the pin — a contract failure, counted, and the
        connection keeps decoding at our own (lower) version, which the
        decoders tolerate for the current schema pair."""
        stamp = resp.headers.get(codec.VERSION_HEADER) if resp.headers \
            else None
        if stamp is None:
            return
        try:
            v = int(stamp)
        except ValueError:
            with self._codec_lock:
                self.codec_failures += 1
            return
        with self._codec_lock:
            if v > self.codec_version:
                self.codec_failures += 1
            prev = self.negotiated_codec.get(partition)
            if prev is not None and prev != v:
                # the server behind this URL changed its answer with no
                # routing seam in between — still a re-negotiation (an
                # in-place restart at the same port)
                self.codec_renegotiations += 1
            elif partition in self._codec_pending_reneg:
                # first echo across a restart seam: re-pinned
                self.codec_renegotiations += 1
            self._codec_pending_reneg.discard(partition)
            self.negotiated_codec[partition] = v

    @staticmethod
    def _note_retry(verb: str, reason: str) -> None:
        # cold path only (a retry already costs a sleep)
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        fabric_metrics().client_retries_total.inc(verb, reason)

    # -- fleet trace propagation ---------------------------------------
    @staticmethod
    def _trace_ctx_for(uids) -> Optional[str]:
        """Outgoing ``X-Ktpu-Trace`` value for a request touching these
        trace-id candidates (pod uids where they exist, ns/name keys
        otherwise), or None when tracing is off / nothing is sampled.

        Bulk discipline: ONE context per object batch — the elected
        trace id is the first locally-sampled uid (deterministic crc32,
        so every client elects identically), carrying the EXPLICIT
        sampled bit; the full sampled-uid list rides as a span
        attribute on the innermost open span (or one ``client.batch``
        instant when none is open), never as N headers."""
        return elect_trace_context(uids)

    @staticmethod
    def _observe_delivery(kind: str, events: List[Event]) -> None:
        """Freshness SLI: commit → decode latency for a decoded watch
        batch. One ``observe_many`` per batch (one histogram lock
        round-trip, not one per event); stamp-less events (legacy
        peers, replay synthetics) are skipped."""
        try:
            from kubernetes_tpu.metrics.freshness_metrics import (
                freshness_metrics,
            )

            fm = freshness_metrics()
            if not fm.enabled:
                return
            now = time.time()
            lags = [max(0.0, now - e.ts) for e in events if e.ts]
            if lags:
                fm.watch_delivery_seconds.observe_many(lags, kind)
        except Exception:  # noqa: BLE001 — SLIs must never break watches
            pass

    @staticmethod
    def _trace_watch_delivery(events: List[Event]) -> None:
        """Stamp a ``watch.deliver`` span for each event carrying a
        SAMPLED commit-time origin context: commit → client decode, the
        cross-process hop of the pod's causal trace. The span's start
        back-dates by the freshness lag (client wall − commit wall —
        the processes share a host, so wall clock is the common
        reference); the explicit inbound bit overrides local crc32."""
        tracer = get_tracer()
        if not tracer.enabled:
            return
        now_m = now_w = None
        for e in events:
            origin = getattr(e, "origin", None)
            if not origin:
                continue
            ctx = parse_trace_header(origin)
            if ctx is None \
                    or not tracer.sampled(ctx.trace,
                                          inbound=ctx.sampled):
                continue
            if now_m is None:
                now_m, now_w = time.monotonic(), time.time()
            start = now_m - max(0.0, now_w - e.ts) if e.ts else now_m
            tracer.record("watch.deliver", start, now_m,
                          trace=ctx.trace, ctx_parent=ctx.parent,
                          kind=e.kind)

    def _request(self, method: str, path: str, payload: Any = None,
                 charge: float = 1.0, body_binary: Optional[bool] = None,
                 partition: int = 0,
                 route: Optional[Callable[[], int]] = None,
                 raise_on_stale: bool = False,
                 retries: Optional[int] = None,
                 trace_ctx: Optional[str] = None) -> Tuple[int, Any]:
        if self.limiter is not None:
            self.limiter.charge(charge)
        body_binary = self.binary if body_binary is None else body_binary
        data = None
        if payload is not None:
            data = codec.encode(payload) if body_binary \
                else json.dumps(payload).encode()
        if route is not None:
            partition = route()
        lane = "ro" if method in ("GET", "HEAD") else "rw"
        use_replica = self._replica_eligible(method, path)
        replica_idx: Optional[int] = None
        if use_replica:
            pool, replica_idx = self._read_pool(partition, lane)
        else:
            pool = self._pools[(partition, lane)]
        headers = self._headers(body_binary)
        if trace_ctx:
            # fleet tracing: propagated context (trace id + parent span
            # + the explicit sampling decision) — retries re-send the
            # SAME context, so a retried hop stays one trace
            headers[TRACE_HEADER] = trace_ctx
        if charge > 1:
            # declare the per-object count so the server's APF width
            # estimation charges proportional seats — the wire half of
            # "the token bucket charges per OBJECT": batching must not
            # launder concurrency server-side either
            headers["X-Kubernetes-Request-Items"] = str(int(charge))
        conn: Optional[http.client.HTTPConnection] = None
        # per-call retry ceiling: topology PROBES pass retries=0 — the
        # poller's endpoint round-robin is their retry policy, and a
        # probe stuck in backoff against a dead endpoint would hold the
        # whole client's routing hostage during exactly the failover
        # window it exists to detect
        max_r = self.max_retries if retries is None else int(retries)
        attempt = 0
        while True:
            try:
                if conn is None:
                    conn = pool.acquire()
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except (http.client.HTTPException, OSError):
                # dropped/reset keep-alive or truncated response (server
                # restart, idle timeout, injected wire fault): retry on
                # a FRESH pooled connection with jittered backoff —
                # requests here are idempotent or conflict-detected
                # server-side. Budget exhaustion surfaces the ORIGINAL
                # transport error. The pool pre-warms a replacement
                # under the backoff sleep so the retry never pays the
                # handshake inside its own window.
                _ConnPool.discard(conn)
                conn = None
                self.breaker.record_failure()
                if attempt >= max_r \
                        or not self._retry_budget.try_spend():
                    raise
                self._note_retry(method, "transport")
                time.sleep(self._backoff.delay(attempt))
                attempt += 1
                # re-resolve the route AND the pool before retrying: a
                # transport error during a restart seam means the
                # endpoint may be GONE — a replumb (topology push/poll)
                # lands mid-backoff and swaps self._pools to the
                # successor URL, and a retry pinned to the pre-seam
                # pool object would dial the dead port until the budget
                # ran out (a rolling upgrade turns that into a lost
                # write). Same rule for the read tier: a read that died
                # against a replica down-marks it FIRST, so this
                # re-resolve — and every sibling caller's — redirects
                # to a healthy replica or the owner instead of burning
                # the whole retry budget on a dead replica.
                if replica_idx is not None:
                    self._mark_replica_down(partition, replica_idx)
                if route is not None:
                    partition = route()
                if use_replica:
                    pool, replica_idx = self._read_pool(partition, lane)
                else:
                    pool = self._pools[(partition, lane)]
                pool.prewarm(1)
                continue
            if resp.status == 429 \
                    and resp.headers.get("X-Partition-Epoch"):
                # MOVED-slice pushback: the server no longer owns part
                # of this request's keyspace and named the live epoch.
                # (A FROZEN slice never carries the header — its cure
                # is the ordinary Retry-After wait below, since the
                # routing is already correct.) Refresh routing so the
                # retry (or the caller's re-split) lands on the owner.
                # Overload ≠ outage: breaker-healthy, like APF 429s.
                try:
                    new_epoch = int(
                        resp.headers.get("X-Partition-Epoch") or 0)
                except ValueError:
                    new_epoch = 0
                if resp.will_close:
                    _ConnPool.discard(conn)
                else:
                    pool.release(conn)
                conn = None
                self.breaker.record_success()
                if new_epoch > self.topology_epoch:
                    try:
                        # the rejecting server carries the newer doc
                        self.refresh_topology(partition=partition,
                                              replumb=False)
                    except Exception:  # noqa: BLE001 — retry below
                        pass
                if raise_on_stale and route is None:
                    # re-split against the (possibly already-) current
                    # topology: even an equal epoch re-groups the batch
                    # correctly when this split predated the flip
                    raise StaleRouteError(
                        f"topology epoch {new_epoch}: re-split needed")
                if attempt >= max_r \
                        or not self._retry_budget.try_spend():
                    ctype = resp.headers.get("Content-Type") or ""
                    if ctype.startswith(codec.BINARY_CONTENT_TYPE):
                        return resp.status, codec.decode(raw)
                    return resp.status, (json.loads(raw) if raw else {})
                try:
                    advertised = float(
                        resp.headers.get("Retry-After") or 0.0)
                except ValueError:
                    advertised = 0.0
                self._note_retry(method, "reshard")
                time.sleep(min(max(advertised,
                                   self._backoff.delay(attempt)),
                               self.retry_after_cap))
                attempt += 1
                if route is not None:
                    partition = route()
                if use_replica:
                    pool, replica_idx = self._read_pool(partition, lane)
                else:
                    pool = self._pools[(partition, lane)]
                continue
            if resp.status == 503 and replica_idx is not None \
                    and resp.headers.get("X-Replica-Fenced") \
                    and attempt < max_r \
                    and self._retry_budget.try_spend():
                # fenced replica: its OWN staleness verdict, not
                # overload — no Retry-After wait. Down-mark it and
                # re-route this very attempt to a sibling (or the
                # owner); the relist cost stays confined to clients
                # that were pinned to the fenced replica.
                if resp.will_close:
                    _ConnPool.discard(conn)
                else:
                    pool.release(conn)
                conn = None
                self.breaker.record_success()
                self._mark_replica_down(partition, replica_idx)
                self._note_retry(method, "replica_fenced")
                pool, replica_idx = self._read_pool(partition, lane)
                attempt += 1
                continue
            if resp.status in (429, 503) and attempt < max_r \
                    and self._retry_budget.try_spend():
                # overload pushback: honor Retry-After, CAPPED — a
                # misbehaving server advertising an hour must not stall
                # this client unboundedly. A 429 is the flow-control
                # layers (APF or the legacy lanes) talking: overload is
                # NOT outage, so tell the breaker the fabric is healthy
                # — a throttled tenant must never trip degraded mode off
                # the back of interleaved transport blips that pushback
                # would otherwise let accumulate to the threshold. A 503
                # is NOT that: nothing server-side emits it — it comes
                # from fault injection or a genuinely failing server —
                # so it stays breaker-neutral (retried, but never
                # laundered into health during a 503 storm).
                if resp.status == 429:
                    self.breaker.record_success()
                try:
                    advertised = float(
                        resp.headers.get("Retry-After") or 0.0)
                except ValueError:
                    advertised = 0.0
                # attribute the pushback to the rejecting priority
                # level (the server's X-Kubernetes-PF-* headers) so the
                # retry series separates "APF throttled me" from
                # generic 429/503 bursts
                pf_level = resp.headers.get(
                    "X-Kubernetes-PF-PriorityLevel") or ""
                self._note_retry(
                    method,
                    f"apf_{pf_level}" if pf_level
                    else f"http_{resp.status}")
                time.sleep(min(max(advertised,
                                   self._backoff.delay(attempt)),
                               self.retry_after_cap))
                attempt += 1
                continue
            # any HTTP response proves the transport — but a terminal
            # 503 is outage-shaped (fault injection or a genuinely
            # failing server; the flow-control layers only ever answer
            # 429), so it stays breaker-neutral here exactly as in the
            # retry branch above: a sustained 503 storm must still let
            # interleaved transport failures accumulate and open the
            # breaker instead of resetting the count on every response.
            if resp.status != 503:
                self.breaker.record_success()
            if resp.will_close:
                _ConnPool.discard(conn)
            else:
                pool.release(conn)
            if replica_idx is None:
                # replica echoes don't feed the per-partition codec pin
                # ledger — that contract is with the OWNER process, and
                # a same-version replica answering between two owner
                # echoes would read as a phantom re-negotiation
                self._record_negotiated(partition, resp)
            ctype = resp.headers.get("Content-Type") or ""
            if ctype.startswith(codec.BINARY_CONTENT_TYPE):
                return resp.status, codec.decode(raw)
            return resp.status, (json.loads(raw) if raw else {})

    @staticmethod
    def _raise_for(code: int, payload: Any) -> None:
        if code < 400:
            return
        msg = payload.get("message", "") if isinstance(payload, dict) \
            else str(payload)
        if code == 404:
            raise KeyError(msg)
        if code in (403, 422):
            raise PermissionError(msg)
        if code == 409:
            raise ValueError(msg)
        raise RuntimeError(f"HTTP {code}: {msg}")

    # -- paths ---------------------------------------------------------
    @staticmethod
    def _path(kind: str, namespace: Optional[str] = None,
              name: Optional[str] = None, sub: Optional[str] = None) -> str:
        plural = KIND_TO_PLURAL.get(kind, kind.lower() + "s")
        p = f"/api/v1/namespaces/{namespace}/{plural}" if namespace \
            else f"/api/v1/{plural}"
        if name:
            p += f"/{name}"
        if sub:
            p += f"/{sub}"
        return p

    def _items(self, payload: Any, kind: str) -> List[Any]:
        items = payload.get("items", [])
        if items and isinstance(items[0], dict):   # JSON wire
            items = [from_wire(i, kind) for i in items]
        return items

    # -- partition routing (apiserver/partition.py's crc32 function —
    # stores, servers and clients must all compute the same shard) ----
    def _pk(self, kind: str, namespace: Optional[str] = None,
            name: Optional[str] = None) -> int:
        topo = self._topology
        if topo is not None:
            return topo.partition_of(kind, namespace, name)
        if self.partitions == 1:
            return 0
        from kubernetes_tpu.apiserver.partition import partition_for

        return partition_for(kind, namespace, name, self.partitions)

    def _pset(self, kind: str,
              namespace: Optional[str] = None) -> List[int]:
        topo = self._topology
        if topo is not None:
            return topo.partitions_for(kind, namespace)
        if self.partitions == 1:
            return [0]
        from kubernetes_tpu.apiserver.partition import partitions_for

        return partitions_for(kind, self.partitions, namespace)

    # -- elastic topology (live resharding) ----------------------------
    def enable_topology(self, poll_interval: float = 0.5) -> bool:
        """Switch to epoch-aware elastic routing: fetch the live
        topology document and (with ``poll_interval`` > 0) start a
        poller that re-routes this client — including its watch
        streams — whenever ``/api/v1/partitiontopology`` changes epoch.
        Returns False when the servers predate live resharding (the
        client stays on static routing)."""
        got = self.refresh_topology()
        if got and poll_interval > 0 and self._topology_thread is None:
            self._topology_stop.clear()
            self._topology_thread = threading.Thread(
                target=self._topology_poll_loop, args=(poll_interval,),
                daemon=True, name="topology-poll")
            self._topology_thread.start()
        return got

    def stop_topology_watch(self) -> None:
        self._topology_stop.set()
        t, self._topology_thread = self._topology_thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _topology_poll_loop(self, interval: float) -> None:
        offset = 0
        while not self._topology_stop.wait(interval):
            # round-robin the endpoints: the canonical partition 0 may
            # be the one that just died (failover is exactly when the
            # topology changes)
            for i in range(len(self._endpoints)):
                p = (offset + i) % len(self._endpoints)
                try:
                    if self.refresh_topology(partition=p,
                                             replumb=True):
                        break
                    break   # reached a server; nothing newer
                except Exception:  # noqa: BLE001 — dead endpoint: next
                    continue
            offset += 1

    def refresh_topology(self, partition: int = 0,
                         replumb: bool = True) -> bool:
        """Fetch the topology document from one endpoint and apply it
        if its epoch is newer. ``replumb=False`` updates routing only
        (the 429-retry path runs on arbitrary threads — watch-stream
        surgery belongs to the poller)."""
        code, doc = self._request("GET", "/api/v1/partitiontopology",
                                  partition=partition, retries=0)
        if code != 200 or not isinstance(doc, dict) \
                or "owner" not in doc:
            return False
        from kubernetes_tpu.apiserver.partition import PartitionTopology

        topo = PartitionTopology.from_dict(doc)
        self._apply_topology(topo, replumb=replumb)
        return True

    def apply_topology(self, topo, replumb: bool = True) -> None:
        """Install a topology object directly (coordinators that just
        committed a migration hand it over instead of waiting a poll
        interval)."""
        self._apply_topology(topo, replumb=replumb)

    def _apply_topology(self, topo, replumb: bool) -> None:
        """Install routing for a newer epoch (any thread), and — for
        replumb-capable callers (the poller, a coordinator) — catch the
        stream layer up to whatever epoch routing has reached. Routing
        and stream surgery are tracked SEPARATELY (``_replumb_epoch``):
        the 429 fast path may apply an epoch routing-only on an
        arbitrary thread, and the owed re-plumb must not be lost to an
        equal-epoch early return."""
        do_streams = False
        changed: set = set()
        gained: set = set()
        with self._topology_lock:
            if self._topology is None or topo.epoch > self.topology_epoch:
                self._install_routing_locked(topo)
            if replumb and self._elastic_watching \
                    and self._replumb_epoch < self.topology_epoch:
                do_streams = True
                self._replumb_epoch = self.topology_epoch
                changed = set(self._pending_changed)
                self._pending_changed = set()
                gained = set(self._pending_gained)
                self._pending_gained = set()
                topo = self._topology
        if do_streams:
            self._replumb_streams(topo, changed, gained)

    def _install_routing_locked(self, topo) -> None:
        """Under _topology_lock: routing tables, pools, and the RV
        watchdog reset for a NEWER epoch."""
        old_urls = list(self.partition_urls)
        old_topo = self._topology
        new_urls = [u.rstrip("/") for u in topo.urls] \
            if topo.urls else old_urls
        # which partitions GAINED keyspace under this epoch: a changed
        # spread set can land a namespace's keys anywhere (per-name
        # slots), so every partition gains; an owner-vector change
        # gains exactly the slots' new owners
        if old_topo is not None:
            if topo.spread != old_topo.spread:
                self._pending_gained |= set(range(len(new_urls)))
            else:
                for s, o in enumerate(topo.owner):
                    if s >= len(old_topo.owner) \
                            or old_topo.owner[s] != o:
                        self._pending_gained.add(o)
        changed = {p for p in range(len(new_urls))
                   if p >= len(old_urls)
                   or new_urls[p] != old_urls[p]}
        self.partition_urls = new_urls
        self.partitions = len(new_urls)
        endpoints = []
        for u in new_urls:
            rest = u.split("://", 1)[1]
            host, _, port = rest.partition(":")
            endpoints.append((host, int(port or 80)))
        self._endpoints = endpoints
        for p in changed:
            host, port = endpoints[p]
            for lane in ("ro", "rw"):
                old_pool = self._pools.get((p, lane))
                if old_pool is not None:
                    old_pool.close_all()
                self._pools[(p, lane)] = _ConnPool(host, port)
        self._topology = topo
        self.topology_epoch = topo.epoch
        self._pending_changed |= changed
        # the RV watchdog and reflector resume state are keyed by
        # (kind, partition INDEX) — after an epoch change an index can
        # denote a different server (a split's new process, a failover
        # restart with a rebuilt store). Carrying the old high-water
        # mark across that boundary would flag a FALSE RV regression on
        # the first list; reset exactly the changed indices (unchanged
        # partitions keep their real monotonicity promise).
        with self._rv_lock:
            for key in [k for k in self._last_rv if k[1] in changed]:
                del self._last_rv[key]
        # a changed index is a restart seam: the recorded wire-version
        # pin belonged to the OLD server behind this URL. Drop it so
        # the first echo from the new server re-registers — and counts
        # as a re-negotiation (the client re-pinned across the seam).
        with self._codec_lock:
            for p in changed:
                if self.negotiated_codec.pop(p, None) is not None:
                    self._codec_pending_reneg.add(p)
        # read-tier advertisement: (re)build replica routing from the
        # doc — an epoch that adds/removes replicas reaches every
        # client through the same poll/429 channels as ownership moves
        self._set_read_replicas(getattr(topo, "replicas", None) or {})

    def _list(self, kind: str, namespace: Optional[str] = None) -> List[Any]:
        parts = self._pset(kind, namespace)

        def one(p: int) -> List[Any]:
            code, payload = self._request(
                "GET", self._path(kind, namespace), partition=p)
            self._raise_for(code, payload)
            return self._items(payload, kind)

        if len(parts) == 1:
            return one(parts[0])
        # the biggest lists in the system (a replica's start() replay
        # of 500k pods) fan in CONCURRENTLY — wall time is the slowest
        # partition, not the sum
        pool = self._fan_out()
        out: List[Any] = []
        for got in pool.map(one, parts):
            out.extend(got)
        return out

    def _list_with_rv(self, kind: str, namespace: Optional[str] = None,
                      partition: Optional[int] = None
                      ) -> Tuple[List[Any], int]:
        """List + consistency RV. With an explicit ``partition`` (the
        per-partition watch loops), exactly that shard is listed and
        the RV is that partition's — the composite-cursor component the
        stream resumes from. Fan-in calls return the max component.
        The RV-monotonicity watchdog is keyed per (kind, partition):
        partitions advance independently, and only the per-partition
        sequence is promised monotonic."""
        out: List[Any] = []
        max_rv = 0
        parts = [partition] if partition is not None \
            else self._pset(kind, namespace)
        for p in parts:
            code, payload = self._request(
                "GET", self._path(kind, namespace), partition=p)
            self._raise_for(code, payload)
            rv = payload.get("resourceVersion")
            if rv is None:
                rv = (payload.get("metadata") or {}).get(
                    "resourceVersion", 0)
            rv = int(rv)
            with self._rv_lock:
                last = self._last_rv.get((kind, p), 0)
                if rv < last:
                    self.rv_regressions.append((kind, last, rv))
                else:
                    self._last_rv[(kind, p)] = rv
            out.extend(self._items(payload, kind))
            max_rv = max(max_rv, rv)
        return out, max_rv

    def _get(self, kind: str, namespace: Optional[str],
             name: str) -> Optional[Any]:
        code, payload = self._request(
            "GET", self._path(kind, namespace, name),
            route=lambda: self._pk(kind, namespace, name))
        if code == 404:
            return None
        self._raise_for(code, payload)
        if isinstance(payload, dict):   # JSON wire
            return from_wire(payload, kind)
        return payload

    def _cached(self, key: str, fetch: Callable[[], Any]) -> Any:
        hit = self._ttl_cache.get(key)
        now = time.monotonic()
        if hit is not None and now - hit[0] < self.cache_ttl:
            return hit[1]
        value = fetch()
        self._ttl_cache[key] = (now, value)
        return value

    # -- hot reads (no cache: the scheduler replays them into its own
    # cache/queue at start, and consults get_pod only on conflicts) ----
    def list_pods(self, namespace: Optional[str] = None) -> List[Any]:
        return self._list("Pod", namespace)

    def list_nodes(self) -> List[Any]:
        return self._list("Node")

    def get_pod(self, namespace: str, name: str) -> Optional[Any]:
        return self._get("Pod", namespace, name)

    # -- kubelet surface (kubemark hollow nodes over the REST fabric:
    # node registration, heartbeat leases, pod lifecycle writes) -------
    def get_node(self, name: str) -> Optional[Any]:
        return self._get("Node", None, name)

    def add_node(self, node) -> None:
        """Upsert like ``store.add_node`` (kubelet registration is an
        upsert: re-registration after a restart must not 409)."""
        try:
            self.create_object("Node", node)
        except ValueError:
            self.update_object("Node", node)

    def update_node(self, node) -> None:
        self.update_object("Node", node)

    def delete_node(self, name: str) -> None:
        code, payload = self._request(
            "DELETE", self._path("Node", None, name),
            route=lambda: self._pk("Node", None, name))
        if code >= 400 and code != 404:
            self._raise_for(code, payload)

    def create_pod(self, pod) -> Any:
        """Single-pod create (the kubelet's mirror-pod path)."""
        return self.create_object("Pod", pod)

    def set_pod_phase(self, namespace: str, name: str, phase: str,
                      pod_ip: str = "", host_ip: str = "") -> bool:
        status: Dict[str, Any] = {}
        if phase:
            status["phase"] = phase
        if pod_ip:
            status["podIP"] = pod_ip
        if host_ip:
            status["hostIP"] = host_ip
        code, payload = self._request(
            "PUT", self._path("Pod", namespace, name, "status"),
            {"status": status}, body_binary=False,
            route=lambda: self._pk("Pod", namespace, name),
            trace_ctx=self._trace_ctx_for([f"{namespace}/{name}"]))
        if code == 404:
            return False
        self._raise_for(code, payload)
        return True

    def try_acquire_or_renew(self, name: str, holder: str, now: float,
                             duration: float) -> bool:
        """Heartbeat/leader lease over REST (POST
        .../leases/{name}/acquire — rest.py's lease verb; the
        in-process ``_Lease`` CAS, made remote). ``now`` is evaluated
        server-side (one clock must arbitrate)."""
        code, payload = self._request(
            "POST", f"/api/v1/leases/{name}/acquire",
            {"holder": holder, "duration": duration},
            body_binary=False)
        self._raise_for(code, payload)
        return bool(payload.get("acquired"))

    def lease_holder(self, name: str) -> Optional[str]:
        obj = self._get("Lease", "kube-system", name)
        return getattr(obj, "holder_identity", None) if obj is not None \
            else None

    # -- cycle reads (TTL-cached: informer-cache consistency) ----------
    def list_services(self, namespace: str) -> List[Any]:
        return self._cached(f"svc/{namespace}",
                            lambda: self._list("Service", namespace))

    def list_replication_controllers(self, namespace: str) -> List[Any]:
        return self._cached(
            f"rc/{namespace}",
            lambda: self._list("ReplicationController", namespace))

    def list_replica_sets(self, namespace: str) -> List[Any]:
        return self._cached(f"rs/{namespace}",
                            lambda: self._list("ReplicaSet", namespace))

    def list_stateful_sets(self, namespace: str) -> List[Any]:
        return self._cached(f"sts/{namespace}",
                            lambda: self._list("StatefulSet", namespace))

    def list_pdbs(self) -> List[Any]:
        return self._cached("pdbs",
                            lambda: self._list("PodDisruptionBudget"))

    def list_pvs(self) -> List[Any]:
        return self._cached("pvs", lambda: self._list("PersistentVolume"))

    def list_csi_nodes(self) -> List[Any]:
        return self._cached("csinodes", lambda: self._list("CSINode"))

    def get_pvc(self, namespace: str, name: str) -> Optional[Any]:
        return self._get("PersistentVolumeClaim", namespace, name)

    def get_pv(self, name: str) -> Optional[Any]:
        return self._get("PersistentVolume", None, name)

    def get_storage_class(self, name: str) -> Optional[Any]:
        return self._cached(f"sc/{name}",
                            lambda: self._get("StorageClass", None, name))

    def get_csi_node(self, name: str) -> Optional[Any]:
        return self._get("CSINode", None, name)

    # -- binds ---------------------------------------------------------
    def bind(self, namespace: str, name: str, uid: str,
             node_name: str) -> None:
        code, payload = self._request(
            "POST", self._path("Pod", namespace, name, "binding"),
            {"kind": "Binding", "uid": uid, "target": {"name": node_name}},
            body_binary=False,
            route=lambda: self._pk("Pod", namespace, name),
        )
        self._raise_for(code, payload)

    # past this size, a bulk bind splits across two pipelined requests:
    # the client pickles chunk k+1 while the server applies chunk k —
    # overlap a single blocking round trip cannot have
    _BIND_SPLIT = 1024

    def _fan_out(self):
        """Shared executor for per-partition bulk-verb fan-out (bulk
        verbs split by partition and ship concurrently — each
        partition's server applies its slice under its own lock/GIL).
        Creation is serialized: fan-out workers themselves reach the
        split-bind pool, and a check-then-create race would leak a
        live executor."""
        from concurrent.futures import ThreadPoolExecutor

        with self._pool_init_lock:
            pool = getattr(self, "_fan_pool", None)
            if pool is None:
                pool = self._fan_pool = ThreadPoolExecutor(
                    max_workers=max(2, min(self.partitions, 8)),
                    thread_name_prefix="partition-fan")
        return pool

    def check_partition_topology(self) -> None:
        """Validate that every configured endpoint serves the partition
        index this client will route to it (GET
        /api/v1/partitiontopology) — a client built with shuffled or
        wrong-count URLs must fail HERE, loudly, not silently read
        half-empty shards. Servers predating the endpoint (404) are
        skipped best-effort."""
        for i in range(self.partitions):
            code, topo = self._request(
                "GET", "/api/v1/partitiontopology", partition=i)
            if code == 404:
                continue
            if code != 200 or not isinstance(topo, dict):
                raise RuntimeError(
                    f"partition {i} topology probe failed: HTTP {code}")
            if topo.get("partition") != i \
                    or topo.get("partitions") != self.partitions:
                raise RuntimeError(
                    f"partition_urls[{i}] ({self.partition_urls[i]}) "
                    f"serves partition {topo.get('partition')} of "
                    f"{topo.get('partitions')}, not {i} of "
                    f"{self.partitions} — misconfigured routing")

    def _group_by_partition(self, items, key_fn):
        """[(partition, [(orig_index, item), ...]), ...] preserving
        per-partition order."""
        groups: Dict[int, list] = {}
        for i, item in enumerate(items):
            groups.setdefault(key_fn(item), []).append((i, item))
        return sorted(groups.items())

    def _fan_by_partition(self, items, key_fn, call_fn, _depth: int = 0):
        """The bulk-verb fan-out scaffold, once: split positional
        ``items`` by partition, run ``call_fn(partition, slice)`` per
        group (concurrently when several partitions are involved), and
        merge each slice's positional results back into item order.

        A group that hits a mid-migration stale route re-splits ALONE
        against the refreshed topology — groups that already committed
        keep their results (a wholesale retry would re-send them, read
        the resulting 409s as failures, and under-count the batch)."""
        results: List[Any] = [None] * len(items)
        groups = self._group_by_partition(items, key_fn)
        retry: List[Tuple[int, Any]] = []
        outs = []
        if len(groups) == 1:
            p, entries = groups[0]
            try:
                outs.append(
                    (entries, call_fn(p, [it for _, it in entries])))
            except StaleRouteError:
                if _depth >= 3:
                    raise
                retry.extend(entries)
        else:
            pool = self._fan_out()
            futures = [
                (entries, pool.submit(call_fn, p,
                                      [it for _, it in entries]))
                for p, entries in groups
            ]
            for entries, fut in futures:
                try:
                    outs.append((entries, fut.result()))
                except StaleRouteError:
                    if _depth >= 3:
                        raise
                    retry.extend(entries)
        for entries, got in outs:
            for (i, _item), r in zip(entries, got):
                results[i] = r
        if retry:
            time.sleep(0.05)
            sub = self._fan_by_partition(
                [it for _, it in retry], key_fn, call_fn,
                _depth=_depth + 1)
            for (i, _item), r in zip(retry, sub):
                results[i] = r
        return results

    def _with_resplit(self, fn):
        """Run a bulk fan-out, re-splitting against the refreshed
        topology when a server answers a stale-epoch 429 mid-migration
        (``StaleRouteError``). Bounded: a torn topology that never
        converges surfaces the error instead of spinning."""
        for _ in range(4):
            try:
                return fn()
            except StaleRouteError:
                time.sleep(0.05)
                continue
        return fn()

    def bind_many(
        self, bindings: List[Tuple[str, str, str, str]]
    ) -> List[Optional[Exception]]:
        """Bulk POST ../bindings; per-item failures come back
        positionally — the exact contract of store.bind_many. With a
        partitioned fabric the batch splits by the pod's partition and
        the slices fan out concurrently."""
        if not bindings:
            return []

        def run():
            if self.partitions == 1:
                return self._bind_partition(0, bindings)
            return self._fan_by_partition(
                bindings, lambda b: self._pk("Pod", b[0], b[1]),
                self._bind_partition)

        return self._with_resplit(run)

    def _bind_partition(
        self, partition: int, bindings: List[Tuple[str, str, str, str]]
    ) -> List[Optional[Exception]]:
        if len(bindings) > self._BIND_SPLIT:
            from concurrent.futures import ThreadPoolExecutor

            with self._pool_init_lock:
                pool = getattr(self, "_bind_pool", None)
                if pool is None:
                    pool = self._bind_pool = ThreadPoolExecutor(
                        max_workers=2, thread_name_prefix="bind-many")
            mid = len(bindings) // 2
            left = pool.submit(self._bind_chunk, bindings[:mid],
                               partition)
            right = self._bind_chunk(bindings[mid:], partition)
            return left.result() + right
        return self._bind_chunk(bindings, partition)

    def _bind_chunk(
        self, bindings: List[Tuple[str, str, str, str]],
        partition: int = 0,
    ) -> List[Optional[Exception]]:
        if self.binary:
            payload: Any = {"kind": "BindingList",
                            "items": [tuple(b) for b in bindings]}
        else:
            payload = {"kind": "BindingList", "items": [
                {"namespace": ns, "name": n, "uid": u,
                 "target": {"name": node}}
                for ns, n, u, node in bindings
            ]}
        code, resp = self._request("POST", "/api/v1/bindings", payload,
                                   charge=len(bindings),
                                   partition=partition,
                                   raise_on_stale=self._topology
                                   is not None,
                                   trace_ctx=self._trace_ctx_for(
                                       [b[2] for b in bindings]))
        if code >= 400:
            err = RuntimeError(
                resp.get("message", f"HTTP {code}")
                if isinstance(resp, dict) else f"HTTP {code}")
            return [err] * len(bindings)
        errors: List[Optional[Exception]] = [None] * len(bindings)
        for f in resp.get("failures", ()):
            exc = KeyError(f["message"]) if f.get("code") == 404 \
                else ValueError(f["message"])
            errors[f["index"]] = exc
        return errors

    # -- pod status / lifecycle writes ---------------------------------
    def _put_status(self, namespace: str, name: str, status: dict) -> None:
        buf = getattr(self._status_buffers, "buf", None)
        if buf is not None:
            # inside a batched_status_writes scope: coalesce — the
            # items apply in order at scope exit as ONE bulk request
            buf.append({"namespace": namespace, "name": name,
                        "status": status})
            return
        code, payload = self._request(
            "PUT", self._path("Pod", namespace, name, "status"),
            {"status": status}, body_binary=False,
            route=lambda: self._pk("Pod", namespace, name),
            trace_ctx=self._trace_ctx_for([f"{namespace}/{name}"]))
        if code == 404:
            return   # pod deleted under us: store semantics are no-op
        self._raise_for(code, payload)

    def write_pod_statuses(self, updates: List[dict]
                           ) -> List[Optional[Exception]]:
        """Bulk POST /api/v1/statuses (PodStatusList): N status writes,
        one round trip per PARTITION (the batch splits by the pod's
        partition and fans out), positional failures. Each item is
        ``{"namespace", "name", "status": {...}}`` with the exact
        per-item semantics of PUT pods/{name}/status; the token bucket
        charges per ITEM, so bulk status writes stay rate-equivalent to
        N singles. 404s are None (pod deleted under us), matching
        ``_put_status``."""
        if not updates:
            return []

        def run():
            if self.partitions == 1:
                return self._statuses_partition(0, list(updates))
            return self._fan_by_partition(
                updates,
                lambda u: self._pk("Pod", u.get("namespace"),
                                   u.get("name")),
                self._statuses_partition)

        return self._with_resplit(run)

    def _statuses_partition(self, partition: int, updates: List[dict]
                            ) -> List[Optional[Exception]]:
        code, resp = self._request(
            "POST", "/api/v1/statuses",
            {"kind": "PodStatusList", "items": updates},
            charge=len(updates), body_binary=False, partition=partition,
            raise_on_stale=self._topology is not None,
            # status items carry no uid: ns/name keys are the trace-id
            # candidates (deterministic crc32 either way)
            trace_ctx=self._trace_ctx_for(
                [f"{u.get('namespace')}/{u.get('name')}"
                 for u in updates]))
        if code >= 400:
            err = RuntimeError(
                resp.get("message", f"HTTP {code}")
                if isinstance(resp, dict) else f"HTTP {code}")
            return [err] * len(updates)
        errors: List[Optional[Exception]] = [None] * len(updates)
        for f in resp.get("failures", ()):
            if f.get("code") == 404:
                continue   # pod deleted under us: single-PUT no-op
            errors[f["index"]] = PermissionError(f["message"]) \
                if f.get("code") in (403, 422) \
                else RuntimeError(f["message"])
        return errors

    def batched_status_writes(self):
        """Scope that coalesces this THREAD's pod-status writes
        (conditions, nominatedNodeName, phase) into one bulk
        ``/statuses`` request flushed at exit — the mass-decline path
        writes thousands of PodScheduled=False conditions per batch,
        and per-object round trips there serialize the whole commit
        loop. Writes become visible at scope exit; the callers that use
        this are already best-effort about status visibility."""
        import contextlib

        @contextlib.contextmanager
        def scope():
            if getattr(self._status_buffers, "buf", None) is not None:
                # nested scope: the outer one owns the flush
                yield
                return
            buf: List[dict] = []
            self._status_buffers.buf = buf
            try:
                yield
            finally:
                self._status_buffers.buf = None
                if buf:
                    try:
                        self.write_pod_statuses(buf)
                    except Exception:  # noqa: BLE001 — best-effort,
                        # like the per-object writes it replaces
                        pass

        return scope()

    def patch_pod_condition(self, namespace: str, name: str,
                            condition) -> None:
        self._put_status(namespace, name, {"conditions": [{
            "type": condition.type, "status": condition.status,
            "reason": condition.reason, "message": condition.message,
        }]})

    def set_nominated_node_name(self, namespace: str, name: str,
                                node: str) -> None:
        self._put_status(namespace, name, {"nominatedNodeName": node})

    def clear_nominated_node_name(self, namespace: str, name: str) -> None:
        self._put_status(namespace, name, {"nominatedNodeName": ""})

    def delete_pod(self, namespace: str, name: str) -> None:
        code, payload = self._request(
            "DELETE", self._path("Pod", namespace, name),
            route=lambda: self._pk("Pod", namespace, name),
            trace_ctx=self._trace_ctx_for([f"{namespace}/{name}"]))
        if code >= 400 and code != 404:
            self._raise_for(code, payload)

    def delete_pods(self, keys: List[Tuple[str, str]]) -> None:
        for namespace, name in keys:
            self.delete_pod(namespace, name)

    # -- PV binding (volume-binding plugin / commit binder) ------------
    # Scheduler-side assume/revert are CLIENT-LOCAL bookkeeping in the
    # reference (the volume binder's AssumeCache); over REST they have
    # no server half, and the commit-time bind goes through object
    # updates. The REST bench families exercise bound-claim and WFC
    # flows through these four.
    def assume_pv_bound(self, pv_name: str, pvc_key: str) -> None:
        raise NotImplementedError(
            "assume_pv_bound is store-local; run PV-assume workloads "
            "against the in-process store or extend the REST surface")

    def revert_assumed_pv(self, pv_name: str) -> None:
        raise NotImplementedError("see assume_pv_bound")

    def bind_pv(self, pv_name: str, pvc_namespace: str,
                pvc_name: str) -> bool:
        raise NotImplementedError("see assume_pv_bound")

    def unbind_pv(self, pv_name: str, pvc_namespace: str,
                  pvc_name: str) -> None:
        raise NotImplementedError("see assume_pv_bound")

    # -- generic objects (event recorder, extenders) -------------------
    def create_object(self, kind: str, obj) -> Any:
        ns = getattr(obj.metadata, "namespace", None)
        code, payload = self._request(
            "POST", self._path(kind, ns),
            obj if self.binary else to_wire(obj),
            route=lambda: self._pk(kind, ns, obj.metadata.name),
            trace_ctx=self._trace_ctx_for(
                [getattr(obj.metadata, "uid", "")
                 or f"{ns}/{obj.metadata.name}"]))
        self._raise_for(code, payload)
        return obj

    def create_objects_bulk(self, kind: str, objs: List[Any]) -> int:
        if not objs:
            return 0

        def run():
            if self.partitions == 1:
                return self._create_bulk_partition(0, kind, objs)

            # ride the shared scaffold by spreading each slice's
            # created COUNT over per-item 0/1 flags (only the sum is
            # contractual)
            def create_slice(p: int, group: List[Any]) -> List[int]:
                created = self._create_bulk_partition(p, kind, group)
                return [1] * created + [0] * (len(group) - created)

            flags = self._fan_by_partition(
                objs,
                lambda o: self._pk(
                    kind, getattr(o.metadata, "namespace", None),
                    o.metadata.name),
                create_slice)
            return sum(flags)

        return self._with_resplit(run)

    def _create_bulk_partition(self, partition: int, kind: str,
                               objs: List[Any]) -> int:
        # a batch spanning namespaces must POST the cluster-scoped
        # collection (the path namespace overrides per-item namespaces
        # server-side)
        ns = getattr(objs[0].metadata, "namespace", None)
        if ns is not None and any(
                getattr(o.metadata, "namespace", None) != ns
                for o in objs):
            ns = None
        payload = {"kind": f"{kind}List",
                   "items": objs if self.binary
                   else [to_wire(o) for o in objs]}
        code, resp = self._request("POST", self._path(kind, ns), payload,
                                   charge=len(objs), partition=partition,
                                   raise_on_stale=self._topology
                                   is not None,
                                   trace_ctx=self._trace_ctx_for(
                                       [getattr(o.metadata, "uid", "")
                                        for o in objs]))
        self._raise_for(code, resp)
        return resp.get("created", 0)

    def update_object(self, kind: str, obj,
                      expect_rv: Optional[str] = None) -> Any:
        ns = getattr(obj.metadata, "namespace", None)
        code, payload = self._request(
            "PUT", self._path(kind, ns, obj.metadata.name),
            obj if self.binary else to_wire(obj),
            route=lambda: self._pk(kind, ns, obj.metadata.name))
        self._raise_for(code, payload)
        return obj

    def get_object(self, kind: str, namespace: str, name: str):
        return self._get(
            kind, namespace if namespace else None, name)

    def list_objects(self, kind: str,
                     namespace: Optional[str] = None) -> List[Any]:
        """Generic list (the informer factory's fallback surface):
        fans in across the partitions the kind can live in."""
        return self._list(kind, namespace)

    def prune_expired_events(self, now: Optional[float] = None) -> int:
        return 0   # server-side Events TTL owns expiry over REST

    # -- watch ---------------------------------------------------------
    def watch(self, fn: Callable[[Event], None],
              batch_fn: Optional[Callable[[List[Event]], None]] = None
              ) -> _WatchHandle:
        """List+Watch every scheduler kind over chunked HTTP, delivering
        through the same (fn, batch_fn) contract as store.watch. Binary
        streams arrive as server-batched frames — one frame, one
        batch_fn call (the store's own batched dispatch, preserved over
        the wire). Against a partitioned fabric this opens ONE stream
        per (kind, partition) and merges: each stream is its own
        reflector with its own resume cursor component and relist
        scope, so a torn/stalled stream on one partition never delays
        (or forces a relist of) another."""
        self._stopping.clear()
        if self._topology is not None:
            # elastic mode: restartable per-(kind, partition) streams
            # with CLIENT-HELD reflector state, so a topology-epoch
            # change can hand a moved slice to its new partition's
            # stream without relisting anything that didn't move
            self._elastic_watching = True
            self._watch_fn, self._watch_batch_fn = fn, batch_fn
            with self._handoff_lock:
                for kind in self.watch_kinds:
                    for p in self._pset(kind):
                        self._start_stream(kind, p, handoff=False)
            return _WatchHandle(self)
        for kind in self.watch_kinds:
            for p in self._pset(kind):
                t = threading.Thread(
                    target=self._watch_loop, args=(kind, p, fn, batch_fn),
                    daemon=True, name=f"watch-{kind}-p{p}")
                t.start()
                self._watch_threads.append(t)
        return _WatchHandle(self)

    def _stop_watches(self) -> None:
        self._stopping.set()
        self._elastic_watching = False
        for ev in list(self._stream_stops.values()):
            ev.set()
        for conn in list(self._stream_conns.values()):
            _sever(conn)
        self._stream_conns.clear()
        self.stop_topology_watch()

    # -- elastic watch streams (cursor-preserving handoff) -------------
    def _start_stream(self, kind: str, p: int, handoff: bool) -> None:
        """Start (or replace) the stream for one (kind, partition).
        ``handoff=True`` = mid-run start after a topology change: the
        first list DELIVERS the diff against the (transferred) known
        map — exactly the window the consumer missed — instead of the
        silent seeding a boot-time stream does."""
        old_stop = self._stream_stops.get((kind, p))
        if old_stop is not None:
            old_stop.set()
        _sever(self._stream_conns.pop((kind, p), None))
        stop = threading.Event()
        self._stream_stops[(kind, p)] = stop
        t = threading.Thread(
            target=self._watch_elastic_loop,
            args=(kind, p, stop, handoff),
            daemon=True, name=f"watch-{kind}-p{p}")
        t.start()
        self._watch_threads.append(t)

    def _deliver(self, kind: str, p: int, events: List[Event]) -> bool:
        """Forward events to the consumer through the stream's known
        map with an RV-MONOTONIC filter per object: a replayed event
        (watch-cache resume past the handoff seam) or a late pre-freeze
        delivery that a reconcile fetch already superseded is dropped —
        the 'zero duplicated, never backwards' half of the handoff
        contract. Returns False when the consumer is gone."""
        topo = self._topology
        out: List[Event] = []
        with self._known_lock:
            known = self._stream_known.setdefault((kind, p), {})
            for e in events:
                key = _key_of(e.obj)
                rv = _rv_of(e.obj)
                prev = known.get(key)
                prev_rv = _rv_of(prev) if prev is not None else -1
                if prev is None and topo is not None \
                        and topo.partition_of(kind, key[0],
                                              key[1]) != p:
                    # a key this stream does not own and has no state
                    # for: either a late pre-transfer delivery (its
                    # entry moved to the new owner, whose reconcile
                    # fetch covers the window) or an early post-flip
                    # one (the owner's stream delivers it). Forwarding
                    # it here would double-deliver — and re-polluting
                    # this stream's known map would turn a future
                    # relist into a synthetic DELETE of a live object.
                    continue
                if e.type == DELETED:
                    if prev is None or (rv and prev_rv > rv):
                        continue
                    known.pop(key, None)
                else:
                    if prev is not None and rv and prev_rv >= rv:
                        continue
                    known[key] = e.obj
                out.append(e)
        if not out:
            return True
        fn, batch_fn = self._watch_fn, self._watch_batch_fn
        if fn is None and batch_fn is None:
            return False
        if batch_fn is not None:
            batch_fn(out)
        else:
            for e in out:
                fn(e)
        return True

    def _watch_elastic_loop(self, kind: str, p: int,
                            stop: threading.Event,
                            handoff: bool) -> None:
        from kubernetes_tpu.client.informers import replace_diff

        first = True
        # the endpoint this stream was plumbed against: if it changes,
        # the partition's process was replaced (rolling restart /
        # failover) and the seam belongs to _replumb_streams — it stops
        # this loop and opens a fresh HANDOFF stream. Without this
        # guard the loop's own reconnect can race the replumb onto the
        # successor's pool and mislabel the restart as a relist of an
        # unmoved slice (and briefly double-stream the partition).
        ep0 = self._endpoints[p] if p < len(self._endpoints) else None
        while not self._stopping.is_set() and not stop.is_set():
            if (self._endpoints[p]
                    if p < len(self._endpoints) else None) != ep0:
                return
            try:
                objs, rv = self._list_with_rv(kind, partition=p)
                if stop.is_set() or self._stopping.is_set():
                    return
                if (self._endpoints[p]
                        if p < len(self._endpoints) else None) != ep0:
                    return
                live = {_key_of(o): o for o in objs}
                if first and not handoff:
                    # boot-time stream: Scheduler.start() replays the
                    # first list itself; just remember what exists
                    with self._known_lock:
                        self._stream_known.setdefault(
                            (kind, p), {}).update(live)
                    events = []
                else:
                    with self._known_lock:
                        snapshot = dict(self._stream_known.setdefault(
                            (kind, p), {}))
                    events = replace_diff(kind, snapshot, live)
                    if first:
                        self.handoff_fetches += 1
                    else:
                        # a torn stream relists ITS slice only; the
                        # mini-cell asserts unmoved slices never land
                        # here during a migration
                        from kubernetes_tpu.metrics.fabric_metrics \
                            import fabric_metrics

                        fabric_metrics().client_relists_total.inc(kind)
                        self.stream_relists[(kind, p)] = \
                            self.stream_relists.get((kind, p), 0) + 1
                first = False
                if events:
                    self._deliver(kind, p, events)
                self._stream_watch(kind, rv,
                                   lambda evs: self._deliver(kind, p,
                                                             evs),
                                   partition=p, stream_key=(kind, p),
                                   stop=stop)
            except (http.client.HTTPException, OSError, RuntimeError):
                pass
            if self._stopping.is_set() or stop.is_set():
                return
            time.sleep(0.2)   # relist-and-rewatch (reflector restart)

    def _reconcile_stream(self, kind: str, p: int,
                          keys: List[tuple]) -> None:
        """One-shot catch-up for keys just transferred INTO partition
        p's live stream (a move to an existing partition, a retire
        draining into survivors): list p once and deliver the diff for
        exactly those keys. The live stream was attached throughout, so
        everything committed on p after the flip arrives through it;
        this covers the pre-flip window the SOURCE stream may not have
        delivered before the transfer.

        The diff is FULL-LIST on the add/update side: a write committed
        inside the freeze window whose event never left the source
        stream is in NO known map, so only the live list can surface it
        (the RV-monotonic filter in ``_deliver`` collapses the overlap
        with the live stream's own delivery). DELETE detection stays
        restricted to the transferred ``keys``: inferring deletes from
        a full diff would race the live stream (a create delivered
        between this snapshot and list would read as a false DELETED).
        The known snapshot is taken BEFORE the list for the same
        reason, in the safe direction: anything that lands in between
        shows up as a duplicate the RV filter drops, never as a
        fabricated event."""
        self.handoff_fetches += 1
        with self._known_lock:
            snapshot = dict(self._stream_known.setdefault((kind, p), {}))
        try:
            objs, _rv = self._list_with_rv(kind, partition=p)
        except (http.client.HTTPException, OSError, RuntimeError):
            return
        live = {_key_of(o): o for o in objs}
        events: List[Event] = []
        for key, cur in live.items():
            old = snapshot.get(key)
            if old is None:
                events.append(Event(ADDED, kind, cur))
            elif _rv_of(old) != _rv_of(cur):
                events.append(Event(MODIFIED, kind, cur, old))
        for key in keys:
            if key not in live and key in snapshot:
                events.append(Event(DELETED, kind, snapshot[key]))
        if events:
            self._deliver(kind, p, events)

    def _replumb_streams(self, topo, changed_urls,
                         gained: Optional[set] = None) -> None:
        """Re-route the watch layer after a topology-epoch change:

        1. stop streams whose partition left the fan set (retired) or
           whose endpoint changed (failover restart) — and JOIN their
           delivery so no late event races the transfer;
        2. redistribute each stopped/moved key's reflector entry to its
           new owner's known map (the client-side cursor transfer);
        3. start handoff streams for partitions that lack one (a
           split's new partition, a restarted endpoint) — their first
           list delivers the missed window as a diff;
        4. reconcile-fetch existing live streams that RECEIVED
           keyspace — whether or not any KNOWN key moved with it: a
           freeze-window write the source stream never delivered is in
           no known map, and only the gaining partition's list shows it.

        Unmoved slices: their streams are never touched — no relist."""
        gained = gained or set()
        with self._handoff_lock:
            fan: Dict[str, set] = {
                kind: set(topo.partitions_for(kind))
                for kind in self.watch_kinds}
            # 1. stop departing/re-pointed streams
            stopped: List[Tuple[str, int]] = []
            for (kind, p) in list(self._stream_stops):
                if kind not in fan:
                    continue
                if p not in fan[kind] or p in changed_urls:
                    ev = self._stream_stops.get((kind, p))
                    if ev is not None:
                        ev.set()
                    _sever(self._stream_conns.pop((kind, p), None))
                    stopped.append((kind, p))
            if stopped:
                time.sleep(0.05)   # let their delivery drain
            # 2. redistribute known entries to new owners
            to_reconcile: Dict[Tuple[str, int], List[tuple]] = {}
            with self._known_lock:
                for kind in self.watch_kinds:
                    for (k, p), known in list(self._stream_known.items()):
                        if k != kind:
                            continue
                        for key in list(known):
                            ns, name = key
                            # partition_of keys Pods by namespace (and
                            # name once spread) and Nodes by name —
                            # stray namespace metadata on cluster-
                            # scoped kinds is ignored by the slot fn
                            q = topo.partition_of(kind, ns, name)
                            if q == p and (kind, p) not in stopped:
                                continue
                            obj = known.pop(key)
                            if q == p:
                                # re-pointed endpoint, same owner: the
                                # restarted handoff stream diffs it
                                known[key] = obj
                                continue
                            self._stream_known.setdefault(
                                (kind, q), {})[key] = obj
                            to_reconcile.setdefault(
                                (kind, q), []).append(key)
            # 3. start handoff streams where the fan set lacks one
            started: set = set()
            for kind in self.watch_kinds:
                for q in fan[kind]:
                    ev = self._stream_stops.get((kind, q))
                    if ev is None or ev.is_set():
                        self._start_stream(kind, q, handoff=True)
                        started.add((kind, q))
            # 4. reconcile live streams that received keyspace: streams
            # holding transferred known keys, plus every GAINING
            # partition's stream (freeze-window writes the source never
            # delivered live in no known map — only the list has them)
            for kind in self.watch_kinds:
                for q in gained & fan[kind]:
                    if (kind, q) not in to_reconcile:
                        to_reconcile[(kind, q)] = []
            for (kind, q), keys in to_reconcile.items():
                if (kind, q) not in started:
                    self._reconcile_stream(kind, q, keys)

    def _watch_loop(self, kind: str, partition: int, fn, batch_fn) -> None:
        first = True
        # objects this stream has shown the consumer, for reflector
        # Replace semantics on reconnect: (ns, name) -> last-seen obj.
        # Per (kind, partition): a partition stream relists only ITS
        # slice, so the diff is against what THIS stream showed.
        known: Dict[tuple, Any] = {}

        def key_of(obj) -> tuple:
            return (getattr(obj.metadata, "namespace", ""),
                    obj.metadata.name)

        def deliver(events: List[Event]) -> None:
            for e in events:
                if e.type == DELETED:
                    known.pop(key_of(e.obj), None)
                else:
                    known[key_of(e.obj)] = e.obj
            if batch_fn is not None:
                batch_fn(events)
            else:
                for e in events:
                    fn(e)

        while not self._stopping.is_set():
            try:
                objs, rv = self._list_with_rv(kind, partition=partition)
                if first:
                    # Scheduler.start() replays the first list itself;
                    # this stream only has to remember what exists
                    known.update((key_of(o), o) for o in objs)
                    first = False
                else:
                    # reflector Replace: a dropped watch lost an
                    # unknowable window — deliver only the diff against
                    # what this stream already showed the consumer
                    # (replace_diff: dedupe unchanged, MODIFIED with
                    # last-known old, synthetic DELETED for vanished)
                    from kubernetes_tpu.client.informers import (
                        replace_diff,
                    )
                    from kubernetes_tpu.metrics.fabric_metrics import (
                        fabric_metrics,
                    )

                    fabric_metrics().client_relists_total.inc(kind)
                    events = replace_diff(
                        kind, dict(known),
                        {key_of(o): o for o in objs})
                    if events:
                        deliver(events)
                self._stream_watch(kind, rv, deliver,
                                   partition=partition)
            except (http.client.HTTPException, OSError, RuntimeError):
                pass
            if self._stopping.is_set():
                return
            time.sleep(0.2)   # relist-and-rewatch (reflector restart)

    def _stream_watch(self, kind: str, rv: int, deliver,
                      partition: int = 0, stream_key=None,
                      stop: Optional[threading.Event] = None) -> None:
        plural = KIND_TO_PLURAL.get(kind, kind.lower() + "s")
        host, port, w_replica = self._read_endpoint(partition)
        conn = http.client.HTTPConnection(host, port, timeout=300)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if stream_key is not None:
            # registered so a topology re-plumb can sever a stream
            # blocked mid-read (stop events alone can't interrupt a
            # socket read)
            self._stream_conns[stream_key] = conn
        headers = {}
        if self.binary:
            headers["Accept"] = codec.BINARY_CONTENT_TYPE
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if self.flow_id:
            headers["X-Flow-Id"] = self.flow_id
        headers[codec.VERSION_HEADER] = str(self.codec_version)
        tracer = get_tracer()
        if tracer.enabled:
            # watch handoff carries an explicitly-UNSAMPLED context (a
            # control-plane call, not a pod trace): the server must
            # honor the bit and never open a request span for it, and
            # the KTPU_TRACE=off arm must shed even this header
            headers[TRACE_HEADER] = format_trace_header(
                f"watch:{kind}/p{partition}",
                tracer.current_span_id(), False)
        try:
            conn.request(
                "GET", f"/api/v1/{plural}?watch=1&resourceVersion={rv}",
                headers=headers)
            resp = conn.getresponse()
            # the stream's wire contract is pinned for its whole life
            # (server-side too); record it so a restart seam that puts
            # a different-version server behind this partition shows up
            # as a re-negotiation (owner streams only — replica echoes
            # stay out of the owner's pin ledger, as in _request)
            if w_replica is None:
                self._record_negotiated(partition, resp)
            if resp.status != 200:
                resp.read()
                if resp.status == 410:
                    # expired resourceVersion (watch-cache compaction or
                    # a server restart): the caller's relist IS the
                    # 410-Gone recovery; count it for observability
                    self._note_retry("WATCH", "http_410")
                return
            binary = (resp.headers.get("Content-Type") or "").startswith(
                codec.BINARY_CONTENT_TYPE)
            while not self._stopping.is_set() \
                    and (stop is None or not stop.is_set()):
                if binary:
                    try:
                        batch = codec.read_frame(resp)
                    except Exception:  # noqa: BLE001 — torn outer frame
                        # the stream was cut mid-frame (injected
                        # truncation, server death): relist, exactly
                        # like the JSON torn-line path below
                        return
                    if batch is None:
                        return
                    # a coalesced chunk carries per-event pickles
                    # (encoded once server-side, shared across
                    # watchers); decode each into the same Event shape.
                    # The 4th element is the store-commit timestamp
                    # (freshness SLI); legacy 3-tuples decode with no
                    # stamp.
                    try:
                        events = []
                        for item in batch:
                            if isinstance(item, (bytes, bytearray)):
                                item = codec.decode(item)
                            origin = None
                            if len(item) == 4:
                                t, obj, old, ts = item
                                if isinstance(ts, tuple):
                                    # fleet tracing: the commit-time
                                    # origin context rides inside the
                                    # ts slot as (ts, origin)
                                    ts, origin = ts
                            else:
                                (t, obj, old), ts = item, 0.0
                            events.append(
                                Event(t, kind, obj, old, ts, origin))
                    except Exception:  # noqa: BLE001 — torn event
                        return
                else:
                    line = resp.readline()
                    if not line:
                        return
                    try:
                        msg = json.loads(line)
                        obj = from_wire(msg["object"], kind)
                    except (ValueError, KeyError, TypeError):
                        # torn frame: the stream was cut mid-line
                        # (injected truncation, server death) — relist.
                        # Scoped to PARSING only: a consumer error in
                        # deliver() must surface, not loop forever.
                        return
                    events = [Event(msg["type"], kind, obj,
                                    ts=float(msg.get("commitTs") or 0.0))]
                self._observe_delivery(kind, events)
                self._trace_watch_delivery(events)
                deliver(events)
        finally:
            if stream_key is not None \
                    and self._stream_conns.get(stream_key) is conn:
                self._stream_conns.pop(stream_key, None)
            try:
                conn.close()
            except OSError:
                pass
