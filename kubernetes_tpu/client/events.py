"""EventRecorder: async, aggregating event recording.

Behavioral equivalent of the reference's client-go ``tools/record``
(EventBroadcaster + recorderImpl, used by the scheduler at
``pkg/scheduler/scheduler.go:331,423`` and preemption at
``default_preemption.go:698``): hot paths enqueue and return immediately;
a background flush thread writes Event objects through the store.
Correlated occurrences (same object + type + reason + message) aggregate
into a single Event with a bumped ``count`` — the reference's
EventAggregator/eventLogger correlation — and the queue is bounded, so a
misbehaving hot loop degrades to dropped events rather than back-pressure
(the broadcaster's full-channel drop, i.e. event-spam protection).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Tuple

from kubernetes_tpu.api.types import Event, ObjectMeta, object_reference

NORMAL = "Normal"
WARNING = "Warning"

_PRUNE_INTERVAL = 60.0


class EventRecorder:
    def __init__(self, client, component: str, queue_cap: int = 8192,
                 flush_interval: float = 0.2):
        self.client = client
        self.component = component
        self._queue: deque = deque()
        self._cap = queue_cap
        self._flush_interval = flush_interval
        self.dropped = 0
        # correlation cache: key -> Event name in the store
        self._correlated: dict = {}
        self._lock = threading.Lock()
        # serializes whole flush passes: external flush_now callers
        # (tests, shutdown) race the background loop otherwise, and
        # _write's correlation cache is not safe under two writers
        self._flush_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._seq = 0
        self._last_prune = 0.0
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def event(self, obj, event_type: str, reason: str, message: str) -> None:
        """Record an event against ``obj``. Non-blocking: enqueues for the
        flush thread (recorderImpl.Event → broadcaster channel). The
        object reference is extracted at FLUSH time — API objects are
        immutable after create (copy-on-write updates), so deferring is
        safe and keeps the hot path to one deque append."""
        self._enqueue(obj, event_type, reason, message, ())

    def eventf(self, obj, event_type: str, reason: str,
               fmt: str, *args) -> None:
        """Like ``event`` but defers ``fmt % args`` to the flush thread —
        the scheduler records one Scheduled event per bound pod, and
        string formatting is pure overhead on the commit hot path."""
        self._enqueue(obj, event_type, reason, fmt, args)

    def _enqueue(self, obj, event_type, reason, fmt, args) -> None:
        with self._lock:
            if len(self._queue) >= self._cap:
                self.dropped += 1   # full channel: drop, never block
                return
            self._queue.append(
                (obj, event_type, reason, fmt, args, time.time())
            )
        self._wake.set()

    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"events-{self.component}"
        )
        self._thread.start()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if flush:
            self.flush_now()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._flush_interval)
            self._wake.clear()
            try:
                with self._flush_lock:
                    self._flush_locked()
            except Exception:  # pragma: no cover — recording must never
                pass           # take down the component

    # ------------------------------------------------------------------
    def flush_now(self) -> int:
        """Drain the queue synchronously (tests and shutdown)."""
        with self._flush_lock:
            return self._flush_locked()

    def _flush_locked(self) -> int:
        """One flush pass: correlated occurrences update their existing
        Event; everything fresh lands through ONE bulk store write (one
        lock acquisition + one batched watch delivery for the whole
        pass — a 9k pods/s commit stream records 9k Scheduled events/s,
        and per-event store round-trips were a measured drag on the
        scheduler's own GIL time)."""
        with self._lock:
            items = list(self._queue)
            self._queue.clear()
        fresh: list = []
        pending: dict = {}   # event name -> Event queued in THIS pass
        bulk = getattr(self.client, "create_objects_bulk", None)
        for obj, etype, reason, fmt, args, ts in items:
            message = fmt % args if args else fmt
            ev = self._build(object_reference(obj), etype, reason,
                             message, ts, pending,
                             immediate=bulk is None)
            if ev is not None:
                if bulk is None:
                    try:
                        self.client.create_object("Event", ev)
                    except ValueError:
                        pass  # name collision: drop
                else:
                    fresh.append(ev)
        if fresh:
            bulk("Event", fresh)
        now = time.time()
        if items and now - self._last_prune > _PRUNE_INTERVAL:
            self._last_prune = now
            prune = getattr(self.client, "prune_expired_events", None)
            if prune is not None:
                prune(now)
        return len(items)

    def _build(self, ref, etype: str, reason: str, message: str,
               ts: float, pending: dict,
               immediate: bool = False) -> Optional[Event]:
        """Correlate or construct: returns the fresh Event to create
        (caller batches the write), or None when an existing Event —
        stored, or queued earlier in THIS pass (``pending``) — absorbed
        the occurrence."""
        # cluster-scoped objects have no namespace; their events live in
        # "default" — the SAME namespace for create and re-lookup, or
        # aggregation silently never hits
        ns = ref.namespace or "default"
        key: Tuple = (ref.kind, ns, ref.name, ref.uid, etype,
                      reason, message)
        name = self._correlated.get(key)
        if name is not None:
            queued = pending.get(name)
            if queued is not None:
                queued.count += 1
                queued.last_timestamp = ts
                if immediate:
                    # non-bulk client: the object was already created
                    # this pass, so the bump must be WRITTEN, not just
                    # applied to a local copy
                    self.client.update_object("Event", queued)
                return None
            existing = self.client.get_object("Event", ns, name)
            if existing is not None and existing.involved_object.uid == ref.uid:
                existing.count += 1
                existing.last_timestamp = ts
                self.client.update_object("Event", existing)
                return None
            del self._correlated[key]
        self._seq += 1
        name = f"{ref.name}.{int(ts * 1e6):x}.{self._seq:x}"
        ev = Event(
            metadata=ObjectMeta(name=name, namespace=ns),
            involved_object=ref,
            reason=reason,
            message=message,
            type=etype,
            count=1,
            first_timestamp=ts,
            last_timestamp=ts,
            source_component=self.component,
        )
        self._correlated[key] = name
        pending[name] = ev
        if len(self._correlated) > 4096:   # bounded correlation cache
            self._correlated.pop(next(iter(self._correlated)))
        return ev
