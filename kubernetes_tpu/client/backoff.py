"""Client-side resilience primitives: jittered exponential backoff, a
per-client retry budget, and a circuit breaker.

Behavioral equivalents of the reference client-go stack the scheduler
depends on to survive an unhealthy apiserver:

- ``Backoff`` — ``k8s.io/apimachinery/pkg/util/wait.Backoff`` (duration,
  factor, jitter, cap): each step multiplies the base delay and smears
  it by ±jitter so a fleet of clients whose connections dropped together
  does not reconnect in lockstep (the thundering-herd relist storm the
  reference's ``JitterUntil`` exists to prevent). Deterministic under a
  caller-supplied seeded RNG so chaos runs replay exactly.
- ``RetryBudget`` — client-go's ``flowcontrol.Backoff`` + the sidecar
  retry-budget idea: a token bucket spent per retry (never per first
  attempt) and refilled over time, so a dying server costs each client a
  bounded amount of extra load instead of retries-squared.
- ``CircuitBreaker`` — consecutive-failure trip wire with listener
  callbacks; the scheduler wires it to degraded mode (pause binding,
  requeue, resume on recovery) the way the reference's leader election
  demotes a scheduler that lost its apiserver.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Iterator, Optional, Tuple

__all__ = ["Backoff", "RetryBudget", "CircuitBreaker", "retry_call"]


class Backoff:
    """Exponential backoff with bounded jitter.

    ``delay(attempt)`` for attempt n (0-based) is
    ``min(base * factor**n, cap)`` smeared to ``d * (1 ± jitter)`` —
    always >= 0, and with ``jitter < 1`` always > 0. ``steps()`` yields
    successive delays statefully.
    """

    def __init__(self, base: float = 0.05, factor: float = 2.0,
                 cap: float = 5.0, jitter: float = 0.4,
                 rng: Optional[random.Random] = None):
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.base = float(base)
        self.factor = float(factor)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self._rng = rng if rng is not None else random.Random()

    def delay(self, attempt: int) -> float:
        d = min(self.base * (self.factor ** max(0, attempt)), self.cap)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return d

    def steps(self) -> Iterator[float]:
        attempt = 0
        while True:
            yield self.delay(attempt)
            attempt += 1


class RetryBudget:
    """Token bucket spent once per RETRY. When empty, the caller must
    surface the original error instead of sleeping again — a misbehaving
    server can slow a client down but never stall it unboundedly."""

    def __init__(self, budget: float = 10.0, refill_per_second: float = 1.0):
        self.capacity = float(budget)
        self.refill = float(refill_per_second)
        self._tokens = self.capacity
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.capacity,
                               self._tokens + (now - self._last) * self.refill)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def remaining(self) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.capacity,
                               self._tokens + (now - self._last) * self.refill)
            self._last = now
            return self._tokens


class CircuitBreaker:
    """Consecutive-failure breaker with open/close notifications.

    ``record_failure()`` trips the breaker after ``failure_threshold``
    consecutive failures; ``record_success()`` closes it immediately
    (requests themselves are the half-open probes — the retry loop keeps
    attempting, so a recovered server closes the circuit on its first
    served request). The listener runs OUTSIDE the lock with the new
    state; it must be idempotent."""

    def __init__(self, failure_threshold: int = 5,
                 listener: Optional[Callable[[bool], None]] = None):
        self.failure_threshold = int(failure_threshold)
        self._failures = 0
        self._open = False
        self._lock = threading.Lock()
        self._listener = listener

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._open

    def set_listener(self, listener: Optional[Callable[[bool], None]],
                     replay: bool = True) -> None:
        """Install ``listener(open: bool)``; with ``replay`` the current
        state is delivered immediately so a late subscriber (a scheduler
        started after the first outage) does not miss an open circuit."""
        with self._lock:
            self._listener = listener
            state = self._open
        if replay and listener is not None:
            listener(state)

    def _notify(self, state: bool) -> None:
        listener = self._listener
        if listener is not None:
            try:
                listener(state)
            except Exception:  # noqa: BLE001 — a bad listener must not
                pass           # poison the transport path

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            tripped = (not self._open
                       and self._failures >= self.failure_threshold)
            if tripped:
                self._open = True
        if tripped:
            self._notify(True)

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            recovered = self._open
            self._open = False
        if recovered:
            self._notify(False)


def retry_call(
    fn: Callable[[], object],
    retryable: Tuple[type, ...] = (OSError,),
    backoff: Optional[Backoff] = None,
    budget: Optional[RetryBudget] = None,
    max_attempts: int = 4,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Call ``fn`` with jittered-backoff retries. Exhausting
    ``max_attempts`` or the ``budget`` re-raises the ORIGINAL error
    (never a synthetic wrapper — callers dispatch on error type)."""
    backoff = backoff or Backoff()
    for attempt in range(max_attempts):
        try:
            return fn()
        except retryable as err:
            last = attempt == max_attempts - 1
            if last or (budget is not None and not budget.try_spend()):
                raise
            if on_retry is not None:
                on_retry(attempt, err)
            sleep(backoff.delay(attempt))
    raise RuntimeError("unreachable")
