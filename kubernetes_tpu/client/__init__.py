"""Client layer: informers/listers, workqueues, leader election.

The focused re-implementation of the reference's ``client-go`` surface the
control plane actually uses (SURVEY.md section 2.6): typed object store +
watch-driven delta feed + event handlers, rate-limited work queues, and
lease-based leader election.
"""

from kubernetes_tpu.client.informers import (
    Lister,
    ResourceEventHandler,
    SharedInformer,
    SharedInformerFactory,
)
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.client.workqueue import (
    DelayingQueue,
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
    WorkQueue,
)

__all__ = [
    "DelayingQueue",
    "ItemExponentialFailureRateLimiter",
    "LeaderElectionConfig",
    "LeaderElector",
    "Lister",
    "RateLimitingQueue",
    "ResourceEventHandler",
    "SharedInformer",
    "SharedInformerFactory",
    "WorkQueue",
]
