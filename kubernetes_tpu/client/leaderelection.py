"""Lease-based leader election.

Behavioral equivalent of the reference's
``client-go/tools/leaderelection/leaderelection.go``: candidates race to
acquire/renew a Lease record; only the holder runs its workload; losing
the lease mid-run invokes ``on_stopped_leading`` (the reference
``klog.Fatalf``s there — ``cmd/kube-scheduler/app/server.go:205`` — we
leave the reaction to the caller so hollow control planes can restart).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.utils.clock import RealClock


@dataclass
class LeaderElectionConfig:
    lock_name: str = "kube-scheduler"
    identity: str = "scheduler-0"
    lease_duration: float = 15.0   # reference defaults: 15s/10s/2s
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    on_started_leading: Optional[Callable[[], None]] = None
    on_stopped_leading: Optional[Callable[[], None]] = None
    on_new_leader: Optional[Callable[[str], None]] = field(default=None)


class LeaderElector:
    def __init__(self, store: ClusterStore, config: LeaderElectionConfig,
                 clock=None):
        self._store = store
        self.config = config
        self._clock = clock or RealClock()
        self._stop = threading.Event()
        self._is_leader = False
        self._observed_leader = ""

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def try_acquire_or_renew(self) -> bool:
        ok = self._store.try_acquire_or_renew(
            self.config.lock_name, self.config.identity,
            self._clock.now(), self.config.lease_duration,
        )
        holder = self._store.lease_holder(self.config.lock_name) or ""
        if holder != self._observed_leader:
            self._observed_leader = holder
            if self.config.on_new_leader is not None:
                self.config.on_new_leader(holder)
        return ok

    def run(self) -> None:
        """Blocks: acquire loop → leading callback → renew loop."""
        while not self._stop.is_set():
            if self.try_acquire_or_renew():
                self._is_leader = True
                if self.config.on_started_leading is not None:
                    # the reference runs OnStartedLeading in its own
                    # goroutine so a blocking workload can't starve renewal
                    threading.Thread(
                        target=self.config.on_started_leading,
                        daemon=True, name="leading",
                    ).start()
                self._renew_loop()
                self._is_leader = False
                if self.config.on_stopped_leading is not None:
                    self.config.on_stopped_leading()
                if self._stop.is_set():
                    return
            self._stop.wait(self.config.retry_period)

    def run_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True, name="leader-elect")
        t.start()
        return t

    def _renew_loop(self) -> None:
        while not self._stop.is_set():
            deadline = self._clock.now() + self.config.renew_deadline
            renewed = False
            while self._clock.now() < deadline and not self._stop.is_set():
                if self.try_acquire_or_renew():
                    renewed = True
                    break
                self._stop.wait(self.config.retry_period)
            if not renewed:
                return  # lost the lease
            self._stop.wait(self.config.retry_period)

    def stop(self) -> None:
        self._stop.set()
