"""Shared informers + listers over the cluster store's watch feed.

Behavioral equivalent of the reference's client-go informer machinery
(``tools/cache/reflector.go:254`` ListAndWatch → DeltaFIFO →
``tools/cache/controller.go:127`` sharedIndexInformer.processLoop →
registered event handlers), collapsed for an in-process store: the initial
List is replayed as synthetic ADDED deltas, then live watch events append
to a per-factory delta FIFO drained by one dispatch thread, so handler
ordering matches event ordering and handlers never run under the store
lock.

Listers read the informer's thread-safe indexer (the reference's
``tools/cache/thread_safe_store.go``) — they see the informer's view, not
the store's, exactly like client-go.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from kubernetes_tpu.apiserver.store import ADDED, DELETED, MODIFIED, ClusterStore, Event

_logger = logging.getLogger(__name__)


class ResourceEventHandler:
    """Handler triple (reference ResourceEventHandlerFuncs)."""

    def __init__(self, on_add=None, on_update=None, on_delete=None,
                 filter_fn: Optional[Callable[[Any], bool]] = None):
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        self.filter_fn = filter_fn

    def handle(self, event: Event) -> None:
        if self.filter_fn is not None and not self.filter_fn(event.obj):
            # FilteringResourceEventHandler: an update moving the object
            # out of the filter set is delivered as a delete (and into it,
            # as an add) — reference tools/cache/controller.go:221-255.
            if (
                event.type == MODIFIED
                and event.old_obj is not None
                and self.filter_fn(event.old_obj)
                and self.on_delete is not None
            ):
                self.on_delete(event.obj)
            return
        if event.type == ADDED and self.on_add is not None:
            self.on_add(event.obj)
        elif event.type == MODIFIED:
            if (
                self.filter_fn is not None
                and event.old_obj is not None
                and not self.filter_fn(event.old_obj)
            ):
                if self.on_add is not None:
                    self.on_add(event.obj)
            elif self.on_update is not None:
                self.on_update(event.old_obj, event.obj)
        elif event.type == DELETED and self.on_delete is not None:
            self.on_delete(event.obj)


# cluster-scoped kinds key by bare name; everything else by namespace/name
# (ObjectMeta defaults namespace to "default" even for cluster-scoped
# objects, so scoping must be decided by kind, not by metadata shape)
_CLUSTER_SCOPED = {"Node", "PersistentVolume", "StorageClass", "CSINode"}


def _meta_key(kind: str, obj: Any) -> str:
    meta = obj.metadata
    if kind in _CLUSTER_SCOPED:
        return meta.name
    return f"{meta.namespace}/{meta.name}"


def replace_diff(kind: str, known: Dict[Any, Any],
                 live: Dict[Any, Any]) -> List[Event]:
    """Reflector Replace as a DIFF (shared by SharedInformer._relist and
    RestClusterClient's watch relist): against ``known`` (what the
    consumer last saw), ``live`` (the fresh list) yields — nothing for
    unchanged objects (same resourceVersion: replays dedupe), MODIFIED
    carrying the last-known old for rv changes (a bind missed during
    the outage still reads as a bind transition), ADDED for new keys,
    and synthetic DELETED for vanished ones (DeletedFinalStateUnknown),
    or caches schedule against phantom objects forever."""
    events: List[Event] = [
        Event(DELETED, kind, obj)
        for key, obj in known.items() if key not in live
    ]
    for key, obj in live.items():
        old = known.get(key)
        if old is None:
            events.append(Event(ADDED, kind, obj))
        elif (old.metadata.resource_version
              != obj.metadata.resource_version):
            events.append(Event(MODIFIED, kind, obj, old))
    return events


class Indexer:
    """Thread-safe key→object map with namespace listing."""

    def __init__(self, kind: str):
        self.kind = kind
        self._lock = threading.Lock()
        self._items: Dict[str, Any] = {}

    def replace(self, objs: List[Any]) -> None:
        with self._lock:
            self._items = {_meta_key(self.kind, o): o for o in objs}

    def upsert(self, obj: Any) -> None:
        with self._lock:
            self._items[_meta_key(self.kind, obj)] = obj

    def delete(self, obj: Any) -> None:
        with self._lock:
            self._items.pop(_meta_key(self.kind, obj), None)

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._items.get(key)

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._items.values())

    def list_keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._items)


class SharedInformer:
    """One kind's informer: indexer + handler fan-out."""

    def __init__(self, kind: str, list_fn: Callable[[], List[Any]]):
        self.kind = kind
        self._list_fn = list_fn
        self.indexer = Indexer(kind)
        self._handlers: List[ResourceEventHandler] = []
        self._synced = False

    def add_event_handler(self, on_add=None, on_update=None, on_delete=None,
                          filter_fn=None) -> ResourceEventHandler:
        h = ResourceEventHandler(on_add, on_update, on_delete, filter_fn)
        self._handlers.append(h)
        return h

    def has_synced(self) -> bool:
        return self._synced

    # -- called by the factory dispatch thread -------------------------
    def _sync(self) -> List[Event]:
        objs = self._list_fn()
        self.indexer.replace(objs)
        self._synced = True
        return [Event(ADDED, self.kind, o) for o in objs]

    def _relist(self) -> List[Event]:
        """Reflector Replace after a dropped watch or an expired
        resourceVersion (410 Gone): RELIST — never resume — and emit
        only the diff against the indexer (see ``replace_diff``)."""
        objs = self._list_fn()
        events = replace_diff(
            self.kind, self.indexer.snapshot(),
            {_meta_key(self.kind, o): o for o in objs})
        self.indexer.replace(objs)
        self._synced = True
        return events

    def _apply(self, event: Event) -> None:
        if event.type == DELETED:
            self.indexer.delete(event.obj)
        else:
            self.indexer.upsert(event.obj)

    def _dispatch(self, event: Event) -> None:
        for h in list(self._handlers):
            h.handle(event)


class Lister:
    """Reads an informer's indexer (reference listers/core/v1)."""

    def __init__(self, informer: SharedInformer):
        self._informer = informer

    def list(self) -> List[Any]:
        return self._informer.indexer.list()

    def get(self, name: str, namespace: str = "default") -> Optional[Any]:
        if self._informer.kind in _CLUSTER_SCOPED:
            return self._informer.indexer.get(name)
        return self._informer.indexer.get(f"{namespace}/{name}")

    def by_namespace(self, namespace: str) -> List[Any]:
        return [
            o for o in self._informer.indexer.list()
            if getattr(o.metadata, "namespace", "") == namespace
        ]


# kind -> ClusterStore list method name
_KIND_LISTS = {
    "Pod": "list_pods",
    "Node": "list_nodes",
    "Service": "list_all_services",
    "ReplicaSet": "list_all_replica_sets",
    "ReplicationController": "list_all_replication_controllers",
    "StatefulSet": "list_all_stateful_sets",
    "PersistentVolume": "list_pvs",
    "PersistentVolumeClaim": "list_all_pvcs",
    "StorageClass": "list_storage_classes",
    "CSINode": "list_csi_nodes",
    "PodDisruptionBudget": "list_pdbs",
    "Endpoints": "list_endpoints",
    "Deployment": "list_deployments",
    "DaemonSet": "list_daemon_sets",
    "Job": "list_jobs",
    "Namespace": "list_namespaces",
    "ResourceQuota": "list_resource_quotas",
    "ServiceAccount": "list_service_accounts",
    "CronJob": "list_cron_jobs",
    "HorizontalPodAutoscaler": "list_hpas",
    "EndpointSlice": "list_endpoint_slices",
}


class SharedInformerFactory:
    """Per-store informer factory (reference informers.NewSharedInformerFactory).

    ``start()`` replays the initial List into every requested informer and
    begins draining live watch events on a dispatch thread;
    ``wait_for_cache_sync()`` blocks until the replay completed.
    """

    def __init__(self, store: ClusterStore):
        self._store = store
        self._informers: Dict[str, SharedInformer] = {}
        self._lock = threading.Lock()
        self._deltas: deque = deque()
        self._cond = threading.Condition(self._lock)
        self._watch_handle = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._synced_event = threading.Event()
        self._pending_sync: List[SharedInformer] = []
        self._pending_resync: List[SharedInformer] = []

    def informer_for(self, kind: str) -> SharedInformer:
        with self._cond:
            inf = self._informers.get(kind)
            if inf is None:
                list_name = _KIND_LISTS.get(kind)
                # store-shaped clients without the typed accessor (the
                # partition-aware RestClusterClient) and kinds without
                # one at all (Secret, ConfigMap, CSR, RBAC kinds,
                # CRD-registered kinds) ride the generic registry
                # surface; the typed store methods stay the in-process
                # fast path
                list_fn = getattr(self._store, list_name, None) \
                    if list_name is not None else None
                if list_fn is None:
                    list_fn = (
                        lambda kind=kind: self._store.list_objects(kind)
                    )
                inf = SharedInformer(kind, list_fn)
                self._informers[kind] = inf
                if self._thread is not None:
                    # registered after start(): sync on the dispatch thread
                    self._pending_sync.append(inf)
                    self._cond.notify()
            return inf

    def lister_for(self, kind: str) -> Lister:
        return Lister(self.informer_for(kind))

    def resync(self, kind: str) -> None:
        """Force a relist of one kind on the dispatch thread — the
        recovery entry point when the watch source reports an expired
        or unknown resourceVersion (HTTP 410 over REST, compaction on
        the watch cache). Handlers observe only the diff; events that
        also arrive through the live feed dedupe against the indexer's
        resourceVersion like initial-sync replays do."""
        with self._cond:
            inf = self._informers.get(kind)
            if inf is None or self._stopped:
                return
            if self._thread is None:
                # not started yet: the initial sync will list anyway
                return
            self._pending_resync.append(inf)
            self._cond.notify()

    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        try:
            # batch ingestion: a source that delivers coalesced batches
            # (the store's _dispatch_many, RestClusterClient's decoded
            # watch chunks) appends the whole run under ONE lock
            # acquisition + notify instead of one per event
            self._watch_handle = self._store.watch(
                self._enqueue, batch_fn=self._enqueue_many)
        except TypeError:
            # store-shaped test doubles without the batch_fn parameter
            self._watch_handle = self._store.watch(self._enqueue)
        self._thread = threading.Thread(target=self._process_loop, daemon=True,
                                        name="informer-factory")
        self._thread.start()

    def _enqueue(self, event: Event) -> None:
        with self._cond:
            if self._stopped:
                return
            self._deltas.append(event)
            self._cond.notify()

    def _enqueue_many(self, events: List[Event]) -> None:
        with self._cond:
            if self._stopped:
                return
            self._deltas.extend(events)
            self._cond.notify()

    def _process_loop(self) -> None:
        # initial list replay (the List half of ListAndWatch). Live events
        # that arrived before/while listing are processed afterwards; the
        # replay-dedup below keeps them from double-firing handlers.
        for inf in list(self._informers.values()):
            self._sync_one(inf)
        self._synced_event.set()
        while True:
            with self._cond:
                while (not self._deltas and not self._pending_sync
                       and not self._pending_resync
                       and not self._stopped):
                    self._cond.wait(0.5)
                if self._stopped and not self._deltas:
                    return
                pending, self._pending_sync = self._pending_sync, []
                resyncs, self._pending_resync = self._pending_resync, []
                # drain the WHOLE backlog under one lock acquisition
                # (batch ingestion: a 30k-event informer catch-up costs
                # O(batches) wakeups, not O(events))
                events: List[Event] = list(self._deltas)
                self._deltas.clear()
            if events:
                self._note_freshness(events)
            for inf in pending:  # informers registered after start()
                self._sync_one(inf)
            for inf in resyncs:  # relist-not-resume recovery (410 Gone)
                try:
                    for ev in inf._relist():
                        self._dispatch_guarded(inf, ev)
                except Exception:  # noqa: BLE001 — dispatch must survive
                    _logger.exception("informer %s relist failed",
                                      inf.kind)
            for event in events:
                self._ingest(event)

    def _note_freshness(self, events: List[Event]) -> None:
        """Freshness SLIs for one drain wakeup: per-kind commit→dispatch
        lag (``informer_lag_seconds``) and the backlog this wakeup
        absorbed (``informer_queue_depth``). One ``observe_many`` per
        (kind, wakeup) — the factory's own batching keeps the cost
        O(kinds), not O(events)."""
        try:
            import time as _time

            from kubernetes_tpu.metrics.freshness_metrics import (
                freshness_metrics,
            )

            fm = freshness_metrics()
            if not fm.enabled:
                return
            fm.informer_queue_depth.set(float(len(events)))
            now = _time.time()
            by_kind: Dict[str, List[float]] = {}
            for e in events:
                if e.ts:
                    by_kind.setdefault(e.kind, []).append(
                        max(0.0, now - e.ts))
            for kind, lags in by_kind.items():
                fm.informer_lag_seconds.observe_many(lags, kind)
        except Exception:  # noqa: BLE001 — SLIs must never break dispatch
            _logger.debug("informer freshness accounting failed",
                          exc_info=True)

    def _ingest(self, event: Event) -> None:
        inf = self._informers.get(event.kind)
        if inf is None or not inf.has_synced():
            return
        # replay dedup: an ADDED that raced the initial list is already
        # in the indexer at the same resource version — skip it.
        if event.type == ADDED:
            existing = inf.indexer.get(_meta_key(inf.kind, event.obj))
            if (existing is not None
                    and existing.metadata.resource_version
                    == event.obj.metadata.resource_version):
                return
        # a MODIFIED that raced a relist dedupes the same way, but
        # ONLY for a distinct instance: the in-process store mutates
        # and redispatches the very object the indexer holds, where
        # an rv comparison against itself would swallow every update
        elif event.type == MODIFIED:
            existing = inf.indexer.get(_meta_key(inf.kind, event.obj))
            if (existing is not None
                    and existing is not event.obj
                    and existing.metadata.resource_version
                    == event.obj.metadata.resource_version):
                return
        inf._apply(event)
        self._dispatch_guarded(inf, event)

    def _sync_one(self, inf: SharedInformer) -> None:
        try:
            for ev in inf._sync():
                self._dispatch_guarded(inf, ev)
        except Exception:  # noqa: BLE001 — the dispatch thread must survive
            _logger.exception("informer %s initial sync failed", inf.kind)

    @staticmethod
    def _dispatch_guarded(inf: SharedInformer, event: Event) -> None:
        try:
            inf._dispatch(event)
        except Exception:  # noqa: BLE001 — a bad handler must not kill
            _logger.exception("event handler failed for %s %s",
                              event.kind, event.type)

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        return self._synced_event.wait(timeout)

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._watch_handle is not None:
            self._watch_handle.stop()
            self._watch_handle = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
