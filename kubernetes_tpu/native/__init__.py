"""Native (C++) runtime components, loaded via ctypes.

The reference's scheduling algorithm is native (Go); this package holds
the framework's native pieces — currently the planes-layout batch solver
(``solver.cc``), used as the CPU-native backend and as an independent
differential oracle for the TPU kernels.

No pybind11 in this environment: the library is a plain ``extern "C"``
shared object built with g++ and bound with ctypes on flat numpy
buffers (the planes layout is already columnar, so there is no object
marshalling at the boundary). Everything degrades gracefully: if the
compiler or library is unavailable, ``load()`` returns None and callers
fall back to the JAX backends.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

_logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "solver.cc")
_LIB = os.path.join(_DIR, "libktpu_solver.so")

_lock = threading.Lock()
_lib = None
_tried = False


def build(force: bool = False) -> bool:
    """Compile solver.cc → libktpu_solver.so. Returns True on success.
    Skipped when the library is newer than the source."""
    if (
        not force
        and os.path.exists(_LIB)
        and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
    ):
        return True
    cmd = [
        # -ffp-contract=off: no FMA contraction — the solver's f32 math
        # must round exactly like XLA's separate mul/add for the
        # bit-identical differential contract
        "g++", "-O3", "-march=native", "-ffp-contract=off",
        "-shared", "-fPIC", "-o", _LIB, _SRC,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        _logger.warning("native solver build unavailable: %s", e)
        return False
    if proc.returncode != 0:
        _logger.warning("native solver build failed:\n%s", proc.stderr)
        return False
    return True


def load():
    """Load (building on first use) the native library. Returns the
    ctypes CDLL with ``ktpu_solve`` configured, or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            _logger.warning("native solver load failed: %s", e)
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.ktpu_solve.restype = ctypes.c_int
        lib.ktpu_solve.argtypes = [
            i32p, f32p, i32p, i32p, i32p, i32p, f32p, i32p, f32p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ]
        _lib = lib
        return _lib
