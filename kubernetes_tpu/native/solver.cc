// Native (C++) batch scheduling solver on the planes layout.
//
// Mirrors ops/solver.py::_step one-to-one (see also the pallas kernel in
// ops/pallas_solver.py): per pod, evaluate feasibility (capacity fit,
// pod-count cap, static predicate masks, hard topology-spread skew,
// (anti-)affinity domain counts) and scores (balanced/least allocation,
// soft spread, preferred affinity, static) over every node, commit the
// argmax (first max wins = lowest node index, matching jnp.argmax), and
// update the dynamic state in place.
//
// Topology/affinity counts are kept PER NODE (the kernel's gather-free
// representation): a commit to node j increments every node sharing j's
// domain value via one compare loop.
//
// All float math is single-precision with the same operation order as
// the JAX paths so results are bit-identical (the differential tests
// assert exact equality of assignments).
//
// Layout contracts (must match ops/pallas_solver.py):
//   static ints  [CS, N]: alloc[R] | max_pods | masks[U] | sc_codes[SC]
//                         | sc_domain[U*SC] | term_codes[T] | node_valid
//   state planes [CD, N]: requested[R] | nonzero[2] | pod_count
//                         | sc_counts[SC] | term_counts[T]
//                         | term_owners[T] | sv_attached[SV]
//                         | totals (flat [0..T) slots)
//   pod ints     [B, C]:  req[R] | nonzero[2] | profile | valid
//                         | pod_sc[SC] | sc_match[SC] | match_by[T]
//                         | own_aff[T] | own_anti[T]
//                         | [sv_slot, sv_col]  (sv > 0 epochs only)
//
// Shared-volume attach planes (sv > 0): a shared CSI volume's attach
// demand is CONDITIONAL per node — 1 only where sv_attached[slot] is
// still 0 (csi.go len(in_use | wanted) set semantics); committing sets
// the chosen node's bit. Mirrors _xla_planes_solve's sv branch.
//
// Built as a shared library; loaded with ctypes (no pybind11 in this
// environment). The runtime gracefully falls back to the JAX backends
// when the library is absent.

#include <cstdint>
#include <cmath>
#include <cstring>
#include <vector>

namespace {

constexpr float kNegInf = -1e30f;
constexpr int32_t kBig = 1 << 30;

inline float clip01(float x) {
  return x < 0.0f ? 0.0f : (x > 1.0f ? 1.0f : x);
}

}  // namespace

extern "C" {

// weights: balanced, least, spread, affinity, static (SolverParams order)
// Returns 0 on success.
int ktpu_solve(const int32_t* static_ints, const float* static_f32s,
               const int32_t* sc_meta, int32_t* state, int32_t* totals,
               const int32_t* pod_ints, const float* pod_floats,
               int32_t* assignments, const float* weights,
               int32_t r, int32_t sc, int32_t t, int32_t u, int32_t v,
               int64_t n, int32_t b, int32_t c_cols, int32_t sv) {
  // static plane offsets
  const int64_t so_alloc = 0;
  const int64_t so_max_pods = so_alloc + r;
  const int64_t so_masks = so_max_pods + 1;
  const int64_t so_sc_codes = so_masks + u;
  const int64_t so_sc_domain = so_sc_codes + sc;
  const int64_t so_term_codes = so_sc_domain + (int64_t)u * sc;
  const int64_t so_node_valid = so_term_codes + t;
  // state plane offsets
  const int64_t do_requested = 0;
  const int64_t do_nonzero = do_requested + r;
  const int64_t do_pod_count = do_nonzero + 2;
  const int64_t do_sc_counts = do_pod_count + 1;
  const int64_t do_term_counts = do_sc_counts + sc;
  const int64_t do_term_owners = do_term_counts + t;
  const int64_t do_sv = do_term_owners + t;
  // pod column offsets (pack_podin)
  const int32_t c_req = 0;
  const int32_t c_nonzero = r;
  const int32_t c_profile = r + 2;
  const int32_t c_valid = r + 3;
  const int32_t c_pod_sc = r + 4;
  const int32_t c_sc_match = r + 4 + sc;
  const int32_t c_match_by = r + 4 + 2 * sc;
  const int32_t c_own_aff = r + 4 + 2 * sc + t;
  const int32_t c_own_anti = r + 4 + 2 * sc + 2 * t;
  const int32_t c_sv = r + 4 + 2 * sc + 3 * t;

  const int32_t* node_valid = static_ints + so_node_valid * n;
  const int32_t* max_pods = static_ints + so_max_pods * n;

  std::vector<int32_t> min_c(sc);
  std::vector<float> score(n);
  std::vector<uint8_t> feas(n);

  for (int32_t bi = 0; bi < b; ++bi) {
    const int32_t* row = pod_ints + (int64_t)bi * c_cols;
    const float* pref_w = pod_floats + (int64_t)bi * (t > 0 ? t : 1);
    const bool pod_valid = row[c_valid] != 0;
    if (!pod_valid) {  // padding rows: no feasible node, no state change
      assignments[bi] = -1;
      continue;
    }
    const int32_t profile = row[c_profile];
    const int32_t* masks = static_ints + (so_masks + profile) * n;
    const float* static_score = static_f32s + (int64_t)profile * n;

    // per-constraint min count over the profile's eligible domain
    for (int32_t sci = 0; sci < sc; ++sci) {
      const int32_t* dom =
          static_ints + (so_sc_domain + (int64_t)profile * sc + sci) * n;
      const int32_t* counts = state + (do_sc_counts + sci) * n;
      int32_t m = kBig;
      bool any = false;
      for (int64_t i = 0; i < n; ++i) {
        if (dom[i] && counts[i] < m) { m = counts[i]; any = true; }
      }
      min_c[sci] = any ? m : 0;
    }

    // shared-volume reference (sv epochs only)
    const bool sv_shared = sv > 0 && row[c_sv] < sv;
    const int32_t sv_slot = sv_shared ? row[c_sv] : 0;
    const int32_t sv_col = sv_shared ? row[c_sv + 1] : 0;
    const int32_t* sv_att =
        sv_shared ? state + (do_sv + sv_slot) * n : nullptr;

    // affinity batch-level predicates (match _step's first-pod rule)
    bool has_aff = false, no_any = true, self_all = true;
    for (int32_t ti = 0; ti < t; ++ti) {
      if (row[c_own_aff + ti]) {
        has_aff = true;
        if (totals[ti] != 0) no_any = false;
        if (!row[c_match_by + ti]) self_all = false;
      }
    }

    // ---- per-node feasibility + score ------------------------------
    for (int64_t i = 0; i < n; ++i) {
      bool ok = pod_valid && node_valid[i] && masks[i] &&
                state[do_pod_count * n + i] < max_pods[i];
      for (int32_t ri = 0; ok && ri < r; ++ri) {
        ok = state[(do_requested + ri) * n + i] + row[c_req + ri] <=
             static_ints[(so_alloc + ri) * n + i];
      }
      if (ok && sv_shared) {
        const int32_t demand = 1 - sv_att[i];
        ok = state[(do_requested + sv_col) * n + i] +
                 row[c_req + sv_col] + demand <=
             static_ints[(so_alloc + sv_col) * n + i];
      }
      if (ok) {
        for (int32_t sci = 0; sci < sc; ++sci) {
          if (!row[c_pod_sc + sci] || !sc_meta[sc + sci]) continue;  // hard?
          const int32_t code =
              static_ints[(so_sc_codes + sci) * n + i];
          const int32_t cnt = state[(do_sc_counts + sci) * n + i];
          const int32_t skew = cnt + row[c_sc_match + sci] - min_c[sci];
          if (code >= v || skew > sc_meta[sci]) { ok = false; break; }
        }
      }
      bool aff_sat = true;
      if (ok) {
        for (int32_t ti = 0; ti < t; ++ti) {
          const int32_t tcnt = state[(do_term_counts + ti) * n + i];
          const int32_t town = state[(do_term_owners + ti) * n + i];
          if (row[c_match_by + ti] && town > 0) { ok = false; break; }
          if (row[c_own_anti + ti] && tcnt > 0) { ok = false; break; }
          if (row[c_own_aff + ti]) {
            const int32_t code =
                static_ints[(so_term_codes + ti) * n + i];
            if (!(tcnt > 0 && code < v)) aff_sat = false;
          }
        }
      }
      if (ok && has_aff && !aff_sat && !(no_any && self_all)) ok = false;
      feas[i] = ok;
      if (!ok) { score[i] = kNegInf; continue; }

      // scores — same op order as _step for bit-identical f32 results
      const float alloc_cpu =
          (float)(static_ints[so_alloc * n + i] < 1
                      ? 1 : static_ints[so_alloc * n + i]);
      const float alloc_mem =
          (float)(static_ints[(so_alloc + 1) * n + i] < 1
                      ? 1 : static_ints[(so_alloc + 1) * n + i]);
      const float cpu_frac =
          (float)(state[do_nonzero * n + i] + row[c_nonzero]) / alloc_cpu;
      const float mem_frac =
          (float)(state[(do_nonzero + 1) * n + i] + row[c_nonzero + 1]) /
          alloc_mem;
      const bool over = cpu_frac >= 1.0f || mem_frac >= 1.0f;
      const float balanced =
          over ? 0.0f : (1.0f - std::fabs(cpu_frac - mem_frac)) * 100.0f;
      const float least =
          (clip01(1.0f - cpu_frac) + clip01(1.0f - mem_frac)) * 50.0f;
      float soft_counts = 0.0f;
      bool any_soft = false;
      for (int32_t sci = 0; sci < sc; ++sci) {
        if (row[c_pod_sc + sci] && !sc_meta[sc + sci]) {
          soft_counts += (float)state[(do_sc_counts + sci) * n + i];
          any_soft = true;
        }
      }
      const float spread =
          any_soft ? 100.0f / (1.0f + soft_counts) : 0.0f;
      float pref = 0.0f;
      for (int32_t ti = 0; ti < t; ++ti) {
        pref += pref_w[ti] * (float)state[(do_term_counts + ti) * n + i];
      }
      score[i] = weights[0] * balanced + weights[1] * least +
                 weights[2] * spread + weights[3] * pref +
                 weights[4] * static_score[i];
    }

    // argmax, first max wins (== jnp.argmax tie rule)
    float mx = kNegInf;
    int64_t chosen = -1;
    for (int64_t i = 0; i < n; ++i) {
      if (feas[i] && score[i] > mx) { mx = score[i]; chosen = i; }
    }
    const bool found = chosen >= 0;
    assignments[bi] = found ? (int32_t)chosen : -1;
    if (!found || !pod_valid) continue;

    // ---- commit ----------------------------------------------------
    for (int32_t ri = 0; ri < r; ++ri) {
      state[(do_requested + ri) * n + chosen] += row[c_req + ri];
    }
    if (sv_shared) {
      int32_t* att = state + (do_sv + sv_slot) * n;
      state[(do_requested + sv_col) * n + chosen] += 1 - att[chosen];
      att[chosen] = 1;
    }
    state[do_nonzero * n + chosen] += row[c_nonzero];
    state[(do_nonzero + 1) * n + chosen] += row[c_nonzero + 1];
    state[do_pod_count * n + chosen] += 1;
    for (int32_t sci = 0; sci < sc; ++sci) {
      if (!row[c_sc_match + sci]) continue;
      const int32_t* codes = static_ints + (so_sc_codes + sci) * n;
      const int32_t code_j = codes[chosen];
      int32_t* counts = state + (do_sc_counts + sci) * n;
      for (int64_t i = 0; i < n; ++i) {
        if (codes[i] == code_j) counts[i] += 1;
      }
    }
    for (int32_t ti = 0; ti < t; ++ti) {
      const bool matched = row[c_match_by + ti];
      const bool own_anti = row[c_own_anti + ti];
      if (!matched && !own_anti) continue;
      const int32_t* codes = static_ints + (so_term_codes + ti) * n;
      const int32_t code_j = codes[chosen];
      int32_t* counts = state + (do_term_counts + ti) * n;
      int32_t* owners = state + (do_term_owners + ti) * n;
      for (int64_t i = 0; i < n; ++i) {
        if (codes[i] == code_j) {
          if (matched) counts[i] += 1;
          if (own_anti) owners[i] += 1;
        }
      }
      if (matched && code_j < v) totals[ti] += 1;
    }
  }
  return 0;
}

}  // extern "C"
