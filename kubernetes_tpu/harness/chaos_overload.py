"""Overload chaos: multi-tenant abuse shapes against the APF-guarded
REST fabric (the third chaos ring, beside wire faults and node churn).

Each seeded cell runs an in-process apiserver with deliberately SMALL
seat budgets, slowed further by PR 1's FaultGate (seeded latency on
reads, so queues actually form), while:

- aggressor tenant threads mount the cell's overload shape — sustained
  list storms, watch reconnect herds, bulk-verb abuse, or all three at
  once (seat saturation);
- a victim tenant streams pod-creation waves and a REAL scheduler
  (control-plane identity, system priority level) binds them;
- an exempt-route prober hammers ``/healthz`` ``/readyz`` ``/metrics``
  ``/debug/faults`` ``/debug/apf`` throughout.

Invariants checked per cell:

- **zero lost pods**: every victim pod exists and is bound after
  quiescence — aggressors can slow the victim, never starve it;
- **exempt always served**: the exemption envelope held at full
  saturation — no probe was queued/429'd and probe p99 stayed sane;
- **no starved flow**: every aggressor tenant's flow still got
  requests dispatched (fair queuing shares, it does not starve the
  noisy to zero either);
- **per-object rate equivalence**: bulk verbs consumed proportional
  seats (average dispatched width > 1 whenever the cell ran bulk
  abuse) — batching must not launder concurrency through APF;
- **apf engaged** (saturation cells): the workload level actually hit
  its seat capacity — the cell exercised the machinery, not an idle
  server.
"""

from __future__ import annotations

import http.client
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.harness.qos import AGGRESSOR_SHAPES, _aggressor_thread

SCHED_TOKEN = "overload-sched-token"
VICTIM_TOKEN = "overload-victim-token"

OVERLOAD_PROFILES: Dict[str, Dict] = {
    # shapes cycled across aggressor threads; budgets = (readonly,
    # mutating) lane numbers the APF seat shares derive from
    "liststorm": {"shapes": ("liststorm",), "threads": 8,
                  "budgets": (16, 10)},
    "watchherd": {"shapes": ("watchherd",), "threads": 8,
                  "budgets": (16, 10)},
    "bulkabuse": {"shapes": ("bulkabuse",), "threads": 6,
                  "budgets": (16, 10)},
    "saturation": {"shapes": AGGRESSOR_SHAPES, "threads": 12,
                   "budgets": (8, 6)},
    "mixed": {"shapes": AGGRESSOR_SHAPES, "threads": 9,
              "budgets": (16, 10)},
}


def overload_fault_spec(seed: int) -> Dict:
    """Seeded read-latency profile: slow the server's list/get path so
    seat demand outruns capacity and queues form deterministically."""
    return {
        "seed": seed,
        "rules": [
            {"fault": "latency", "verb": "GET", "probability": 0.35,
             "latency": 0.02},
        ],
    }


def _probe_exempt(url: str, token: str, stop: threading.Event,
                  out: Dict, lock: threading.Lock) -> None:
    """Hammer the exemption envelope for the whole cell; every probe
    must be served immediately — never queued, never 429'd."""
    rest = url.split("://", 1)[1]
    host, _, port = rest.partition(":")
    paths = ("/healthz", "/readyz", "/metrics",
             "/debug/faults", "/debug/apf")
    headers = {"Authorization": f"Bearer {token}"}
    conn: Optional[http.client.HTTPConnection] = None
    i = 0
    while not stop.is_set():
        path = paths[i % len(paths)]
        i += 1
        t0 = time.monotonic()
        try:
            if conn is None:
                conn = http.client.HTTPConnection(host, int(port or 80),
                                                  timeout=10)
            conn.request("GET", path, headers=headers)
            resp = conn.getresponse()
            resp.read()
            status = resp.status
        except Exception:  # noqa: BLE001 — transport blip
            status = -1
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None
        elapsed = time.monotonic() - t0
        with lock:
            out["probes"] += 1
            out["max_latency_s"] = max(out["max_latency_s"], elapsed)
            if status != 200:
                out["failures"].append((path, status))
        time.sleep(0.03)


def run_chaos_overload(
    seed: int,
    nodes: int = 12,
    pods: int = 96,
    node_cpu: int = 16,
    tenants: int = 4,
    waves: int = 4,
    overload_profile: str = "mixed",
    wait_timeout: float = 90.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """One seeded overload cell; returns ``{"ok", "invariants",
    "stats"}`` in the chaos-matrix row shape."""
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.client.backoff import RetryBudget
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    def note(msg: str) -> None:
        if progress:
            progress(f"overload[{seed}/{overload_profile}]: {msg}")

    profile = OVERLOAD_PROFILES[overload_profile]
    rng = random.Random(seed)
    tenant_tokens = {f"ovl-tenant-{i}-token": f"ovl-tenant-{i}"
                     for i in range(tenants)}
    tokens = {SCHED_TOKEN: "system:kube-scheduler",
              VICTIM_TOKEN: "qos-victim"}
    tokens.update(tenant_tokens)
    ro, mut = profile["budgets"]
    store = ClusterStore()
    server = APIServer(store=store, tokens=tokens,
                       max_readonly_inflight=ro,
                       max_mutating_inflight=mut).start()
    server.fault_gate.configure(overload_fault_spec(seed))
    fc = server.flowcontrol

    stop = threading.Event()
    agg_stats = {"requests": 0, "throttled": 0}
    agg_lock = threading.Lock()
    probe_stats = {"probes": 0, "max_latency_s": 0.0, "failures": []}
    probe_lock = threading.Lock()
    threads: List[threading.Thread] = []
    sched = None
    invariants: Dict[str, bool] = {}
    failure = ""
    try:
        host, _, port = server.url.split("://", 1)[1].partition(":")
        victim = RestClusterClient(
            server.url, token=VICTIM_TOKEN, watch_kinds=(),
            max_retries=10, retry_after_cap=0.5, retry_seed=seed,
            retry_budget=RetryBudget(budget=128, refill_per_second=16.0))
        sched_client = RestClusterClient(
            server.url, token=SCHED_TOKEN,
            max_retries=10, retry_after_cap=0.5, retry_seed=seed + 1,
            retry_budget=RetryBudget(budget=128, refill_per_second=16.0))
        node_objs = [
            MakeNode().name(f"n{i}").capacity(
                {"cpu": str(node_cpu), "memory": "64Gi", "pods": "110"}
            ).obj()
            for i in range(nodes)
        ]
        code, resp = sched_client._request(
            "POST", "/api/v1/nodes",
            {"kind": "NodeList", "items": node_objs}, charge=nodes)
        if code >= 400:
            raise RuntimeError(f"node create failed: {resp}")
        sched = Scheduler.create(sched_client)
        sched.run()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and sched.cache.node_count() < nodes:
            time.sleep(0.02)

        # aggressors + exempt prober run for the WHOLE victim workload
        shapes = profile["shapes"]
        tenant_list = list(tenant_tokens)
        for i in range(profile["threads"]):
            token = tenant_list[i % len(tenant_list)]
            t = threading.Thread(
                target=_aggressor_thread,
                args=(host, int(port or 80), token,
                      shapes[i % len(shapes)], seed * 100 + i, stop,
                      agg_stats, agg_lock),
                daemon=True, name=f"aggr-{i}")
            t.start()
            threads.append(t)
        prober = threading.Thread(
            target=_probe_exempt,
            args=(server.url, SCHED_TOKEN, stop, probe_stats, probe_lock),
            daemon=True, name="exempt-probe")
        prober.start()
        threads.append(prober)
        note(f"{nodes} nodes up, {profile['threads']} aggressor "
             f"threads over {tenants} tenants armed")

        per_wave = pods // waves
        created = 0
        for w in range(waves):
            count = per_wave if w < waves - 1 else pods - created
            from kubernetes_tpu.api.serialization import to_wire

            # the victim is an ordinary tenant: JSON wire dicts (binary
            # bodies are control-plane-only)
            items = [
                to_wire(MakePod().name(f"v{w}-{i}").uid(f"vu{w}-{i}")
                        .req({"cpu": "250m"}).obj())
                for i in range(count)
            ]
            wave_deadline = time.monotonic() + 60
            while True:
                try:
                    code, resp = victim._request(
                        "POST", "/api/v1/namespaces/default/pods",
                        {"kind": "PodList", "items": items},
                        charge=count, body_binary=False)
                except (OSError, RuntimeError) as e:
                    code, resp = 0, e
                if code == 201 and all(
                        f.get("code") == 409
                        for f in (resp.get("failures") or ())):
                    break
                if time.monotonic() > wave_deadline:
                    raise RuntimeError(f"victim wave {w} failed: {resp}")
                time.sleep(0.1)
            created += count
            time.sleep(rng.uniform(0.0, 0.15))

        deadline = time.monotonic() + wait_timeout
        bound = 0
        while time.monotonic() < deadline:
            pods_live = store.list_pods()
            bound = sum(1 for p in pods_live if p.spec.node_name)
            if len(pods_live) >= created and bound >= created:
                break
            time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)

        snap = fc.snapshot()
        workload = snap["levels"]["workload"]
        system = snap["levels"]["system"]

        invariants["zero_lost_pods"] = bound >= created
        if not invariants["zero_lost_pods"]:
            failure = f"bound {bound}/{created} victim pods"

        with probe_lock:
            probe_fail = list(probe_stats["failures"])
            probe_max = probe_stats["max_latency_s"]
            probes = probe_stats["probes"]
        invariants["exempt_always_served"] = (
            probes > 0 and not probe_fail and probe_max < 2.0)
        if not invariants["exempt_always_served"] and not failure:
            failure = (f"exempt probes failed: {probe_fail[:5]} "
                       f"max_latency={probe_max:.2f}s")

        flows = workload.get("flows", {})

        def flow_of(user: str, key: str) -> bool:
            # flow keys are "user" or "user|flow_id" — exact match only
            # (substring matching would let tenant-10's traffic mask a
            # fully starved tenant-1)
            return key == user or key.startswith(user + "|")

        starved = [u for u in tenant_tokens.values()
                   if not any(flow_of(u, k) and n > 0
                              for k, n in flows.items())]
        invariants["no_starved_flow"] = not starved \
            and any(flow_of("qos-victim", k) for k in flows)
        if not invariants["no_starved_flow"] and not failure:
            failure = f"starved flows: {starved[:4] or 'victim'}"

        if "bulkabuse" in shapes:
            # rate equivalence: 200-item bulk verbs must read as width,
            # not as single-seat requests
            disp = max(1, workload["dispatched_total"])
            avg_width = workload["seats_dispatched_total"] / disp
            invariants["bulk_width_proportional"] = avg_width > 1.02
            if not invariants["bulk_width_proportional"] and not failure:
                failure = f"bulk avg width {avg_width:.3f} (laundered?)"

        if overload_profile == "saturation":
            invariants["apf_engaged"] = (
                workload["peak_executing_seats"] >= workload["capacity"])
            if not invariants["apf_engaged"] and not failure:
                failure = (f"workload level never saturated "
                           f"(peak {workload['peak_executing_seats']}"
                           f"/{workload['capacity']})")
    except Exception as e:  # noqa: BLE001 — a crashed cell is a FAIL row
        invariants["no_crash"] = False
        failure = failure or f"{type(e).__name__}: {e}"
        snap = fc.snapshot() if fc is not None else {}
        workload = (snap.get("levels") or {}).get("workload", {})
        system = (snap.get("levels") or {}).get("system", {})
    finally:
        stop.set()
        if sched is not None:
            sched.stop()
        server.shutdown_server()

    with agg_lock:
        agg_requests = agg_stats["requests"]
        agg_throttled = agg_stats["throttled"]
    rejections = sum((workload.get("rejected") or {}).values()) \
        + sum((system.get("rejected") or {}).values())
    return {
        "seed": seed,
        "profile": overload_profile,
        "ok": bool(invariants) and all(invariants.values()),
        "invariants": invariants,
        "failure": failure,
        "stats": {
            "pods": pods,
            "aggressor_requests": agg_requests,
            "aggressor_throttled": agg_throttled,
            "apf_rejections": rejections,
            "faults_injected": server.fault_gate.injected_total(),
            "exempt_probes": probe_stats["probes"],
            "exempt_probe_max_latency_s": round(
                probe_stats["max_latency_s"], 3),
            "workload_peak_seats": workload.get("peak_executing_seats"),
            "workload_capacity": workload.get("capacity"),
        },
    }
