"""Reusable pending-burst workload generator (factored out of the
per-suite copies that grew around ``harness/workloads.py``): burst N
pods — typically exceeding current capacity — into a store or REST
client, then report time-to-all-bound. One implementation shared by
the autoscaler bench (``harness/elastic.py``), the chaos suites
(``harness/chaos_nodes.py`` waves), and the tests.

Pod shapes come from ``workloads.basic_pod`` (the same template every
benchmark workload builds on) so a burst pod is indistinguishable from
a bench pod; the burst layer only adds naming/uid discipline, the
optional safe-to-evict annotation (so the autoscaler may drain burst
pods during scale-down), and the bound-set wait.

jax-free by design: the REST harness's creator children import this.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from kubernetes_tpu.api.types import FAILED, SUCCEEDED, Pod
# nodegroups is jax-free (api types only), so the shared constant keeps
# this module's jax-free contract
from kubernetes_tpu.autoscaler.nodegroups import SAFE_TO_EVICT_ANNOTATION
from kubernetes_tpu.harness.workloads import basic_pod


def make_burst_pods(
    count: int,
    cpu_milli: int = 500,
    memory: str = "500Mi",
    name_prefix: str = "burst-",
    uid_prefix: str = "bu-",
    offset: int = 0,
    labels: Optional[Dict[str, str]] = None,
    safe_to_evict: bool = False,
    owner_ref: Optional[dict] = None,
    namespaces: Optional[Sequence[str]] = None,
) -> List[Pod]:
    """N plain resource pods named ``{name_prefix}{i}`` for i in
    [offset, offset+count) — the pending-burst shape every elastic
    suite shares. ``namespaces`` spreads the pods round-robin over
    several namespaces (the partitioned control plane shards pods by
    (kind, namespace-hash), so a multi-namespace burst exercises every
    store partition instead of hashing whole into one)."""
    out: List[Pod] = []
    for i in range(offset, offset + count):
        d = basic_pod(i, cpu=f"{cpu_milli}m", memory=memory, labels=labels)
        d["metadata"]["name"] = f"{name_prefix}{i}"
        if namespaces:
            d["metadata"]["namespace"] = namespaces[i % len(namespaces)]
        pod = Pod.from_dict(d)
        pod.metadata.uid = f"{uid_prefix}{i}"
        if safe_to_evict:
            pod.metadata.annotations[SAFE_TO_EVICT_ANNOTATION] = "true"
        if owner_ref is not None:
            pod.metadata.owner_references.append(dict(owner_ref))
        out.append(pod)
    return out


@dataclass
class BurstResult:
    injected: int
    bound: int
    time_to_all_bound: Optional[float]   # None = timed out
    names: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.time_to_all_bound is not None

    @property
    def pods_per_second(self) -> float:
        if not self.time_to_all_bound:
            return 0.0
        return self.injected / self.time_to_all_bound


def sample_percentile(samples: Sequence[float], q: float) -> float:
    """Exact-sample percentile (index ``int(len*q)``, clamped) — THE
    shared copy for harnesses that hold raw samples (the throughput
    collector, the replay engine's arrival→bind latencies). Histogram
    consumers use ``metrics.registry.quantile_from_counts`` instead;
    this lives here because this module is the jax-free harness
    commons the REST children may import."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(int(len(s) * q), len(s) - 1)]


def create_chunk(store, pods: Sequence[Pod]) -> None:
    """Bulk-admit one chunk: the in-process store's one-lock path
    (``create_pods``), the REST bulk verb (``create_objects_bulk``),
    then per-object creates as the last resort."""
    create_bulk = getattr(store, "create_pods", None)
    if create_bulk is not None:
        create_bulk(list(pods))
        return
    bulk_verb = getattr(store, "create_objects_bulk", None)
    if bulk_verb is not None:
        bulk_verb("Pod", list(pods))
        return
    for pod in pods:
        store.create_object("Pod", pod)


def stream_arrivals(
    arrivals,
    send: Callable[[List], None],
    *,
    chunk: int = 512,
    time_scale: float = 1.0,
    flush_window: float = 0.0,
    clock: Callable[[], float] = time.monotonic,
    stop=None,
    on_sent: Optional[Callable[[object, float], None]] = None,
) -> int:
    """THE open-loop arrival-injection loop (one implementation for the
    replay engine, the pre-created burst path, and the REST creator
    children — the ISSUE-13 no-copy-paste contract).

    ``arrivals`` is an iterable of ``(due_s, item)`` pairs ordered by
    ``due_s``; ``send(items)`` delivers one chunk (raises on failure).
    The loop is OPEN-LOOP: an item whose due time has passed is sent
    regardless of what happened to earlier items — nothing here waits
    on binds. ``time_scale`` compresses/stretches the trace clock;
    ``time_scale=0`` collapses every due time to NOW, which reduces the
    loop to today's chunked-burst path exactly (the rate=∞ differential
    guard rides on this). ``flush_window`` coalesces items due within
    the next window into one send (fewer wire round-trips at high
    rates). ``on_sent(item, offset_s)`` stamps each item with its real
    send offset from loop start — the replay engine's arrival clock.
    ``stop`` (threading.Event) aborts between sends. Returns the number
    of items sent."""
    t0 = clock()
    sent = 0
    batch: List = []

    def flush() -> None:
        nonlocal sent, batch
        while batch:
            part, batch = batch[:chunk], batch[chunk:]
            send(part)
            now_off = clock() - t0
            if on_sent is not None:
                for item in part:
                    on_sent(item, now_off)
            sent += len(part)

    for due_s, item in arrivals:
        if stop is not None and stop.is_set():
            break
        due = due_s * time_scale
        while True:
            wait = due - (clock() - t0) - flush_window
            if wait <= 0:
                break
            if batch:
                flush()
            if stop is not None and stop.is_set():
                return sent
            time.sleep(min(wait, 0.05))
        batch.append(item)
        if len(batch) >= chunk:
            flush()
    flush()
    return sent


def count_bound(store, names: Sequence[str]) -> int:
    """Bound-or-terminal count BY NAME: a rescued replacement (same
    name, fresh uid) counts — the chaos suites' lost-pod invariant is
    name-based for exactly this reason."""
    wanted = set(names)
    n = 0
    for pod in store.list_pods():
        if pod.metadata.name not in wanted:
            continue
        if pod.spec.node_name or pod.status.phase in (SUCCEEDED, FAILED):
            n += 1
    return n


def wait_all_bound(
    store, names: Sequence[str], timeout: float,
    poll: float = 0.05,
    progress: Optional[Callable[[str], None]] = None,
) -> Optional[float]:
    """Seconds until every named pod is bound (or terminal); None on
    timeout."""
    t0 = time.monotonic()
    deadline = t0 + timeout
    last_report = 0
    while time.monotonic() < deadline:
        bound = count_bound(store, names)
        if bound >= len(names):
            return time.monotonic() - t0
        if progress and bound - last_report >= max(50, len(names) // 20):
            last_report = bound
            progress(f"burst: {bound}/{len(names)} bound")
        time.sleep(poll)
    return None


def run_pending_burst(
    store, count: int, timeout: float = 120.0,
    progress: Optional[Callable[[str], None]] = None,
    **make_kwargs,
) -> BurstResult:
    """Inject a burst and wait: create ``count`` pods (kwargs forwarded
    to ``make_burst_pods``), then measure time-to-all-bound."""
    pods = make_burst_pods(count, **make_kwargs)
    names = [p.metadata.name for p in pods]
    # the burst path IS the replay loop at rate=∞: every due time
    # collapses to now. chunk=len(pods) keeps this the ONE bulk-admit
    # call (one store lock, one batched watch delivery) the committed
    # rows have always measured — the helper unifies the loop, not
    # the chunking
    stream_arrivals(((0.0, p) for p in pods),
                    lambda chunk_pods: create_chunk(store, chunk_pods),
                    chunk=max(len(pods), 1), time_scale=0.0)
    elapsed = wait_all_bound(store, names, timeout, progress=progress)
    return BurstResult(
        injected=count,
        bound=count_bound(store, names),
        time_to_all_bound=elapsed,
        names=names,
    )
