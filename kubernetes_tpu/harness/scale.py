"""The 10×-tier scale harness: partitioned control plane under kubemark.

Drives the full sharded deployment shape — P apiserver processes (one
store partition each, its own GIL: the Pathways-style sharded
coordinator), a kubemark ``HollowFleet`` registering tens of thousands
of hollow nodes and heartbeating their leases over the fabric, creator
children streaming pods across namespaces (so the (kind,
namespace-hash) partition key spreads them), and M scheduler replicas
in the parent (pod-hash queue sharding + disjoint node pools by
default), each with its own partition-aware client merging one watch
stream per (kind, partition).

The committed ``scale10x`` bench row (bench.py --config scale10x) runs
TWO arms at the same scale — partitions=P vs partitions=1 — plus the
in-process **conflict chaos cell** (replicas deliberately overlapping
with the capacity guard + bind-time ledger arbitrating), and reports:

- aggregate pods/s per arm and the partitioned/single speedup
  ("sharding must pay for itself, not just exist");
- invariants: zero lost pods, zero double-binds (every pod bound
  exactly once, no node oversubscribed — checked against per-partition
  server truth, not client-side optimism), and in the conflict cell
  ``stale_binds_rejected_total`` > 0 with every conflict resolved;
- the PR 8 observability wire-up: every partition server and scheduler
  replica registry federated (``federation_instances`` ≥ partitions +
  replicas), SLO verdicts from the live engine, and a ``shards[...]``
  diag segment.

Child mains here must stay jax-free (harness/__init__ contract): the
scheduler — and so the solver — runs only in the parent.
"""

from __future__ import annotations

import multiprocessing as mp
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.harness.burst import make_burst_pods, stream_arrivals

SCHEDULER_TOKEN = "scale-scheduler-token"
CREATOR_TOKEN = "scale-creator-token"
KUBEMARK_TOKEN = "scale-kubemark-token"

POD_CPU_MILLI = 500
POD_MEMORY = "500Mi"


def scale_namespaces(partitions: int, per_partition: int = 2) -> List[str]:
    """Namespaces whose hashes cover every partition (the partition key
    is (kind, namespace-hash): a single-namespace burst would hash
    whole into one shard). Greedily picks names until each partition
    owns ``per_partition`` of them."""
    if partitions <= 1:
        return ["default"]
    from kubernetes_tpu.apiserver.partition import partition_for

    want = {p: per_partition for p in range(partitions)}
    out: List[str] = []
    i = 0
    while any(v > 0 for v in want.values()) and i < 10_000:
        ns = f"scale-{i}"
        p = partition_for("Pod", ns, None, partitions)
        if want.get(p, 0) > 0:
            want[p] -= 1
            out.append(ns)
        i += 1
    return out


# ---------------------------------------------------------------------------
# child mains (spawned; jax-free)


def _scale_apiserver_main(conn, index: int, count: int,
                          wal_dir: Optional[str]) -> None:
    """One partition of the sharded control plane: a plain ClusterStore
    (partition ``index`` of the keyspace) behind a full APIServer —
    authn, RBAC, admission, APF, watch coalescing all live."""
    from kubernetes_tpu.apiserver.rbac import provision_bootstrap_policy
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.apiserver.wal import attach_wal
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    store = ClusterStore()
    wal = attach_wal(store, wal_dir, snapshot_every=200_000,
                     async_serialize=True) if wal_dir else None
    authz = provision_bootstrap_policy(store)
    authz.add_user_to_group("scale-creator", "system:masters")
    authz.add_user_to_group("scale-kubemark", "system:masters")
    tokens = {SCHEDULER_TOKEN: "system:kube-scheduler",
              CREATOR_TOKEN: "scale-creator",
              KUBEMARK_TOKEN: "scale-kubemark"}
    server = APIServer(store=store, authorizer=authz, tokens=tokens,
                       partition=(index, count)).start()
    conn.send(server.url)
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if msg == "counts":
            # invariant inputs computed CHILD-side (shipping 500k pods
            # to the parent to re-derive them would dwarf the row):
            # per-node requested milli-CPU for pods THIS partition
            # holds, allocatable for nodes it holds — the parent joins
            # across partitions (a pod and its node usually live in
            # different shards).
            pods = store.list_pods()
            node_req: Dict[str, int] = {}
            for p in pods:
                if p.spec.node_name:
                    node_req[p.spec.node_name] = node_req.get(
                        p.spec.node_name, 0) + POD_CPU_MILLI
            node_alloc: Dict[str, int] = {}
            for n in store.list_nodes():
                q = (n.status.allocatable or n.status.capacity or {}).get(
                    "cpu")
                node_alloc[n.name] = int(q.milli_value()) if q is not None \
                    else 1 << 62
            if wal is not None:
                wal.drain()
            conn.send({
                "partition": index,
                "pods_total": len(pods),
                "pods_bound": sum(1 for p in pods if p.spec.node_name),
                "node_req": node_req,
                "node_alloc": node_alloc,
                "nodes": len(node_alloc),
            })
    server.shutdown_server()
    if wal is not None:
        wal.close()
    conn.send("stopped")


def _scale_driver_main(conn, urls: List[str], qps: Optional[float],
                       creator_clients: int) -> None:
    """The kubemark + workload driver child: registers the hollow
    fleet (bulk NodeList posts fanned per partition + ONE shared
    heartbeat thread renewing leases through the lease verb) and
    streams pod bursts through partition-aware creator clients."""
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.kubemark import HollowFleet
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    fleet_client = RestClusterClient(urls[0], partition_urls=urls,
                                     token=KUBEMARK_TOKEN, qps=None)
    fleet = HollowFleet(fleet_client, interval=30.0)
    creators = [RestClusterClient(urls[0], partition_urls=urls,
                                  token=CREATOR_TOKEN, qps=qps)
                for _ in range(max(1, creator_clients))]
    CHUNK = 1024
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        cmd = msg[0]
        if cmd == "nodes":
            _cmd, count, cpu = msg
            try:
                fleet.register(count, cpu=str(cpu), progress=None)
                fleet.start()
            except Exception as e:  # noqa: BLE001 — the parent must
                # see the real registration failure, not an unpack
                # error on the shutdown sentinel
                conn.send(("error", str(e)[:500]))
                continue
            conn.send(("done", count))
        elif cmd == "pods":
            _cmd, count, offset, namespaces = msg
            # shared open-loop injection helper at rate=∞ (lazy
            # per-chunk pod construction: a 500k-pod burst must never
            # materialize at once), per-chunk client rotation. The
            # reported count is the SERVER-CONFIRMED create total —
            # a partial bulk create must not masquerade as complete
            rotation, confirmed = [0], [0]

            def send(items):
                client = creators[rotation[0] % len(creators)]
                rotation[0] += 1
                confirmed[0] += client.create_objects_bulk(
                    "Pod", items)

            def gen():
                for lo in range(0, count, CHUNK):
                    for p in make_burst_pods(
                            min(CHUNK, count - lo),
                            cpu_milli=POD_CPU_MILLI, memory=POD_MEMORY,
                            name_prefix="scale-", uid_prefix="sc-",
                            offset=offset + lo, namespaces=namespaces):
                        yield (0.0, p)

            try:
                stream_arrivals(gen(), send, chunk=CHUNK,
                                time_scale=0.0)
                conn.send(("done", confirmed[0]))
            except Exception as e:  # noqa: BLE001
                conn.send(("error", str(e)[:500]))
    fleet.stop()
    conn.send("stopped")


# ---------------------------------------------------------------------------
# parent-side arms


def _shard_diag(partitions: int, replicas: int, conflicts: int,
                capacity_rejects: int, balance: Optional[float],
                watch_streams: Optional[int]) -> None:
    import sys

    from kubernetes_tpu.harness import diagfmt

    seg = diagfmt.format_shards({
        "partitions": partitions, "replicas": replicas,
        "conflicts": conflicts, "capacity_rejects": capacity_rejects,
        "balance": balance, "watch_streams": watch_streams,
    })
    print(diagfmt.format_diag([seg]), file=sys.stderr, flush=True)


def _conflict_counts() -> Dict[str, float]:
    from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

    return {lbl[0]: v for _, lbl, v
            in fabric_metrics().stale_binds_rejected_total.collect()}


def _conflict_delta(before: Dict[str, float]) -> Dict[str, int]:
    after = _conflict_counts()
    return {k: int(v - before.get(k, 0.0)) for k, v in after.items()
            if v - before.get(k, 0.0) > 0}


def run_scale_arm_rest(
    nodes: int,
    pods: int,
    partitions: int,
    replicas: int = 2,
    use_batch: bool = True,
    max_batch: int = 1024,
    qps: Optional[float] = 5000.0,
    creator_clients: int = 4,
    node_cpu: int = 32,
    shard_nodes: bool = True,
    wal: bool = False,
    wait_timeout: float = 1800.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """One measured arm over the REAL fabric: P apiserver processes, a
    hollow fleet, creator children, M scheduler replicas in-parent."""
    import tempfile

    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.harness.perf import (
        ThroughputCollector,
        attach_slo_baseline,
        collect_freshness,
        reset_sli_window,
    )
    from kubernetes_tpu.observability.devprof import get_devprof
    from kubernetes_tpu.scheduler.replicas import SchedulerReplicaSet

    reset_sli_window()
    get_devprof().reset(workload=f"scale10x/p{partitions}")
    conflicts_before = _conflict_counts()
    ctx = mp.get_context("spawn")
    wal_root = tempfile.mkdtemp(prefix="ktpu-scale-wal-") if wal else None

    servers = []
    urls: List[str] = []
    for i in range(partitions):
        parent_conn, child_conn = ctx.Pipe()
        seg = f"{wal_root}/p{i}" if wal_root else None
        if seg:
            import os

            os.makedirs(seg, exist_ok=True)
        proc = ctx.Process(target=_scale_apiserver_main,
                           args=(child_conn, i, partitions, seg),
                           daemon=True)
        proc.start()
        servers.append((parent_conn, proc))
        urls.append(parent_conn.recv())

    drv_conn, drv_child = ctx.Pipe()
    drv_proc = ctx.Process(target=_scale_driver_main,
                           args=(drv_child, urls, qps, creator_clients),
                           daemon=True)
    drv_proc.start()

    namespaces = scale_namespaces(partitions)
    rs = None   # SchedulerReplicaSet (lazily imported — jax-free module)
    collector = None
    row: Dict = {}

    def teardown() -> None:
        try:
            drv_conn.send("stop")
        except (BrokenPipeError, OSError):
            pass
        for conn, _proc in servers:
            try:
                conn.send("stop")
            except (BrokenPipeError, OSError):
                pass
        for conn, proc in [(drv_conn, drv_proc)] + list(servers):
            try:
                if conn.poll(5.0):
                    conn.recv()
            except (EOFError, OSError):
                pass
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.terminate()
        if wal_root:
            import shutil

            shutil.rmtree(wal_root, ignore_errors=True)

    try:
        # routing sanity: every endpoint must serve the partition index
        # the clients will route to it (shuffled URLs fail HERE, not as
        # silently half-empty shards)
        probe = RestClusterClient(urls[0], partition_urls=urls,
                                  token=SCHEDULER_TOKEN, qps=None)
        probe.check_partition_topology()
        probe._drop_conn()

        # -- kubemark fleet ------------------------------------------
        drv_conn.send(("nodes", nodes, node_cpu))
        status, n = drv_conn.recv()
        if status == "error":
            raise RuntimeError(f"hollow-fleet registration failed: {n}")
        if progress:
            progress(f"scale10x[p{partitions}]: {n} hollow nodes "
                     f"registered")

        # -- scheduler replicas --------------------------------------
        def client_factory(i: int):
            return RestClusterClient(urls[0], partition_urls=urls,
                                     token=SCHEDULER_TOKEN, qps=qps)

        rs = SchedulerReplicaSet(
            client_factory, count=replicas, shard_pods=True,
            shard_nodes=shard_nodes, capacity_guard=not shard_nodes,
            use_batch=use_batch, max_batch=max_batch,
            event_client_factory=client_factory)
        attach_slo_baseline(rs.replicas[0])
        rs.run()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            cached = sum(s.cache.node_count() for s in rs.replicas)
            want = nodes if shard_nodes else nodes * replicas
            if cached >= want:
                break
            time.sleep(0.1)
        if progress:
            progress(f"scale10x[p{partitions}]: replica caches warm "
                     f"({[s.cache.node_count() for s in rs.replicas]})")
        if use_batch:
            samples = make_burst_pods(8, cpu_milli=POD_CPU_MILLI,
                                      memory=POD_MEMORY,
                                      namespaces=namespaces)
            for bs in rs.batch_schedulers:
                if bs is not None:
                    bs.warmup(sample_pods=samples)

        # -- measured burst ------------------------------------------
        collector = ThroughputCollector(count_fn=rs.bound_count)
        collector.start()
        t0 = time.monotonic()
        drv_conn.send(("pods", pods, 0, namespaces))
        done = False
        deadline = time.monotonic() + wait_timeout
        created = None
        last_note = 0.0
        while time.monotonic() < deadline:
            if created is None and drv_conn.poll(0.0):
                status, created = drv_conn.recv()
                if status == "error":
                    raise RuntimeError(f"creator failed: {created}")
            bound = rs.bound_count()
            if bound >= pods:
                done = True
                break
            if progress and time.monotonic() - last_note > 10:
                last_note = time.monotonic()
                progress(f"scale10x[p{partitions}]: {bound}/{pods} bound")
            time.sleep(0.2)
        if not done:
            raise TimeoutError(
                f"scale10x[p{partitions}]: bound {rs.bound_count()}"
                f"/{pods} before deadline")
        rs.flush()
        elapsed = time.monotonic() - t0
        collector.stop()

        # -- server truth + invariants -------------------------------
        node_alloc: Dict[str, int] = {}
        node_req: Dict[str, int] = {}
        pods_bound = pods_total = 0
        part_pods: List[int] = []
        for conn, _proc in servers:
            conn.send("counts")
            counts = conn.recv()
            pods_bound += counts["pods_bound"]
            pods_total += counts["pods_total"]
            part_pods.append(counts["pods_total"])
            node_alloc.update(counts["node_alloc"])
            for name, req in counts["node_req"].items():
                node_req[name] = node_req.get(name, 0) + req
        oversubscribed = sum(
            1 for name, req in node_req.items()
            if req > node_alloc.get(name, 1 << 62))
        # double-binds checked against server truth, two ways: a pod
        # bound to two nodes within one store is impossible (one key),
        # so the cross-partition failure mode is a DUPLICATED pod (a
        # misroute landing one logical pod in two shards — totals then
        # exceed the distinct names created) plus node oversubscription
        dup_pods = max(0, pods_total - pods)
        double_binds = oversubscribed + dup_pods
        conflicts = _conflict_delta(conflicts_before)

        # -- federation: every partition server + replica registry ---
        from kubernetes_tpu.metrics import default_registry
        from kubernetes_tpu.metrics.federation import metrics_federation

        fed = metrics_federation()
        for i, url in enumerate(urls):
            fed.forget_instance(f"apiserver-p{i}")
            try:
                fed.scrape(url, instance=f"apiserver-p{i}",
                           token=SCHEDULER_TOKEN, fold=True)
            except Exception:  # noqa: BLE001 — best-effort per child
                pass
        for i, sched in enumerate(rs.replicas):
            fed.forget_instance(f"scheduler-{i}")
            fed.absorb_registry(sched.metrics.registry,
                                instance=f"scheduler-{i}")
        fed.forget_instance("scheduler")
        fed.absorb_registry(default_registry(), instance="scheduler")
        federation_instances = sorted(fed.instances())

        p99_ms = max(
            s.metrics.e2e_scheduling_duration.quantile(
                0.99, "scheduled") * 1000
            for s in rs.replicas)
        balance = (min(part_pods) / max(part_pods)) \
            if part_pods and max(part_pods) else None
        watch_streams = sum(len(s.client._watch_threads)
                            for s in rs.replicas)
        _shard_diag(partitions, replicas,
                    sum(v for k, v in conflicts.items()
                        if k != "capacity"),
                    conflicts.get("capacity", 0), balance, watch_streams)
        row = {
            "partitions": partitions,
            "replicas": replicas,
            "nodes": nodes,
            "pods": pods,
            "pods_per_sec": round(pods / elapsed, 1) if elapsed else 0.0,
            "time_to_all_bound_s": round(elapsed, 1),
            "p99_latency_ms": round(p99_ms),
            "throughput": collector.summary(),
            "server_pods_bound": pods_bound,
            "server_pods_total": pods_total,
            "lost_pods": max(0, pods - pods_bound),
            "double_binds": double_binds,
            "oversubscribed_nodes": oversubscribed,
            "duplicated_pods": dup_pods,
            "conflicts": conflicts,
            "partition_balance": round(balance, 3)
            if balance is not None else None,
            "watch_streams": watch_streams,
            "federation_instances": federation_instances,
            "freshness": collect_freshness(),
        }
        if pods_bound < pods:
            raise RuntimeError(
                f"store truth disagrees: servers bound {pods_bound} "
                f"< expected {pods}")
        return row
    finally:
        if collector is not None:
            collector.stop()
        if rs is not None:
            rs.stop()
        teardown()


def run_scale_arm_inproc(
    nodes: int,
    pods: int,
    partitions: int,
    replicas: int = 2,
    use_batch: bool = False,
    max_batch: int = 512,
    node_cpu: int = 32,
    shard_pods: bool = True,
    shard_nodes: bool = True,
    capacity_guard: Optional[bool] = None,
    wait_timeout: float = 300.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """The in-process arm: a ``PartitionedStore`` (per-partition async
    watch dispatch + bind-time capacity ledger) under a hollow fleet
    and M replicas — the tier-1-fast mini-scale shape, and the
    conflict chaos cell's substrate (``shard_pods=False`` makes every
    replica race on every pod on purpose)."""
    from kubernetes_tpu.apiserver.partition import PartitionedStore
    from kubernetes_tpu.harness.perf import (
        collect_freshness,
        reset_sli_window,
    )
    from kubernetes_tpu.kubemark import HollowFleet
    from kubernetes_tpu.scheduler.replicas import SchedulerReplicaSet

    reset_sli_window()
    conflicts_before = _conflict_counts()
    if capacity_guard is None:
        capacity_guard = not shard_nodes
    store = PartitionedStore(partitions, async_dispatch=partitions > 1,
                             capacity_guard=capacity_guard)
    namespaces = scale_namespaces(partitions)
    fleet = HollowFleet(store, interval=30.0)
    fleet.register(nodes, cpu=str(node_cpu))
    fleet.start()
    rs = SchedulerReplicaSet(
        lambda i: store, count=replicas, shard_pods=shard_pods,
        shard_nodes=shard_nodes, capacity_guard=capacity_guard,
        use_batch=use_batch, max_batch=max_batch)
    rs.run()
    t0 = time.monotonic()
    try:
        burst = make_burst_pods(pods, cpu_milli=POD_CPU_MILLI,
                                memory=POD_MEMORY, name_prefix="scale-",
                                uid_prefix="sc-", namespaces=namespaces)
        store.create_pods(burst)
        deadline = time.monotonic() + wait_timeout
        while time.monotonic() < deadline:
            bound = sum(1 for p in store.list_pods() if p.spec.node_name)
            if bound >= pods and rs.pending_count() == 0:
                break
            time.sleep(0.05)
        rs.flush()
        store.drain()
        elapsed = time.monotonic() - t0

        all_pods = store.list_pods()
        bound = [p for p in all_pods if p.spec.node_name]
        node_req: Dict[str, int] = {}
        for p in bound:
            node_req[p.spec.node_name] = node_req.get(
                p.spec.node_name, 0) + POD_CPU_MILLI
        oversubscribed = sum(
            1 for name, req in node_req.items()
            if req > node_cpu * 1000)
        conflicts = _conflict_delta(conflicts_before)

        # federation: absorb every partition's registry + replicas
        from kubernetes_tpu.metrics.federation import metrics_federation

        fed = metrics_federation()
        for i, reg in enumerate(store.partition_registries()):
            fed.forget_instance(f"partition-{i}")
            fed.absorb_registry(reg, instance=f"partition-{i}")
        for i, sched in enumerate(rs.replicas):
            fed.forget_instance(f"scheduler-{i}")
            fed.absorb_registry(sched.metrics.registry,
                                instance=f"scheduler-{i}")
        federation_instances = sorted(fed.instances())

        part_pods = [len(p.list_pods()) for p in store.parts]
        balance = (min(part_pods) / max(part_pods)) \
            if max(part_pods) else None
        _shard_diag(partitions, replicas,
                    sum(v for k, v in conflicts.items()
                        if k != "capacity"),
                    conflicts.get("capacity", 0), balance, None)
        return {
            "partitions": partitions,
            "replicas": replicas,
            "nodes": nodes,
            "pods": pods,
            "pods_per_sec": round(pods / elapsed, 1) if elapsed else 0.0,
            "time_to_all_bound_s": round(elapsed, 1),
            "bound": len(bound),
            "lost_pods": max(0, pods - len(bound)),
            "double_binds": oversubscribed,
            "oversubscribed_nodes": oversubscribed,
            "conflicts": conflicts,
            "partition_balance": round(balance, 3)
            if balance is not None else None,
            "federation_instances": federation_instances,
            "freshness": collect_freshness(),
        }
    finally:
        rs.stop()
        fleet.stop()
        store.stop()


def run_conflict_cell(nodes: int = 10, pods: int = 38,
                      partitions: int = 2, replicas: int = 2,
                      node_cpu: int = 2,
                      progress: Optional[Callable[[str], None]] = None
                      ) -> Dict:
    """The conflict chaos cell: replicas with OVERLAPPING
    responsibility (no pod-hash sharding, shared node pool) over a
    tight cluster — every pod is raced by every brain, so the bind CAS
    + capacity guards must arbitrate constantly. Invariants: every pod
    bound exactly once, zero oversubscription, and conflicts actually
    occurred (``stale_binds_rejected_total`` > 0 — a cell that never
    conflicted proved nothing)."""
    cell = run_scale_arm_inproc(
        nodes=nodes, pods=pods, partitions=partitions,
        replicas=replicas, use_batch=False, node_cpu=node_cpu,
        shard_pods=False, shard_nodes=False, capacity_guard=True,
        wait_timeout=120.0, progress=progress)
    cell["conflicts_total"] = sum(cell["conflicts"].values())
    cell["ok"] = (cell["lost_pods"] == 0 and cell["double_binds"] == 0
                  and cell["conflicts_total"] > 0)
    return cell


def run_scale10x_row(
    nodes: int = 50_000,
    pods: int = 500_000,
    partitions: int = 4,
    replicas: int = 2,
    use_batch: bool = True,
    max_batch: int = 1024,
    qps: Optional[float] = 5000.0,
    node_cpu: int = 32,
    wait_timeout: float = 2400.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """The committed bench row: partitioned arm, single-partition arm
    (same scale — the A/B that shows sharding pays for itself), and
    the conflict chaos cell."""
    arm = run_scale_arm_rest(
        nodes=nodes, pods=pods, partitions=partitions,
        replicas=replicas, use_batch=use_batch, max_batch=max_batch,
        qps=qps, node_cpu=node_cpu, wait_timeout=wait_timeout,
        progress=progress)
    single = run_scale_arm_rest(
        nodes=nodes, pods=pods, partitions=1, replicas=replicas,
        use_batch=use_batch, max_batch=max_batch, qps=qps,
        node_cpu=node_cpu, wait_timeout=wait_timeout, progress=progress)
    cell = run_conflict_cell(progress=progress)
    speedup = (arm["pods_per_sec"] / single["pods_per_sec"]) \
        if single["pods_per_sec"] else 0.0
    row = {
        "metric": (f"pods_scheduled_per_sec[Scale10x {nodes}nodes/"
                   f"{pods}pods, partitioned fabric {partitions}p x "
                   f"{replicas}r]"),
        "value": arm["pods_per_sec"],
        "unit": "pods/s",
        "p99_latency_ms": arm.get("p99_latency_ms", 0),
        "scale": {"nodes": nodes, "pods": pods,
                  "partitions": partitions, "replicas": replicas},
        "ab": {
            "partitioned_pods_per_sec": arm["pods_per_sec"],
            "single_partition_pods_per_sec": single["pods_per_sec"],
            "speedup": round(speedup, 3),
            "sharding_pays": speedup >= 1.0,
        },
        "invariants": {
            "lost_pods": arm["lost_pods"] + single["lost_pods"],
            "double_binds": arm["double_binds"] + single["double_binds"],
        },
        "conflict_cell": {
            "conflicts": cell["conflicts"],
            "conflicts_total": cell["conflicts_total"],
            "lost_pods": cell["lost_pods"],
            "double_binds": cell["double_binds"],
            "ok": cell["ok"],
        },
        "partition_balance": arm.get("partition_balance"),
        "watch_streams": arm.get("watch_streams"),
        "federation_instances": arm.get("federation_instances", []),
        "freshness": arm.get("freshness", {}),
    }
    return row
