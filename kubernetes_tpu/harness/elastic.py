"""Elastic bench harness: the static benchmark world made autoscaled.

The scheduler-perf harness (``harness/perf.py``) measures a FIXED node
set; this one starts the cluster at a fraction of the capacity the
workload needs and lets the cluster autoscaler buy the rest while the
burst is pending — measuring pods/s *through* the scale-up plus
time-to-all-bound (capacity acquisition included), the number an
elastic production cluster actually experiences.

Wiring per run: in-process store, scheduler on the TPU batch path,
``ClusterAutoscaler`` with queue introspection, and the
``SimulatedProvisioner`` registering real Node objects after the
configured boot latency. The burst comes from the shared generator
(``harness/burst.py``).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.harness.burst import run_pending_burst


def run_autoscale_bench(
    burst: int = 1000,
    pod_cpu_milli: int = 500,
    pod_memory: str = "500Mi",
    node_cpu: int = 16,
    node_memory: str = "64Gi",
    initial_fraction: float = 0.2,
    boot_latency: float = 0.0,
    use_batch: bool = True,
    max_batch: int = 1024,
    expander: str = "least-waste",
    scale_down: bool = False,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """One elastic run: cluster at ``initial_fraction`` of needed
    capacity, burst to ``burst`` pods, autoscaler fills the gap.
    Returns a BENCH-JSON-shaped row."""
    from kubernetes_tpu.autoscaler import (
        ClusterAutoscaler,
        NodeGroup,
        NodeGroupRegistry,
    )
    from kubernetes_tpu.client.informers import SharedInformerFactory
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler

    def note(msg: str) -> None:
        if progress:
            progress(f"elastic: {msg}")

    # capacity per node is the tighter of cpu fit and the template's
    # 110-pod cap (at high node_cpu the pod cap binds first); +2 slack
    # keeps the max-size cap out of the way of estimator rounding
    per_node = max(1, min(node_cpu * 1000 // pod_cpu_milli, 110))
    needed = max(1, math.ceil(burst / per_node))
    initial = max(1, math.ceil(initial_fraction * needed))
    store = ClusterStore()
    registry = NodeGroupRegistry()
    group = registry.add(NodeGroup(
        "ng-elastic", cpu=str(node_cpu), memory=node_memory,
        min_size=initial, max_size=needed + 2,
        boot_latency=boot_latency,
    ))
    for i in range(initial):
        store.add_node(group.node_template(i))

    factory = SharedInformerFactory(store)
    ca = ClusterAutoscaler(store, factory, registry=registry)
    ca.RESYNC_SECONDS = 0.1
    ca.scale_up_cooldown = 0.5
    ca.expander = expander
    # cover the whole gap in few rounds (cooldown-paced) even at bench
    # scale; the what-if still pays one solve per round, not per pod
    ca.max_virtual_per_group = min(256, needed + 2)
    ca.scale_down_enabled = scale_down

    gates = FeatureGates({"TPUBatchScheduler": use_batch})
    sched = Scheduler.create(store, feature_gates=gates)
    bs = attach_batch_scheduler(sched, max_batch=max_batch) \
        if use_batch else None
    ca.queue_introspect = sched.queue

    result = None
    try:
        sched.run()
        factory.start()
        factory.wait_for_cache_sync()
        ca.run()
        if bs is not None:
            from kubernetes_tpu.harness.burst import make_burst_pods

            warm = bs.warmup(sample_pods=make_burst_pods(
                min(64, burst), cpu_milli=pod_cpu_milli,
                memory=pod_memory, name_prefix="warm-", uid_prefix="w-"))
            if warm > 0.05:
                note(f"solver warmup {warm:.1f}s")
        note(f"{initial}/{needed} nodes up, bursting {burst} pods "
             f"(boot latency {boot_latency}s)")
        result = run_pending_burst(
            store, burst, timeout=wait_timeout,
            cpu_milli=pod_cpu_milli, memory=pod_memory,
            name_prefix="eb-", uid_prefix="ebu-", safe_to_evict=True,
            progress=progress,
        )
        note(f"{result.bound}/{burst} bound, "
             f"t={result.time_to_all_bound}")
    finally:
        ca.stop()
        sched.stop()
        factory.stop()

    final_nodes = len(store.list_nodes())
    row = {
        "metric": (
            f"pods_scheduled_per_sec[autoscale {initial}->{final_nodes}"
            f"nodes/{burst}pods, boot {boot_latency}s, "
            f"{'TPU batch' if use_batch else 'serial'} path]"
        ),
        "value": round(result.pods_per_second, 1) if result else 0.0,
        "unit": "pods/s",
        "time_to_all_bound_s": (
            round(result.time_to_all_bound, 2)
            if result and result.time_to_all_bound is not None else None
        ),
        "bound": result.bound if result else 0,
        "nodes_start": initial,
        "nodes_end": final_nodes,
        "scaleup_decisions": ca.scale_up_events,
        "nodes_provisioned": ca.provisioner.provisioned_total,
        "whatif_solves": ca.whatif_solves,
        "expander": expander,
    }
    if result and result.time_to_all_bound is None:
        row["error"] = f"timeout: {result.bound}/{burst} bound"
    return row


def run_scale_cell(
    burst: int, boot_latency: float, repeats: int = 2,
    wait_timeout: float = 120.0,
    progress: Optional[Callable[[str], None]] = None,
    **kwargs,
) -> Dict:
    """One chaos-matrix ``scale`` suite cell: ``repeats`` independent
    elastic runs at (burst size × boot latency); reports the worst
    (p99-for-small-N = max) time-to-capacity across runs."""
    samples: List[float] = []
    rows = []
    failure = ""
    for r in range(repeats):
        row = run_autoscale_bench(
            burst=burst, boot_latency=boot_latency,
            wait_timeout=wait_timeout, progress=progress, **kwargs)
        rows.append(row)
        if row.get("time_to_all_bound_s") is None:
            failure = row.get("error", "timeout")
        else:
            samples.append(row["time_to_all_bound_s"])
    ok = len(samples) == repeats
    return {
        "ok": ok,
        "failure": failure,
        "burst": burst,
        "boot_latency": boot_latency,
        "stats": {
            "runs": repeats,
            "time_to_capacity_p99_s": max(samples) if samples else None,
            "time_to_capacity_p50_s": (
                sorted(samples)[(len(samples) - 1) // 2]
                if samples else None),
            "pods_per_s_min": min(
                (r["value"] for r in rows), default=0.0),
            "scaleup_decisions": sum(
                r["scaleup_decisions"] for r in rows),
            "nodes_provisioned": sum(
                r["nodes_provisioned"] for r in rows),
        },
    }
