"""Node-churn chaos: the node-death half of the chaos ring.

PR 1's ``chaos_rest`` attacks the WIRE (resets, pushback, apiserver
SIGKILL); this harness attacks the NODES the batched scheduling path
solves over (reference ``test/e2e/chaosmonkey`` + the nodelifecycle
suites): while a workload streams in over REST, a seeded injector stops
node heartbeats, deletes and later recreates nodes (same name — the
flap re-registration path), flaps Ready conditions, and applies
cordons/taints, all at configurable rates. Meanwhile the REAL control
loops run colocated with the store, exactly like the reference
controller-manager:

- ``NodeLifecycleController`` marks silent nodes NotReady, taints them
  ``node.kubernetes.io/unreachable`` and evicts their pods past the
  eviction grace;
- ``PodGCController`` collects pods orphaned by node deletion;
- the harness's ``PodRescuer`` plays the workload's owning controller:
  every evicted/orphaned workload pod is recreated (fresh uid, same
  name) so it re-enters the scheduling queue, and the eviction → bound
  replacement latency lands in ``pod_rescue_seconds``.

The scheduler under test runs the TPU batch path over REST: batches are
solved against snapshots that go stale mid-cycle by construction, which
is exactly what the commit-time stale-node guards
(``commit_target_flags`` → ``commit_target_stale``) and the session's
node-epoch drift trigger exist for.

Invariants checked after quiescence (churn stopped, cluster healed):

- **no binds into the void**: every bound pod's node exists — the store
  accepts binds to nonexistent nodes, so a single unguarded stale
  commit would leave a permanent violation;
- **no lost pods**: every workload pod name ends Bound (possibly as a
  rescue generation) or terminally failed with a status;
- **no oversubscription** on the surviving nodes;
- **cache == store**: the scheduler's cache converges to store truth
  (same node set, same pod placements, no stuck assumed pods).
"""

from __future__ import annotations

import copy
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from kubernetes_tpu.api.types import (
    FAILED,
    NO_SCHEDULE,
    SUCCEEDED,
    Taint,
)
from kubernetes_tpu.apiserver.store import ADDED, DELETED, MODIFIED

# injected (non-lifecycle) taint the injector applies and removes
CHAOS_TAINT = "chaos.kubernetes.io/injected"


# ---------------------------------------------------------------------------
# churn configuration


@dataclass
class ChurnSpec:
    """Seeded churn schedule. ``action_period`` is the mean pause
    between injector actions; per-action weights pick what happens.
    All recovery delays are drawn from the same seeded rng, so a
    (seed, spec) pair replays the same action sequence."""

    action_period: float = 0.25
    kill_weight: float = 3.0      # delete node (+ heartbeat stop), recreate later
    flap_weight: float = 3.0      # mute heartbeats past grace, then resume
    cordon_weight: float = 2.0    # spec.unschedulable toggle
    taint_weight: float = 2.0     # NoSchedule chaos taint, removed later
    recover_min: float = 0.6      # seconds before a kill/cordon/taint heals
    recover_max: float = 1.8
    flap_extra: float = 0.8       # mute duration past the grace period
    max_dead_fraction: float = 0.34  # capacity guard: never kill/cordon more


CHURN_PROFILES: Dict[str, ChurnSpec] = {
    "mixed": ChurnSpec(),
    "killer": ChurnSpec(kill_weight=6.0, flap_weight=1.0,
                        cordon_weight=1.0, taint_weight=1.0),
    "flappy": ChurnSpec(kill_weight=1.0, flap_weight=6.0,
                        cordon_weight=1.0, taint_weight=1.0,
                        action_period=0.15),
    "gentle": ChurnSpec(action_period=0.6, max_dead_fraction=0.2),
}


# ---------------------------------------------------------------------------
# hollow heartbeats


class HeartbeatPump:
    """The hollow kubelets' lease renewals: one thread heartbeating
    every live node through the lifecycle controller, with a per-node
    mute set the injector flips to simulate kubelet death."""

    def __init__(self, nlc, node_names: List[str], interval: float):
        self._nlc = nlc
        self._interval = interval
        self._lock = threading.Lock()
        self._nodes: Set[str] = set(node_names)
        self._muted: Set[str] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.beat_now()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hollow-heartbeats")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def mute(self, name: str) -> None:
        with self._lock:
            self._muted.add(name)

    def unmute(self, name: str) -> None:
        with self._lock:
            self._muted.discard(name)

    def add_node(self, name: str) -> None:
        """Adopt a node that registered after the pump started (the
        autoscaler's provisioned capacity needs heartbeats like any
        other hollow kubelet, or nodelifecycle taints it at grace)."""
        with self._lock:
            self._nodes.add(name)

    def beat_now(self) -> None:
        with self._lock:
            live = self._nodes - self._muted
        for name in live:
            self._nlc.heartbeat(name)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.beat_now()


# ---------------------------------------------------------------------------
# the eviction → requeue rescue pipeline


class PodRescuer:
    """The workload's owning controller: recreates every deleted
    workload pod (same name, fresh uid) so it re-enters the scheduling
    queue, and measures eviction → replacement-bound latency into
    ``pod_rescue_seconds``. Watches the store directly (in-process
    exactness); recreates over REST (the workload's own admission
    path)."""

    def __init__(self, store, client, name_prefix: str):
        self._store = store
        self._client = client
        self._prefix = name_prefix
        self._lock = threading.Lock()
        # name -> (eviction monotonic time, rescue generation)
        self._pending: Dict[str, float] = {}
        self._generation: Dict[str, int] = {}
        self._active = threading.Event()
        self._handle = None
        self.rescues: List[float] = []   # completed rescue latencies
        self.evictions_seen = 0
        self.recreate_failures = 0

    def start(self) -> None:
        self._active.set()
        self._handle = self._store.watch(self._on_event)

    def stop(self) -> None:
        self._active.clear()
        if self._handle is not None:
            self._handle.stop()
            self._handle = None

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def _on_event(self, event) -> None:
        if event.kind != "Pod" or not self._active.is_set():
            return
        pod = event.obj
        name = pod.metadata.name
        if not name.startswith(self._prefix):
            return
        if event.type == DELETED:
            if pod.status.phase in (SUCCEEDED, FAILED):
                return   # terminal pods stay dead
            with self._lock:
                already = name in self._pending
                if not already:
                    self._pending[name] = time.monotonic()
                    gen = self._generation.get(name, 0) + 1
                    self._generation[name] = gen
                self.evictions_seen += 1
            if not already:
                # recreate OUTSIDE the lock: REST round trip
                threading.Thread(
                    target=self._recreate, args=(pod, name),
                    daemon=True, name=f"rescue-{name}").start()
        elif event.type == MODIFIED and pod.spec.node_name:
            with self._lock:
                t0 = self._pending.pop(name, None)
            if t0 is not None:
                from kubernetes_tpu.metrics.fabric_metrics import (
                    fabric_metrics,
                )

                elapsed = time.monotonic() - t0
                fabric_metrics().pod_rescue_seconds.observe(elapsed)
                with self._lock:
                    self.rescues.append(elapsed)

    def _recreate(self, dead_pod, name: str) -> None:
        from kubernetes_tpu.api.types import shallow_copy

        with self._lock:
            gen = self._generation.get(name, 1)
        fresh = shallow_copy(dead_pod)
        fresh.metadata = copy.copy(dead_pod.metadata)
        fresh.metadata.uid = f"{dead_pod.uid}-r{gen}"
        fresh.metadata.resource_version = ""
        fresh.spec = copy.copy(dead_pod.spec)
        fresh.spec.node_name = ""
        fresh.status = type(dead_pod.status)()
        deadline = time.monotonic() + 30
        while self._active.is_set():
            try:
                self._client.create_object("Pod", fresh)
                return
            except ValueError:
                return   # AlreadyExists: an earlier retry landed
            except Exception:  # noqa: BLE001 — transient wire trouble
                if time.monotonic() > deadline:
                    break
                time.sleep(0.1)
        with self._lock:
            self._pending.pop(name, None)
            self.recreate_failures += 1


class VoidBindWatch:
    """During-churn tripwire for the headline invariant: a bind event
    whose target node was deleted comfortably BEFORE the bind arrived
    (beyond commit→watch-delivery latency) and has not been recreated
    is a bind into the void — exactly what the commit-time stale-node
    guards exist to prevent. The post-quiesce bound-nodes-exist check
    alone can't see these for churn-killed nodes, because quiescence
    recreates them under the same names before the check runs."""

    # tolerance for the legitimate race: a bind committed while the
    # node lived, delivered just after it died
    GRACE_S = 0.25

    def __init__(self, store, name_prefix: str):
        self._store = store
        self._prefix = name_prefix
        self._lock = threading.Lock()
        self._dead_since: Dict[str, float] = {}
        self._handle = None
        self.violations: List[str] = []

    def start(self) -> None:
        self._handle = self._store.watch(self._on_event)

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.stop()
            self._handle = None

    def _on_event(self, event) -> None:
        if event.kind == "Node":
            with self._lock:
                if event.type == DELETED:
                    self._dead_since.setdefault(
                        event.obj.name, time.monotonic())
                else:
                    self._dead_since.pop(event.obj.name, None)
            return
        if event.kind != "Pod" or event.type != MODIFIED:
            return
        pod = event.obj
        if not pod.metadata.name.startswith(self._prefix) or \
                not pod.spec.node_name:
            return
        if event.old_obj is not None and event.old_obj.spec.node_name:
            return   # not a bind transition
        with self._lock:
            died = self._dead_since.get(pod.spec.node_name)
            if died is not None and \
                    time.monotonic() - died > self.GRACE_S:
                self.violations.append(
                    f"{pod.metadata.name} bound to {pod.spec.node_name} "
                    f"{time.monotonic() - died:.2f}s after its deletion")


# ---------------------------------------------------------------------------
# the seeded injector


@dataclass
class _NodeState:
    template: object                 # pristine Node object to recreate from
    dead: bool = False
    cordoned: bool = False
    tainted: bool = False
    heal_at: float = field(default=0.0)
    heal: Optional[str] = None       # pending recovery action


class NodeChurnInjector:
    """Seeded node-churn loop. Each tick draws one action for one node
    from the seeded rng, applies it through the store (the injector
    plays the cloud provider / kubelet process, not an API client), and
    schedules the matching recovery. ``restore_all`` heals the cluster
    for the quiesce phase."""

    def __init__(self, store, pump: HeartbeatPump, spec: ChurnSpec,
                 node_names: List[str], seed: int,
                 grace_period: float,
                 progress: Optional[Callable[[str], None]] = None):
        self._store = store
        self._pump = pump
        self._spec = spec
        self._rng = random.Random(seed)
        self._grace = grace_period
        self._progress = progress
        self._states: Dict[str, _NodeState] = {
            n.name: _NodeState(template=copy.deepcopy(n))
            for n in store.list_nodes() if n.name in set(node_names)
        }
        self._flapping: Dict[str, float] = {}   # name -> unmute at
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.actions: Dict[str, int] = {
            "kill": 0, "recreate": 0, "flap": 0, "cordon": 0,
            "uncordon": 0, "taint": 0, "untaint": 0,
        }

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-churn")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def restore_all(self) -> None:
        """Heal every injected condition (quiesce): recreate dead
        nodes, resume heartbeats, uncordon, strip chaos taints. The
        lifecycle controller clears its own unreachable taints once
        heartbeats resume."""
        for name, st in self._states.items():
            if st.dead:
                self._recreate(name, st)
            if st.cordoned:
                self._uncordon(name, st)
            if st.tainted:
                self._untaint(name, st)
            self._pump.unmute(name)
        self._flapping.clear()
        self._pump.beat_now()

    # -- the loop ------------------------------------------------------
    def _loop(self) -> None:
        spec = self._spec
        while not self._stop.wait(self._rng.uniform(
                0.5 * spec.action_period, 1.5 * spec.action_period)):
            try:
                now = time.monotonic()
                self._heal_due(now)
                self._unmute_due(now)
                self._act(now)
            except Exception:  # noqa: BLE001 — churn must not die mid-run
                import logging

                logging.getLogger(__name__).exception("churn action failed")

    def _heal_due(self, now: float) -> None:
        for name, st in self._states.items():
            if st.heal is not None and now >= st.heal_at:
                heal, st.heal = st.heal, None
                if heal == "recreate":
                    self._recreate(name, st)
                elif heal == "uncordon":
                    self._uncordon(name, st)
                elif heal == "untaint":
                    self._untaint(name, st)

    def _unmute_due(self, now: float) -> None:
        for name, at in list(self._flapping.items()):
            if now >= at:
                del self._flapping[name]
                self._pump.unmute(name)

    def _disabled_count(self) -> int:
        return sum(1 for st in self._states.values()
                   if st.dead or st.cordoned) + len(self._flapping)

    def _act(self, now: float) -> None:
        spec = self._spec
        rng = self._rng
        weights = [("kill", spec.kill_weight), ("flap", spec.flap_weight),
                   ("cordon", spec.cordon_weight),
                   ("taint", spec.taint_weight)]
        total = sum(w for _, w in weights)
        if total <= 0:
            return
        pick = rng.uniform(0, total)
        action = weights[-1][0]
        for name, w in weights:
            if pick < w:
                action = name
                break
            pick -= w
        # capacity guard: disabling actions respect the dead budget
        budget = int(spec.max_dead_fraction * len(self._states))
        candidates = [n for n, st in sorted(self._states.items())
                      if not st.dead and st.heal is None
                      and n not in self._flapping]
        if not candidates:
            return
        target = rng.choice(candidates)
        st = self._states[target]
        heal_delay = rng.uniform(spec.recover_min, spec.recover_max)
        if action == "kill" and self._disabled_count() < budget:
            self._pump.mute(target)
            self._store.delete_node(target)
            st.dead = True
            st.heal = "recreate"
            st.heal_at = now + heal_delay
            self.actions["kill"] += 1
            self._note(f"kill {target} (recreate in {heal_delay:.2f}s)")
        elif action == "flap" and self._disabled_count() < budget:
            self._pump.mute(target)
            self._flapping[target] = now + self._grace + spec.flap_extra
            self.actions["flap"] += 1
            self._note(f"flap {target}")
        elif action == "cordon" and not st.cordoned \
                and self._disabled_count() < budget:
            node = copy.deepcopy(self._store.get_node(target))
            if node is None:
                return
            node.spec.unschedulable = True
            self._store.update_node(node)
            st.cordoned = True
            st.heal = "uncordon"
            st.heal_at = now + heal_delay
            self.actions["cordon"] += 1
            self._note(f"cordon {target}")
        elif action == "taint" and not st.tainted:
            node = copy.deepcopy(self._store.get_node(target))
            if node is None:
                return
            node.spec.taints = list(node.spec.taints) + [
                Taint(CHAOS_TAINT, "x", NO_SCHEDULE)]
            self._store.update_node(node)
            st.tainted = True
            st.heal = "untaint"
            st.heal_at = now + heal_delay
            self.actions["taint"] += 1
            self._note(f"taint {target}")

    # -- recoveries ----------------------------------------------------
    def _recreate(self, name: str, st: _NodeState) -> None:
        node = copy.deepcopy(st.template)
        node.metadata.resource_version = ""
        try:
            self._store.add_node(node)
        except Exception:  # noqa: BLE001 — e.g. already re-added
            pass
        st.dead = False
        self._pump.unmute(name)
        self.actions["recreate"] += 1
        self._note(f"recreate {name}")

    def _uncordon(self, name: str, st: _NodeState) -> None:
        node = self._store.get_node(name)
        if node is not None:
            node = copy.deepcopy(node)
            node.spec.unschedulable = False
            self._store.update_node(node)
        st.cordoned = False
        self.actions["uncordon"] += 1

    def _untaint(self, name: str, st: _NodeState) -> None:
        node = self._store.get_node(name)
        if node is not None:
            node = copy.deepcopy(node)
            node.spec.taints = [t for t in node.spec.taints
                                if t.key != CHAOS_TAINT]
            self._store.update_node(node)
        st.tainted = False
        self.actions["untaint"] += 1

    def _note(self, msg: str) -> None:
        if self._progress:
            self._progress(f"churn: {msg}")


# ---------------------------------------------------------------------------
# the seeded chaos run


def _cache_matches_store(sched, store) -> Optional[str]:
    """None when the scheduler cache equals store truth; else a short
    divergence description (polled until quiesce timeout)."""
    dump = sched.cache.dump()
    if dump["assumed_pods"]:
        return f"assumed pods linger: {sorted(dump['assumed_pods'])[:4]}"
    cache_nodes = {n for n, info in dump["nodes"].items()
                   if info.node is not None}
    store_nodes = {n.name for n in store.list_nodes()}
    if cache_nodes != store_nodes:
        return (f"node sets differ: cache-only="
                f"{sorted(cache_nodes - store_nodes)[:4]} store-only="
                f"{sorted(store_nodes - cache_nodes)[:4]}")
    cache_placed = {}
    for _name, info in dump["nodes"].items():
        for pi in info.pods:
            pod = pi.pod
            cache_placed[f"{pod.namespace}/{pod.name}"] = \
                pod.spec.node_name
    store_placed = {
        f"{p.namespace}/{p.name}": p.spec.node_name
        for p in store.list_pods() if p.spec.node_name
        and p.status.phase not in (SUCCEEDED, FAILED)
    }
    if cache_placed != store_placed:
        diff = set(cache_placed.items()) ^ set(store_placed.items())
        return f"{len(diff)} placement(s) differ: {sorted(diff)[:4]}"
    return None


def run_chaos_nodes(
    seed: int,
    nodes: int = 16,
    pods: int = 96,
    node_cpu: int = 16,
    pod_cpu_milli: int = 500,
    waves: int = 6,
    churn_profile: str = "mixed",
    use_batch: bool = True,
    max_batch: int = 64,
    grace_period: float = 1.0,
    eviction_grace: float = 0.5,
    heartbeat_interval: float = 0.2,
    wait_timeout: float = 120.0,
    autoscale: bool = False,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """One seeded node-churn run; returns ``{"ok", "invariants",
    "stats"}``. The workload streams in over REST while the injector
    churns nodes; quiescence heals the cluster and the invariants are
    checked against store truth.

    ``autoscale=True`` runs the cluster autoscaler colocated with the
    control plane: when churn-killed capacity leaves workload pods
    unschedulable, the what-if solve buys replacement nodes from an
    ``ng-chaos`` group (scale-down stays off — removing nodes mid-churn
    is the injector's job). The PR 3 invariants must hold unchanged."""
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.client.informers import SharedInformerFactory
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.controllers.nodelifecycle import (
        NodeLifecycleController,
    )
    from kubernetes_tpu.controllers.podgc import PodGCController
    from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler
    from kubernetes_tpu.testing import MakeNode

    def note(msg: str) -> None:
        if progress:
            progress(f"chaos_nodes[{seed}/{churn_profile}]: {msg}")

    rng = random.Random(seed)
    spec = CHURN_PROFILES[churn_profile]
    fm = fabric_metrics()

    def churn_counters() -> Dict[str, float]:
        return {
            "evictions": sum(
                v for _, _, v in fm.node_evictions_total.collect()),
            "stale_rejected": sum(
                v for _, _, v in fm.stale_binds_rejected_total.collect()),
        }

    before = churn_counters()

    store = ClusterStore()
    node_names = [f"cn{i}" for i in range(nodes)]
    for name in node_names:
        store.add_node(
            MakeNode().name(name).capacity(
                {"cpu": str(node_cpu), "memory": "64Gi", "pods": "110"}
            ).obj())

    server = APIServer(store=store).start()
    sched = None
    pump = injector = rescuer = nlc = gc = void_watch = None
    ca = None
    ca_node_watch = None
    factory = None
    invariants: Dict[str, bool] = {}
    failure = ""
    try:
        creator = RestClusterClient(server.url, watch_kinds=())
        sched_client = RestClusterClient(server.url, retry_seed=seed)

        # the colocated control plane (reference controller-manager)
        factory = SharedInformerFactory(store)
        nlc = NodeLifecycleController(store, factory)
        nlc.grace_period = grace_period
        nlc.eviction_grace = eviction_grace
        nlc.monitor_interval = min(0.05, grace_period / 4)
        gc = PodGCController(store, factory)
        gc.RESYNC_SECONDS = 0.25
        if autoscale:
            from kubernetes_tpu.autoscaler import (
                ClusterAutoscaler,
                NodeGroup,
                NodeGroupRegistry,
            )

            registry = NodeGroupRegistry()
            registry.add(NodeGroup(
                "ng-chaos", cpu=str(node_cpu), memory="64Gi",
                min_size=0, max_size=nodes, boot_latency=0.1,
            ))
            ca = ClusterAutoscaler(store, factory, registry=registry)
            ca.RESYNC_SECONDS = 0.1
            ca.scale_up_cooldown = 0.75
            ca.scale_down_enabled = False
        factory.start()
        factory.wait_for_cache_sync()
        nlc.run()
        gc.run()

        pump = HeartbeatPump(nlc, node_names, heartbeat_interval)
        pump.start()
        if ca is not None:
            # provisioned nodes must heartbeat like any hollow kubelet
            def _adopt_autoscaled(event) -> None:
                if event.kind == "Node" and event.type == ADDED \
                        and event.obj.name.startswith("ng-chaos-"):
                    pump.add_node(event.obj.name)

            ca_node_watch = store.watch(_adopt_autoscaled)

        gates = FeatureGates({"TPUBatchScheduler": use_batch})
        sched = Scheduler.create(sched_client, feature_gates=gates)
        bs = attach_batch_scheduler(sched, max_batch=max_batch) \
            if use_batch else None
        if ca is not None:
            ca.queue_introspect = sched.queue
            ca.run()
        sched.run()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                sched.cache.node_count() < nodes:
            time.sleep(0.02)

        rescuer = PodRescuer(store, creator, name_prefix="cp-")
        rescuer.start()
        void_watch = VoidBindWatch(store, name_prefix="cp-")
        void_watch.start()
        injector = NodeChurnInjector(store, pump, spec, node_names,
                                     seed, grace_period, progress=note)
        injector.start()
        note(f"{nodes} nodes up, churn running")

        # the workload, over REST, interleaved with the churn — waves
        # of the shared pending-burst generator (harness/burst.py)
        from kubernetes_tpu.harness.burst import make_burst_pods

        per_wave = pods // waves
        created = 0
        for w in range(waves):
            count = per_wave if w < waves - 1 else pods - created
            items = make_burst_pods(
                count, cpu_milli=pod_cpu_milli,
                name_prefix=f"cp-{w}-", uid_prefix=f"cu{w}-")
            made = creator.create_objects_bulk("Pod", items)
            if made != count:
                raise RuntimeError(
                    f"wave {w} create failed: {made}/{count} created")
            created += count
            time.sleep(rng.uniform(0.1, 0.4))

        # let the churn keep biting while the tail schedules
        time.sleep(2 * grace_period)

        # quiesce: stop the churn, heal the cluster, let the lifecycle
        # controller clear its unreachable taints, then wait for every
        # workload pod to settle
        injector.stop()
        injector.restore_all()
        note("churn stopped, cluster healing")

        deadline = time.monotonic() + wait_timeout

        def settled() -> Optional[str]:
            live = {p.metadata.name: p for p in store.list_pods()
                    if p.metadata.name.startswith("cp-")}
            missing = [f"cp-{w}-{i}"
                       for w in range(waves)
                       for i in range(per_wave if w < waves - 1
                                      else pods - (waves - 1) * per_wave)
                       if f"cp-{w}-{i}" not in live]
            if missing:
                return f"{len(missing)} pods missing ({missing[:4]})"
            unbound = [n for n, p in live.items()
                       if not p.spec.node_name
                       and p.status.phase not in (SUCCEEDED, FAILED)]
            if unbound:
                return f"{len(unbound)} pods unbound ({unbound[:4]})"
            if rescuer.pending():
                return f"{rescuer.pending()} rescues in flight"
            return None

        why = "never polled"
        while time.monotonic() < deadline:
            why = settled()
            if why is None:
                break
            time.sleep(0.25)
        invariants["all_bound_or_terminal"] = why is None
        if why is not None:
            failure = why

        # taints healed: no unreachable leftovers on live nodes
        deadline = time.monotonic() + 30
        leftover = True
        while time.monotonic() < deadline:
            from kubernetes_tpu.controllers.nodelifecycle import (
                UNREACHABLE_TAINT,
            )

            leftover = any(
                t.key in (UNREACHABLE_TAINT, CHAOS_TAINT)
                for n in store.list_nodes() for t in n.spec.taints)
            if not leftover:
                break
            time.sleep(0.1)
        invariants["taints_healed"] = not leftover

        # no binds into the void: every bound pod's node exists at
        # quiesce AND no bind ever targeted a long-dead node during
        # the churn (the final check alone is vacuous for churn-killed
        # nodes — quiescence recreates them under the same names)
        live_nodes = {n.name for n in store.list_nodes()}
        pods_live = [p for p in store.list_pods()
                     if p.metadata.name.startswith("cp-")]
        voided = [p.metadata.name for p in pods_live
                  if p.spec.node_name and p.spec.node_name not in live_nodes]
        voided.extend(void_watch.violations)
        invariants["no_binds_to_dead_nodes"] = not voided
        if voided and not failure:
            failure = f"bound into the void: {voided[:6]}"

        # no oversubscription on surviving nodes
        used: Dict[str, int] = {}
        for p in pods_live:
            if p.spec.node_name and p.status.phase not in (SUCCEEDED,
                                                           FAILED):
                used[p.spec.node_name] = \
                    used.get(p.spec.node_name, 0) + pod_cpu_milli
        node_by_name = {n.name: n for n in store.list_nodes()}
        invariants["no_oversubscription"] = all(
            name in node_by_name
            and milli <= int(node_by_name[name]
                             .status.allocatable["cpu"].milli_value())
            for name, milli in used.items())

        # cache == store convergence
        deadline = time.monotonic() + 30
        diverged = "never polled"
        while time.monotonic() < deadline:
            diverged = _cache_matches_store(sched, store)
            if diverged is None:
                break
            time.sleep(0.25)
        invariants["cache_converged"] = diverged is None
        if diverged is not None and not failure:
            failure = f"cache diverged: {diverged}"
    finally:
        for component in (injector, pump, rescuer, void_watch, nlc, gc,
                          ca, ca_node_watch):
            if component is not None:
                try:
                    component.stop()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        if factory is not None:
            factory.stop()
        if sched is not None:
            sched.stop()
        server.shutdown_server()

    after = churn_counters()
    rescues = sorted(rescuer.rescues) if rescuer is not None else []

    def pct(q: float) -> float:
        if not rescues:
            return 0.0
        return rescues[min(len(rescues) - 1, int(q * len(rescues)))]

    return {
        "seed": seed,
        "profile": churn_profile,
        "ok": all(invariants.values()),
        "invariants": invariants,
        "failure": failure,
        "stats": {
            "pods": pods,
            "churn_actions": dict(injector.actions)
            if injector is not None else {},
            "evictions": after["evictions"] - before["evictions"],
            "stale_binds_rejected": after["stale_rejected"]
            - before["stale_rejected"],
            "rescues": len(rescues),
            "rescue_p50_s": round(pct(0.50), 3),
            "rescue_p99_s": round(pct(0.99), 3),
            "recreate_failures": rescuer.recreate_failures
            if rescuer is not None else 0,
            "session_rebuilds": sched.batch_scheduler.session.rebuilds
            if sched is not None and sched.batch_scheduler is not None
            else 0,
            "autoscaler_scaleups": ca.scale_up_events
            if ca is not None else 0,
            "autoscaler_nodes_added": ca.provisioner.provisioned_total
            if ca is not None else 0,
        },
    }
