"""Shared formatter + parser for the bench ``diag:`` line.

The ``diag:`` line is the per-row solver postmortem every bench run
leaves in its stderr tail (and so in the driver-committed ``BENCH_r*``
artifacts): phase totals, session counters, device-profiler summary,
and the e2e latency histogram. Before this module, bench.py built the
line from hand-rolled f-strings and every consumer (perf trend tools,
tests, humans grepping artifacts) re-derived its own ad-hoc regexes —
which silently diverged the moment a segment changed shape. Now:

- every segment is rendered HERE (``format_*``), so the line has one
  writer;
- ``parse_diag`` round-trips the current format AND the legacy one in
  the committed r01–r05 artifacts (``tools/perf_report.py`` reads both
  to attribute a regression to a phase);
- the e2e bucket text is rendered from the metrics-registry histogram's
  public accessors (``bucket_counts`` + interpolated ``quantile``,
  ``metrics/registry.py``) — the SAME series ``/metrics`` exposes, so
  the diag line and the scrape can never disagree about e2e latency.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# formatters (one writer for every diag segment)


def format_phases(stats: Dict[str, dict]) -> List[str]:
    """Tracer phase stats → ``solve.commit=4.32s/8~p99 540ms`` segments
    (``stats`` is ``Tracer.phase_stats()``)."""
    return [
        f"{phase}={s['total_s']:.2f}s/{s['count']}"
        f"~p99 {s['p99_s'] * 1000:.0f}ms"
        for phase, s in sorted(stats.items())
    ]


def format_hist_segments(hist) -> List[str]:
    """Fallback phase segments from the solver-segment histogram when
    the tracer is off (the A/B's off arm): ``device=1.34s/14``."""
    return [
        f"{labels[0]}={total_sum:.2f}s/{count}"
        for _name, labels, total_sum, count in sorted(hist.collect())
    ]


def format_session(session, chunk: int, max_cycle_s: float,
                   pad_warms: int) -> str:
    """The solver-session counters segment (mirror validity + tuner)."""
    return (f"session[hits={session.incremental_hits} "
            f"rebuilds={session.rebuilds} "
            f"state_only={session.state_only_rebuilds}] "
            f"chunk={chunk} "
            f"max_cycle={max_cycle_s:.2f}s "
            f"pad_warms={pad_warms}")


def format_devprof(summary: dict) -> str:
    """Device-profiler segment from ``DevProfiler.summary()``: compile
    ledger, dispatch-vs-block split, pad waste, transfer volume, and
    the slowest cycle's dominant phase."""
    parts = [
        f"cycles={summary['cycles']}",
        f"compiles={summary['compiles']}",
        f"unexpected={summary['unexpected_compiles']}",
        f"warm={summary['warm_compiles']}",
        f"wait_share={summary['device_wait_share']:.2f}",
        f"pad_waste={summary['pad_waste_pct']:.1f}%",
        f"h2d_mb={summary['h2d_bytes'] / 1e6:.1f}",
        f"d2h_mb={summary['d2h_bytes'] / 1e6:.1f}",
    ]
    mc = summary.get("max_cycle")
    if mc:
        parts.append(f"max_cycle_phase={max_cycle_phase(mc)}")
    if summary.get("donated_bytes"):
        # bytes donated device-resident buffers kept OFF the link this
        # window — printed only when the sharded donation path ran
        parts.append(f"donated_mb={summary['donated_bytes'] / 1e6:.1f}")
    parts.append(f"detector={summary['compile_detector']}")
    return "devprof[" + " ".join(parts) + "]"


def max_cycle_phase(max_cycle: dict) -> str:
    """Which phase made the slowest cycle slow — the first question
    every blown p99 asks. A cycle that compiled answers ``compile``
    regardless of the split (the compile IS the story)."""
    if max_cycle.get("compiles"):
        return "compile"
    phases = {k[:-2]: max_cycle.get(k, 0.0)
              for k in ("encode_s", "dispatch_s", "block_s")}
    return max(phases, key=phases.get) if any(phases.values()) else "none"


def format_slo(evaluation: dict) -> str:
    """The ``slo[...]`` segment from an ``SLOEngine.evaluate()`` dict,
    emitted ONLY when an objective is violated (mirrors the ``apf``
    segment's quiet-row convention — a green row prints nothing).
    Names every violated SLO and carries the worst offender's burn
    rates so a red row is attributable from the line alone."""
    slos = (evaluation or {}).get("slos") or {}
    bad = {n: s for n, s in slos.items() if s.get("violated")}
    if not bad:
        return ""
    worst_name = max(bad, key=lambda n: bad[n].get("burn_fast", 0.0))
    worst = bad[worst_name]
    parts = [
        "violated=" + ",".join(sorted(bad)),
        f"worst={worst_name}",
        f"burn_fast={worst.get('burn_fast', 0.0):.1f}",
        f"burn_slow={worst.get('burn_slow', 0.0):.1f}",
        f"budget={worst.get('budget_remaining_pct', 0.0):.1f}%",
    ]
    alerting = sorted(n for n, s in bad.items() if s.get("alerting"))
    if alerting:
        parts.append("alerting=" + ",".join(alerting))
    return "slo[" + " ".join(parts) + "]"


def format_shards(info: Dict) -> str:
    """The partitioned-control-plane segment: topology (partitions ×
    scheduler replicas), conflict ledger (same-pod CAS losses +
    capacity-guard refusals, all resolved by the stale-commit path),
    and the partition balance ratio (min/max objects per partition —
    1.0 is perfectly even). Emitted by the scale harness whenever the
    row ran sharded; parsed by the generic bracket scan in
    ``parse_diag`` (key ``shards``)."""
    if not info:
        return ""
    parts = [
        f"partitions={int(info.get('partitions', 1))}",
        f"replicas={int(info.get('replicas', 1))}",
        f"conflicts={int(info.get('conflicts', 0))}",
        f"capacity_rejects={int(info.get('capacity_rejects', 0))}",
    ]
    if info.get("balance") is not None:
        parts.append(f"balance={float(info['balance']):.2f}")
    if info.get("watch_streams") is not None:
        parts.append(f"watch_streams={int(info['watch_streams'])}")
    return "shards[" + " ".join(parts) + "]"


def format_mesh(info: Optional[Dict]) -> str:
    """The sharded-solve segment: mesh width (``devices``), node-axis
    shard count (``shards``), and whether the solve donates its state
    buffers (``donated`` 1/0). Emitted by bench rows whenever the
    session's ACTIVE backend is the mesh tier (``TPUBatchScheduler
    .mesh_info``); parsed by the generic bracket scan in ``parse_diag``
    (key ``mesh``) — tools/perf_report.py reads it to attribute a
    devscale regression to mesh shape or a donation regression."""
    if not info:
        return ""
    parts = [
        f"devices={int(info.get('devices', 1))}",
        f"shards={int(info.get('shards', 1))}",
        f"donated={1 if info.get('donated') else 0}",
    ]
    return "mesh[" + " ".join(parts) + "]"


def format_pipeline(info: Optional[Dict]) -> str:
    """The streaming-scheduler segment: pipeline depth (how many
    batches were in flight at once — drain/encode N+1, solve N, commit
    N−1 — max observed over the row) and the overlap share (fraction of
    the in-flight device window hidden under host work; 0.0 = the old
    barrier, 1.0 = the materializer never waited). Emitted by bench
    rows whenever the batch path ran with the pipeline enabled; parsed
    by the generic bracket scan in ``parse_diag`` (key ``pipeline``) —
    tools/perf_report.py reads it to attribute a sustained-arrival
    regression to lost overlap."""
    if not info:
        return ""
    parts = [
        f"depth={int(info.get('depth', 0))}",
        f"overlap={float(info.get('overlap', 0.0)):.2f}",
    ]
    if info.get("cycles") is not None:
        parts.append(f"cycles={int(info['cycles'])}")
    return "pipeline[" + " ".join(parts) + "]"


def format_replay(info: Optional[Dict]) -> str:
    """The trace-replay segment: which family ran, the offered
    open-loop arrival rate, the arrival→bind p99 (the latency a
    submitting user experiences), the preemption ledger, and the gang
    atomicity verdict (``gangs_intact`` 1/0 — 1 also when the trace
    carried no gangs). Emitted by every replay row/cell; parsed by the
    generic bracket scan in ``parse_diag`` (key ``replay``) —
    tools/perf_report.py reads it to gate the ``replay_*`` families."""
    if not info:
        return ""
    parts = [
        f"family={info.get('family', '?')}",
        f"rate={float(info.get('rate', 0.0)):.1f}",
        f"p99_arrival_to_bind="
        f"{float(info.get('p99_arrival_to_bind_ms', 0.0)):.0f}ms",
        f"preempted={int(info.get('preempted', 0))}",
        f"gangs_intact={1 if info.get('gangs_intact', True) else 0}",
    ]
    if info.get("lost") is not None:
        parts.append(f"lost={int(info['lost'])}")
    if info.get("expired") is not None:
        parts.append(f"expired={int(info['expired'])}")
    if info.get("inversions") is not None:
        parts.append(f"inversions={int(info['inversions'])}")
    return "replay[" + " ".join(parts) + "]"


def format_reshard(info: Optional[Dict]) -> str:
    """The elastic-control-plane segment: how many slice migrations the
    row performed (``moves`` — splits, moves, merges, failovers), the
    cumulative freeze-window time (``frozen_ms`` — the bounded
    unavailability the migrations cost), the topology epoch the row
    ended at, and ``lost_watches`` (informer-vs-server-truth delta at
    quiesce — MUST be 0; printed so a red row is attributable from the
    line alone). Emitted by the hotspot bench and reshard chaos cells;
    parsed by the generic bracket scan in ``parse_diag`` (key
    ``reshard``) — tools/perf_report.py reads it to gate the
    ``hotspot`` family."""
    if not info:
        return ""
    parts = [
        f"moves={int(info.get('moves', 0))}",
        f"frozen_ms={float(info.get('frozen_ms', 0.0)):.1f}",
        f"epoch={int(info.get('epoch', 0))}",
        f"lost_watches={int(info.get('lost_watches', 0))}",
    ]
    return "reshard[" + " ".join(parts) + "]"


def format_upgrade(info: Optional[Dict]) -> str:
    """The rolling-upgrade segment: how many processes the roll cycled
    (``rolled`` — partitions plus scheduler replicas, each exactly
    once), the widest per-partition write-freeze window
    (``frozen_ms_max`` — the bounded unavailability any one slice paid
    for its restart), ``reneg`` (codec re-negotiations observed by
    clients riding the seams — proof the mixed-version wire guard was
    exercised, not bypassed), and the two MUST-be-zero counters:
    ``lost`` (lost pods plus lost/duplicated watch events) and
    ``relists`` (relists of slices whose partition did not move).
    Emitted by the upgrade row and the upgrade chaos cells; parsed by
    the generic bracket scan in ``parse_diag`` (key ``upgrade``) —
    tools/perf_report.py reads it to gate the ``upgrade_flags``
    family."""
    if not info:
        return ""
    parts = [
        f"rolled={int(info.get('rolled', 0))}",
        f"frozen_ms_max={float(info.get('frozen_ms_max', 0.0)):.1f}",
        f"reneg={int(info.get('reneg', 0))}",
        f"lost={int(info.get('lost', 0))}",
        f"relists={int(info.get('relists', 0))}",
    ]
    return "upgrade[" + " ".join(parts) + "]"


def format_federation(info: Optional[Dict]) -> str:
    """The federation segment: fleet width (``clusters``), how many
    pods the saturation path steered off their home cluster
    (``spilled``), how many whole-cluster failovers fired
    (``failovers``), the MUST-be-zero fleet-wide ``lost`` counter, and
    the failover ``recovery`` ratio (share of the dead cell's unbound
    pods re-bound on survivors inside the recovery budget; 1.0 when no
    cluster died). Emitted by the federation rows and chaos cells;
    parsed by the generic bracket scan in ``parse_diag`` (key
    ``federation``) — tools/perf_report.py reads it to gate the
    ``federation_flags`` family."""
    if not info:
        return ""
    parts = [
        f"clusters={int(info.get('clusters', 0))}",
        f"spilled={int(info.get('spilled', 0))}",
        f"failovers={int(info.get('failovers', 0))}",
        f"lost={int(info.get('lost', 0))}",
        f"recovery={float(info.get('recovery', 0.0)):.2f}",
    ]
    return "federation[" + " ".join(parts) + "]"


def format_readtier(info: Optional[Dict]) -> str:
    """The read-tier segment: how wide the tier ran (``replicas``),
    how many list+watch streams rode it (``streams``), the worst
    replica's replication-lag p99 (``lag_p99_ms`` — the staleness the
    fence state machine judges against the lag budget), how many fence
    trips fired (``fenced`` — a replica past budget self-severing its
    readers), and ``relists`` (MUST be zero outside a killed or fenced
    process — the watch contract's confinement counter). Emitted by
    the watch-herd rows and the readtier chaos cells; parsed by the
    generic bracket scan in ``parse_diag`` (key ``readtier``) —
    tools/perf_report.py reads it to gate the ``readtier_flags``
    family."""
    if not info:
        return ""
    parts = [
        f"replicas={int(info.get('replicas', 0))}",
        f"streams={int(info.get('streams', 0))}",
        f"lag_p99_ms={float(info.get('lag_p99_ms', 0.0)):.1f}",
        f"fenced={int(info.get('fenced', 0))}",
        f"relists={int(info.get('relists', 0))}",
    ]
    return "readtier[" + " ".join(parts) + "]"


def format_mirror(info: Optional[Dict]) -> str:
    """The device-resident cluster-state segment: how many watch-event
    deltas the mirror scattered into the donated planes (``events``),
    the link cost of those index/value triples (``scatter_mb`` — the
    only per-event h2d the mirror path pays), the surviving per-cycle
    encode share (``encode_share`` — host encode + pack over the phase
    total; the tentpole target is near-zero on sustained rows), and
    ``reseeds`` (journal gaps, inexpressible deltas, or topology churn
    forcing a full host rebuild — a sustained row should show none
    after warmup). Emitted by bench rows whenever the session carries a
    mirror (``KTPU_MIRROR`` on AND a backend with scatter hooks);
    parsed by the generic bracket scan in ``parse_diag`` (key
    ``mirror``) — tools/perf_report.py reads it to gate the
    ``mirror_flags`` family."""
    if not info:
        return ""
    parts = [
        f"events={int(info.get('events', 0))}",
        f"scatter_mb={float(info.get('scatter_mb', 0.0)):.3f}",
    ]
    if info.get("encode_share") is not None:
        parts.append(f"encode_share={float(info['encode_share']):.4f}")
    parts.append(f"reseeds={int(info.get('reseeds', 0))}")
    return "mirror[" + " ".join(parts) + "]"


def format_critpath(info: Optional[Dict]) -> str:
    """The fleet critical-path segment: which phase owns the sampled
    pods' end-to-end latency (``top``/``share``), how much of the
    summed in-flight windows no phase span covers (``unattributed`` —
    the tracing gap, not a scheduling cost), and the worst clock-skew
    bound the cross-process merge carried (``skew_ms`` — how far two
    processes' spans may really be apart). Emitted by bench rows
    whenever the row collected a fleet trace (the ``critical_path``
    sub-object); parsed by the generic bracket scan in ``parse_diag``
    (key ``critpath``) — tools/perf_report.py reads it to gate the
    ``critpath_flags`` family."""
    if not info or not info.get("pods"):
        return ""
    parts = [
        f"top={info.get('top') or 'none'}",
        f"share={float(info.get('top_share', 0.0)):.2f}",
        f"unattributed={float(info.get('unattributed_share', 0.0)):.2f}",
        f"skew_ms={float(info.get('max_skew_ms', 0.0)):.1f}",
    ]
    if info.get("seam_windows"):
        parts.append(f"seams={int(info['seam_windows'])}")
    return "critpath[" + " ".join(parts) + "]"


def format_e2e(hist, label: str = "scheduled") -> List[str]:
    """E2e latency segments rendered from the metrics-registry
    histogram itself: interpolated p99 (``quantile``) plus the legacy
    bucket text (``bucket_counts``) — one series, two renderings."""
    counts = hist.bucket_counts(label)
    if not counts or not any(counts):
        return []
    p99 = hist.quantile(0.99, label)
    edges = list(hist.buckets) + ["inf"]
    nonzero = [f"<={edges[i]}:{c}" for i, c in enumerate(counts) if c]
    return [f"e2e[p99={p99 * 1000:.0f}ms]",
            "e2e_buckets[" + " ".join(nonzero) + "]"]


def format_diag(segments: List[str]) -> str:
    """The full line (bench.py prints this to stderr, indented so the
    driver tail keeps it visually attached to its row)."""
    return "    diag: " + " ".join(s for s in segments if s)


# ---------------------------------------------------------------------------
# parser (handles the current format AND the committed legacy artifacts)

_BRACKET_RE = re.compile(r"(\w+)\[([^\]]*)\]")
_PHASE_RE = re.compile(
    r"([\w.]+)=([0-9.]+)s/(\d+)(?:~p99\s+([0-9.]+)ms)?")
_SCALAR_RE = re.compile(r"([\w.]+)=([^\s\[\]]+)")
_BUCKET_RE = re.compile(r"<=([0-9.a-z]+):(\d+)")


def _coerce(value: str):
    """Numeric coercion with unit stripping (ms/s/%/plain)."""
    for suffix, scale in (("ms", 1.0), ("s", 1.0), ("%", 1.0), ("", 1.0)):
        if suffix and not value.endswith(suffix):
            continue
        body = value[: len(value) - len(suffix)] if suffix else value
        try:
            num = float(body) * scale
            return int(num) if num.is_integer() and "." not in body \
                else num
        except ValueError:
            continue
    return value


def _parse_kv(body: str) -> dict:
    return {k: _coerce(v) for k, v in _SCALAR_RE.findall(body)}


def parse_diag(line: str) -> Optional[dict]:
    """Parse one ``diag:`` line into a structured dict, or None when
    the line is not a diag line. Keys (all optional): ``phases``
    (name → total_s/count/p99_ms), ``session``, ``chunk``,
    ``max_cycle_s``, ``pad_warms``, ``devprof``, ``churn``,
    ``autoscaler``, ``apf``, ``slo``, ``shards``, ``mesh``,
    ``replay``, ``pipeline``, ``e2e_p99_ms``, ``e2e_buckets``
    (upper-edge str → count). Handles both the current diagfmt output
    and the legacy hand-rolled format in committed BENCH_r* tails."""
    marker = "diag:"
    idx = line.find(marker)
    if idx < 0:
        return None
    body = line[idx + len(marker):].strip()
    out: dict = {}
    # bracket segments first (their contents must not leak into the
    # flat phase/scalar scan below)
    for name, inner in _BRACKET_RE.findall(body):
        if name == "e2e_buckets":
            out["e2e_buckets"] = {
                edge: int(c) for edge, c in _BUCKET_RE.findall(inner)
            }
        elif name == "e2e":
            kv = _parse_kv(inner)
            if "p99" in kv:
                out["e2e_p99_ms"] = float(kv["p99"])
        else:
            out[name] = _parse_kv(inner)
    flat = _BRACKET_RE.sub(" ", body)
    phases: dict = {}
    for name, total, count, p99 in _PHASE_RE.findall(flat):
        phases[name] = {"total_s": float(total), "count": int(count)}
        if p99:
            phases[name]["p99_ms"] = float(p99)
    if phases:
        out["phases"] = phases
    flat = _PHASE_RE.sub(" ", flat)
    for key, value in _SCALAR_RE.findall(flat):
        if key in ("chunk", "pad_warms"):
            out[key] = int(float(value))
        elif key == "max_cycle":
            out["max_cycle_s"] = float(value.rstrip("s"))
        elif key == "tracer":
            out["tracer"] = value
    return out or None


def parse_diag_lines(text: str) -> List[dict]:
    """Every diag line in a blob (e.g. a driver-captured stdout tail),
    in order."""
    out = []
    for line in text.splitlines():
        parsed = parse_diag(line)
        if parsed is not None:
            out.append(parsed)
    return out
