"""Rolling-upgrade orchestrator: the fleet never stops serving.

Production fleets are never all one version — upstream Kubernetes
certifies an N/N−1 skew contract and rolls one process at a time. This
harness is that scenario for our control plane: every partition
apiserver AND every scheduler replica restarts exactly once while the
PR 12 replay engine keeps open-loop arrivals flowing, and the roll is
judged by the same invariants the reshard chaos family established —
zero lost pods, zero lost/duplicated watch events, zero relists of
unmoved slices, a single topology epoch at quiesce.

The roll state machine, per partition (make-before-break):

1. **standby** — a replacement process is pre-spawned PAUSED (imports
   paid, not serving), so the serving gap is the WAL restore, never the
   Python spawn.
2. **drain** — the partition's owned slots FREEZE (PR 13 machinery,
   bounded ETA): writers get 429+Retry-After, in-flight mutations
   settle into the synchronous WAL. ``_verify_frozen`` before the cut:
   a drain that outlives its freeze budget ABORTS — unfreeze, old
   process keeps serving, the roll records the abort and retries with a
   doubled budget (the abort-and-rollback contract).
3. **cut** — the old process stops (or is SIGKILLed, in the chaos
   cells: the crash-consistent path restores identically), the standby
   restores the WAL segment and serves at a fresh URL.
4. **reroute** — ``reroute_after_restart`` bumps the topology epoch;
   every elastic client re-points its streams and rides its
   ``CompositeCursor`` across the seam (handoff fetch, never a relist
   of unmoved slices).

Scheduler replicas roll the same way: the replacement replica warms
its informers and queue shard via ``Scheduler.start()`` (the
leader-election standby discipline) while the old replica still binds;
the cut stops the old loop — its in-flight bindings unwind through the
PR 3 unreserve/forget/requeue path — and starts the new loop with a
warm cache.

Mixed-version wire guard: every client stamps the codec version it
speaks (``codec.VERSION_HEADER``); servers pin ``min(server, client)``
and echo it. The roll drives one client pinned to the OLD stamp for
the duration, and every client re-negotiates across each restart seam
(``codec_renegotiations``); a contract violation (``codec_failures``)
fails the row.

``tools/perf_report.py`` gates the committed row (``upgrade_flags``):
lost pods/events, a red SLO verdict, a partition over its freeze
budget, relists of unmoved slices, codec re-negotiation failures, or a
process that did not restart exactly once all fail ``--strict``.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.client.restcluster import RestClusterClient
from kubernetes_tpu.harness.burst import make_burst_pods

UPGRADE_SCENARIOS = ("partitions-first", "schedulers-first",
                     "sigkill-partitions-first",
                     "sigkill-schedulers-first")

UPGRADE_QPS = 5000.0
FREEZE_BUDGET_S = 2.0
P99_ARRIVAL_TO_BIND_BUDGET_MS = 500.0

POD_CPU_MILLI = 100
POD_MEMORY = "50Mi"

SCHEDULER_TOKEN = "upgrade-scheduler-token"
CREATOR_TOKEN = "upgrade-creator-token"


def build_upgrade_trace(seed: int, pods: int, qps: float = UPGRADE_QPS,
                        namespaces: int = 16):
    """Open-loop steady arrivals like the sustained row's trace, but
    fanned across ``namespaces`` tenants round-robin — a single
    namespace is a single hash slot, which would park every pod on one
    partition and the roll would never cross a seam under load."""
    from dataclasses import replace

    from kubernetes_tpu.workloads.trace import generate_trace

    trace = generate_trace(
        seed, pods, pods / qps, family="upgrade",
        name_prefix="up-", cpu_alpha=1.8, cpu_lo=100, cpu_hi=500,
        lifetime_modes=None, burst_factor=1.0, burst_period_s=0.0,
    )
    spread = [f"up-{i}" for i in range(namespaces)]
    trace.events[:] = [
        replace(e, namespace=spread[i % len(spread)])
        for i, e in enumerate(trace.events)]
    return trace


# ---------------------------------------------------------------------------
# spawned partition fleet (real processes, synchronous WAL, standbys)


def _upgrade_apiserver_main(conn, index: int, count: int, wal_dir: str,
                            restore: bool, hold: bool) -> None:
    """Partition server child. ``hold=True`` is the pre-spawned
    standby: imports are paid up front, then the child WAITS — the WAL
    restore must not start while the incumbent still appends. The
    parent's "serve" begins restore+serve; "abort" exits unused."""
    from kubernetes_tpu.apiserver.rbac import provision_bootstrap_policy
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.apiserver.wal import attach_wal, restore_store
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    if hold:
        # imports above are the expensive part of a spawn — pay them
        # BEFORE the roll needs this process, ack readiness, then wait
        conn.send("ready")
        if conn.recv() != "serve":
            return
    store = ClusterStore()
    if restore:
        restore_store(wal_dir, store)
    wal = attach_wal(store, wal_dir, snapshot_every=100_000,
                     async_serialize=False)
    authz = provision_bootstrap_policy(store)
    authz.add_user_to_group("upgrade-creator", "system:masters")
    tokens = {SCHEDULER_TOKEN: "system:kube-scheduler",
              CREATOR_TOKEN: "upgrade-creator"}
    server = APIServer(store=store, authorizer=authz, tokens=tokens,
                       partition=(index, count)).start()
    conn.send(server.url)
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if msg == "quiesce":
            # drain endgame: compact to a snapshot and detach the WAL
            # while the server KEEPS SERVING reads/watches — writes are
            # frozen, so the log is quiet; the standby can now restore
            # this directory (one snapshot load, not a replay) while
            # this process still answers the fleet
            if wal is not None:
                wal.snapshot()
                wal.close()
                wal = None
            conn.send("quiesced")
        elif msg == "counts":
            from kubernetes_tpu.apiserver import codec

            pods = [(p.namespace, p.metadata.name,
                     p.metadata.resource_version,
                     bool(p.spec.node_name))
                    for p in store.list_pods()]
            conn.send({
                "partition": index,
                "pods": pods,
                "nodes": len(store.list_nodes()),
                "codec_version": codec.CODEC_VERSION,
                "epoch": server.partition_topology.epoch
                if server.partition_topology is not None else 0,
            })
    server.shutdown_server()
    if wal is not None:
        wal.close()
    conn.send("stopped")


class _SpawnedFleet:
    """The partition processes and their paused standbys."""

    def __init__(self, count: int, progress: Optional[Callable] = None):
        import multiprocessing as mp

        self.count = count
        self.progress = progress
        self.ctx = mp.get_context("spawn")
        self.wal_root = tempfile.mkdtemp(prefix="ktpu-upgrade-wal-")
        self.children: List[list] = []   # [conn, proc] — per partition
        self.standbys: Dict[int, list] = {}
        self.urls: List[str] = []
        self.restarts = [0] * count

    def _spawn(self, i: int, restore: bool, hold: bool) -> list:
        seg = os.path.join(self.wal_root, f"p{i}")
        os.makedirs(seg, exist_ok=True)
        parent_conn, child_conn = self.ctx.Pipe()
        proc = self.ctx.Process(
            target=_upgrade_apiserver_main,
            args=(child_conn, i, self.count, seg, restore, hold),
            daemon=True)
        proc.start()
        return [parent_conn, proc]

    def start(self) -> List[str]:
        self.children = [self._spawn(i, restore=False, hold=False)
                         for i in range(self.count)]
        self.urls = [conn.recv() for conn, _ in self.children]
        return self.urls

    def prespawn_standbys(self, timeout: float = 60.0) -> None:
        for i in range(self.count):
            self.standbys[i] = self._spawn(i, restore=True, hold=True)
        # wait until every standby has paid its imports and is parked
        # at the serve gate — a not-yet-ready standby would put its
        # spawn cost back inside some partition's freeze window
        for i, (conn, _proc) in self.standbys.items():
            if conn.poll(timeout):
                conn.recv()

    def quiesce(self, i: int) -> None:
        """Snapshot + detach the incumbent's WAL (it keeps serving
        reads; writes are frozen) so the standby's restore is one
        snapshot load off a dead log."""
        conn, _proc = self.children[i]
        conn.send("quiesce")
        if conn.poll(10.0):
            conn.recv()

    def kill(self, i: int) -> None:
        """SIGKILL the incumbent mid-drain — the chaos seam. The WAL
        tail may be torn; the standby's restore must absorb it."""
        _conn, proc = self.children[i]
        proc.kill()
        proc.join(timeout=5.0)

    def promote(self, i: int) -> Tuple[list, str]:
        """Un-pause the standby: it restores the (quiesced or torn)
        WAL directory and serves at a fresh URL. Returns the OLD child
        for ``retire`` — it keeps serving reads until the reroute has
        re-pointed every client."""
        standby = self.standbys.pop(i)
        standby[0].send("serve")
        new_url = standby[0].recv()
        old = self.children[i]
        self.children[i] = standby
        self.urls[i] = new_url
        self.restarts[i] += 1
        return old, new_url

    def retire(self, old: list, killed: bool = False) -> None:
        conn, proc = old
        if not killed and proc.is_alive():
            try:
                conn.send("stop")
                if conn.poll(5.0):
                    conn.recv()
            except (BrokenPipeError, EOFError, OSError):
                pass
        proc.join(timeout=5.0)
        if proc.is_alive():
            proc.terminate()

    def counts(self) -> List[dict]:
        out = []
        for conn, _proc in self.children:
            conn.send("counts")
            out.append(conn.recv())
        return out

    def teardown(self) -> None:
        for extra in self.standbys.values():
            try:
                extra[0].send("abort")
            except (BrokenPipeError, OSError):
                pass
            extra[1].join(timeout=3.0)
            if extra[1].is_alive():
                extra[1].terminate()
        for conn, proc in self.children:
            try:
                conn.send("stop")
            except (BrokenPipeError, OSError):
                pass
        for conn, proc in self.children:
            try:
                if conn.poll(3.0):
                    conn.recv()
            except (EOFError, OSError):
                pass
            proc.join(timeout=3.0)
            if proc.is_alive():
                proc.terminate()
        shutil.rmtree(self.wal_root, ignore_errors=True)


# ---------------------------------------------------------------------------
# scheduler replica fleet (in-process brains over the REST fabric)


def _build_replica(index: int, count: int, client_factory,
                   use_batch: bool, max_batch: int):
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.scheduler.replicas import (
        ReplicaSpec,
        install_replica_sharding,
    )
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    sched = Scheduler.create(
        client_factory(index),
        feature_gates=FeatureGates({"TPUBatchScheduler": use_batch}),
        provider="GangSchedulingProvider")
    install_replica_sharding(sched, ReplicaSpec(
        index=index, count=count, shard_pods=count > 1,
        shard_nodes=False, capacity_guard=count > 1))
    bs = None
    if use_batch:
        from kubernetes_tpu.sidecar import attach_batch_scheduler

        bs = attach_batch_scheduler(sched, max_batch=max_batch)
    return sched, bs


class _ReplicaFleet:
    """M replica brains with a make-before-break roll: the replacement
    warms via ``start()`` (informer replay + queue shard) while the
    incumbent still binds — exactly the leader-election standby
    discipline — then the cut swaps the scheduling loop."""

    def __init__(self, client_factory, count: int,
                 use_batch: bool = True, max_batch: int = 4096,
                 progress: Optional[Callable] = None):
        self.client_factory = client_factory
        self.count = count
        self.use_batch = use_batch
        self.max_batch = max_batch
        self.progress = progress
        self.restarts = [0] * count
        self.retired_bound = 0
        self.replicas = []
        self.batch_schedulers = []
        self._standbys: Dict[int, tuple] = {}
        for j in range(count):
            sched, bs = _build_replica(j, count, client_factory,
                                       use_batch, max_batch)
            self.replicas.append(sched)
            self.batch_schedulers.append(bs)

    def prepare_standbys(self, warm_pods=None) -> None:
        """Build, warm, and SYNC every successor BEFORE the open-loop
        clock starts — the replica half of the prespawned-standby
        discipline. A ``Scheduler.create`` + solver warmup + informer
        list mid-roll monopolizes the interpreter for seconds on a
        small host, and the incumbent's binding loop starving for that
        long reads as a roll-seam latency spike. Successors built here
        run informers (cache + queue shard track the cluster live, the
        hot-standby posture of leader election) but NO binding loop
        until ``roll`` promotes them; their queues self-clean as the
        incumbent's binds land as pod updates."""
        for j in range(self.count):
            if j in self._standbys:
                continue
            new, nbs = _build_replica(j, self.count,
                                      self.client_factory,
                                      self.use_batch, self.max_batch)
            if nbs is not None and warm_pods:
                nbs.warmup(sample_pods=warm_pods)
            new.start()
            self._standbys[j] = (new, nbs)

    def run(self) -> None:
        for sched in self.replicas:
            sched.run()

    def warmup(self, sample_pods) -> None:
        for bs in self.batch_schedulers:
            if bs is not None and sample_pods:
                bs.warmup(sample_pods=sample_pods)

    def _bound_of(self, sched) -> int:
        s = sched.metrics.e2e_scheduling_duration._series.get(
            ("scheduled",))
        return s[2] if s else 0

    def bound_count(self) -> int:
        return self.retired_bound + sum(
            self._bound_of(s) for s in self.replicas)

    def pending_count(self) -> int:
        return sum(s.queue.pending_active_count() for s in self.replicas)

    def cache_nodes(self) -> List[int]:
        return [s.cache.node_count() for s in self.replicas]

    def roll(self, j: int, warm_pods=None,
             warm_timeout: float = 60.0) -> dict:
        t0 = time.monotonic()
        if j in self._standbys:
            # hot standby: informers already live, cache already warm
            new, nbs = self._standbys.pop(j)
        else:
            new, nbs = _build_replica(j, self.count,
                                      self.client_factory,
                                      self.use_batch, self.max_batch)
            if nbs is not None and warm_pods:
                nbs.warmup(sample_pods=warm_pods)
            # standby warm-up: informers + queue shard replay, NO
            # binding
            new.start()
        old = self.replicas[j]
        deadline = time.monotonic() + warm_timeout
        want = old.cache.node_count()
        while time.monotonic() < deadline \
                and new.cache.node_count() < want:
            time.sleep(0.05)
        # cut, make-before-break: the NEW loop starts binding while
        # the old one still runs — the shard never goes dark. The brief
        # overlap is the replica-race the fleet already resolves: bind
        # CAS + capacity guards pick one winner, the loser unwinds
        # through PR 3's unreserve/forget/requeue
        self.replicas[j] = new
        self.batch_schedulers[j] = nbs
        threading.Thread(target=new._loop, daemon=True,
                         name=f"scheduleOne-rolled-{j}").start()
        old.stop()
        try:
            old.wait_for_inflight_bindings(timeout=10.0)
        except Exception:  # noqa: BLE001 — unwound via requeue
            pass
        self.retired_bound += self._bound_of(old)
        old.client._stop_watches()
        old.client._drop_conn()
        self.restarts[j] += 1
        handoff_ms = (time.monotonic() - t0) * 1000.0
        if self.progress:
            self.progress(f"upgrade: replica {j} rolled "
                          f"({handoff_ms:.0f}ms handoff)")
        return {"replica": j, "handoff_ms": round(handoff_ms, 1)}

    def flush(self, timeout: float = 30.0) -> None:
        for sched, bs in zip(self.replicas, self.batch_schedulers):
            if bs is not None:
                bs.flush(timeout=timeout)
            sched.wait_for_inflight_bindings(timeout=timeout)

    def stop(self) -> None:
        for new, _nbs in self._standbys.values():
            try:
                new.client._stop_watches()
                new.client._drop_conn()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._standbys.clear()
        for sched in self.replicas:
            sched.stop()
            try:
                sched.client._stop_watches()
                sched.client._drop_conn()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# ---------------------------------------------------------------------------
# the roll itself


def _roll_one_partition(fleet: _SpawnedFleet, coordinator, i: int,
                        budget_s: float, kill: bool,
                        progress: Optional[Callable],
                        drain_settle_s: float = 0.15) -> dict:
    """Freeze → drain → verify → cut → reroute for one partition.
    Returns the per-partition record (frozen_ms, aborts, killed)."""
    from kubernetes_tpu.apiserver.reshard import ReshardError

    rec = {"partition": i, "aborts": 0, "killed": bool(kill),
           "rolled": False, "frozen_ms": 0.0,
           "freeze_budget_ms": budget_s * 1000.0}
    eta = budget_s
    for attempt in range(2):
        t0 = time.monotonic()
        topo = coordinator.fetch_topology()
        slots = topo.slots_of_partition(i)
        if slots:
            coordinator._freeze({i: slots}, eta)
            time.sleep(drain_settle_s)   # in-flight writes settle into
            # the synchronous WAL under the freeze
            try:
                coordinator._verify_frozen({i: slots})
            except ReshardError:
                # the drain outlived its freeze budget: ABORT — thaw,
                # the incumbent keeps serving, retry with 2× budget
                coordinator._unfreeze({i: slots})
                rec["aborts"] += 1
                eta *= 2.0
                continue
        if kill:
            # the chaos seam: SIGKILL the process CURRENTLY DRAINING —
            # no quiesce, the standby restores a possibly-torn tail
            fleet.kill(i)
        else:
            fleet.quiesce(i)
        old, new_url = fleet.promote(i)
        reroute = coordinator.reroute_after_restart(i, new_url)
        # the write-frozen window ends here: the new process serves
        # unfrozen and every client has been re-pointed
        rec["frozen_ms"] = round((time.monotonic() - t0) * 1000.0, 1)
        rec["rolled"] = True
        # first-class seam span into the fleet timeline: a sampled pod
        # whose queue.wait overlaps this roll window names the roll in
        # its critical path instead of unattributed stall
        try:
            from kubernetes_tpu.observability import get_tracer

            get_tracer().record(
                "upgrade.roll", t0,
                trace=f"seam:{reroute.get('epoch', 0)}",
                partition=i, killed=bool(kill),
                frozen_ms=rec["frozen_ms"])
        except Exception:  # noqa: BLE001 — tracing must not fail a roll
            pass
        if not kill:
            # grace before retiring the read-only incumbent: let every
            # client's topology poll observe the new epoch and replumb
            # its streams, so the old process dies with no stream on it
            time.sleep(0.5)
        fleet.retire(old, killed=kill)
        if progress:
            progress(f"upgrade: partition {i} rolled "
                     f"({'SIGKILL' if kill else 'drained'}, "
                     f"{rec['frozen_ms']:.0f}ms frozen) → {new_url}")
        return rec
    return rec


def _client_counters(clients) -> dict:
    relists = 0
    reneg = 0
    failures = 0
    rv_regressions = 0
    handoffs = 0
    for c in clients:
        relists += sum(c.stream_relists.values())
        reneg += c.codec_renegotiations
        failures += c.codec_failures
        rv_regressions += len(c.rv_regressions)
        handoffs += c.handoff_fetches
    return {"unmoved_relists": relists,
            "codec_renegotiations": reneg,
            "codec_failures": failures,
            "rv_regressions": rv_regressions,
            "handoff_fetches": handoffs}


def run_upgrade_roll(
    *,
    partitions: int = 3,
    replicas: int = 2,
    pods: int = 30_000,
    qps: float = UPGRADE_QPS,
    seed: int = 16,
    scenario: str = "partitions-first",
    node_cpu: int = 32,
    max_batch: int = 4096,
    use_batch: bool = True,
    freeze_budget_s: float = FREEZE_BUDGET_S,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """One full rolling upgrade under open-loop load. Returns the raw
    result surface; ``run_upgrade_row`` shapes the committed row and
    ``run_upgrade_cell`` the chaos verdict."""
    from kubernetes_tpu.api.types import Node
    from kubernetes_tpu.apiserver.partition import PartitionTopology
    from kubernetes_tpu.apiserver.reshard import ReshardCoordinator
    from kubernetes_tpu.harness.chaos_reshard import _Recorder
    from kubernetes_tpu.harness.perf import (
        attach_slo_baseline,
        collect_freshness,
        reset_sli_window,
    )
    from kubernetes_tpu.harness.sustained import sustained_nodes
    from kubernetes_tpu.harness.workloads import node_template
    from kubernetes_tpu.observability.devprof import get_devprof
    from kubernetes_tpu.utils.gctune import tune_for_throughput
    from kubernetes_tpu.workloads.replay import ReplayEngine
    from kubernetes_tpu.workloads.trace import events_to_pods

    if scenario not in UPGRADE_SCENARIOS:
        raise ValueError(f"unknown upgrade scenario {scenario!r} "
                         f"(have: {', '.join(UPGRADE_SCENARIOS)})")
    tune_for_throughput()
    reset_sli_window()
    get_devprof().reset(workload=f"upgrade/{scenario}")
    rng = random.Random(seed)
    trace = build_upgrade_trace(seed, pods, qps)
    node_dicts = sustained_nodes(trace, node_cpu=node_cpu)

    fleet = _SpawnedFleet(partitions, progress=progress)
    urls = fleet.start()
    clients: List[RestClusterClient] = []

    def make_client(token: str, watch_kinds=(), codec_version=None,
                    qps_limit=None) -> RestClusterClient:
        kw = {}
        if codec_version is not None:
            kw["codec_version"] = codec_version
        # max_retries=8: a seam (retire/SIGKILL → promote → reroute →
        # replumb) must fit inside one request's retry envelope — the
        # backoff re-resolves the pool each attempt, so the retries
        # follow the replumb onto the successor process
        c = RestClusterClient(urls[0], partition_urls=list(urls),
                              token=token, qps=qps_limit,
                              watch_kinds=watch_kinds, max_retries=8,
                              **kw)
        assert c.enable_topology(poll_interval=0.2)
        clients.append(c)
        return c

    rfleet = None
    engine = None
    try:
        control = RestClusterClient(urls[0], partition_urls=list(urls),
                                    token=CREATOR_TOKEN)
        clients.append(control)
        coordinator = ReshardCoordinator(control,
                                         freeze_eta=freeze_budget_s,
                                         evict_grace_s=0.05)
        topo = PartitionTopology.default(partitions, urls=urls)
        coordinator.install_topology(topo)
        assert control.enable_topology(poll_interval=0.2)

        nodes = [Node.from_dict(d) for d in node_dicts]
        for lo in range(0, len(nodes), 512):
            control.create_objects_bulk("Node", nodes[lo:lo + 512])
        if progress:
            progress(f"upgrade[{scenario}]: {len(nodes)} nodes across "
                     f"{partitions} partitions, {replicas} replicas, "
                     f"{len(trace.events)} arrivals @ {qps:.0f}/s")

        rfleet = _ReplicaFleet(
            lambda j: make_client(SCHEDULER_TOKEN,
                                  watch_kinds=("Pod", "Node")),
            count=replicas, use_batch=use_batch, max_batch=max_batch,
            progress=progress)
        for sched in rfleet.replicas:
            attach_slo_baseline(sched)
        rfleet.run()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if min(rfleet.cache_nodes()) >= len(nodes):
                break
            time.sleep(0.1)
        samples = events_to_pods(trace.events[:128])
        rfleet.warmup(samples)
        if progress:
            progress(f"upgrade[{scenario}]: replica caches warm "
                     f"{rfleet.cache_nodes()}")

        # the OLD-VERSION witness: pinned to codec v1 for the whole
        # roll — its watch frames arrive in the legacy 3-tuple shape,
        # and every restart seam must still re-pin it explicitly
        recorder = _Recorder()
        v1_client = make_client(CREATOR_TOKEN, watch_kinds=("Pod",),
                                codec_version=1)
        v1_client.watch(lambda e: recorder.on_events([e]),
                        batch_fn=recorder.on_events)

        engine_client = make_client(CREATOR_TOKEN,
                                    watch_kinds=("Pod",))
        engine = ReplayEngine(engine_client, trace, time_scale=1.0,
                              expire=False, progress=progress)

        # pay the standby spawns (process start + imports) BEFORE the
        # open-loop clock starts: a standby importing the world while
        # arrivals stream steals exactly the CPU the injector and
        # binders need, and the backlog it causes reads as roll-seam
        # latency. The standbys hold pre-restore, so spawning early
        # cannot observe a stale WAL — restore begins at "serve".
        # Same discipline for the replica successors: build + solver
        # warmup up front, promote-only at roll time.
        fleet.prespawn_standbys()
        rfleet.prepare_standbys(warm_pods=samples)

        t_start = time.monotonic()
        engine.start()
        time.sleep(max(0.5, 0.05 * len(trace.events) / max(qps, 1.0)))

        # ---- the roll --------------------------------------------------
        kill_victim = (rng.randrange(partitions)
                       if scenario.startswith("sigkill-") else None)
        part_order = list(range(partitions))
        part_records: List[dict] = []
        replica_records: List[dict] = []

        def roll_partitions() -> None:
            for i in part_order:
                part_records.append(_roll_one_partition(
                    fleet, coordinator, i, freeze_budget_s,
                    kill=(i == kill_victim), progress=progress))

        def roll_replicas() -> None:
            for j in range(replicas):
                replica_records.append(rfleet.roll(j, warm_pods=samples))
                attach_slo_baseline(rfleet.replicas[j])

        if scenario.endswith("schedulers-first"):
            roll_replicas()
            roll_partitions()
        else:
            roll_partitions()
            roll_replicas()
        roll_wall_s = time.monotonic() - t_start

        # ---- quiesce: every arrival bound ------------------------------
        want = len(trace.events)
        deadline = time.monotonic() + wait_timeout
        last_note = 0.0
        while time.monotonic() < deadline:
            with engine._lock:
                bound = len(engine._bind)
            if engine.injection_done.is_set() and bound >= want:
                break
            if progress and time.monotonic() - last_note > 10.0:
                last_note = time.monotonic()
                progress(f"upgrade[{scenario}]: {bound}/{want} bound")
            time.sleep(0.1)
        rfleet.flush()
        stats = engine.finish()
        engine = None
        time.sleep(0.5)   # quiesce: streams catch up before the audit

        # ---- invariants ------------------------------------------------
        union: Dict[tuple, str] = {}
        dups = 0
        bound_truth = 0
        epochs = set()
        for counts in fleet.counts():
            epochs.add(counts["epoch"])
            for ns, name, rv, is_bound in counts["pods"]:
                key = (ns, name)
                if key in union:
                    dups += 1
                union[key] = rv
                if is_bound:
                    bound_truth += 1
        rec_missing = [k for k in union if k not in recorder.state]
        rec_extra = [k for k in recorder.state if k not in union]
        rec_stale = [k for k, rv in union.items()
                     if recorder.state.get(k) not in (None, rv)]
        doubles = recorder.doubles()
        counters = _client_counters(clients)
        fresh = collect_freshness(
            get_devprof().summary() if get_devprof().enabled else None)
        slo = (fresh or {}).get("slo") or {}
        frozen_ms_max = max(
            (r["frozen_ms"] for r in part_records), default=0.0)
        rolled_ok = (
            all(r["rolled"] for r in part_records)
            and list(fleet.restarts) == [1] * partitions
            and list(rfleet.restarts) == [1] * replicas)
        v1_pins = dict(v1_client.negotiated_codec)
        result = {
            "scenario": scenario,
            "seed": seed,
            "partitions": partitions,
            "replicas": replicas,
            "qps": qps,
            "injected": stats.injected,
            "ever_bound": stats.ever_bound,
            "server_pods": len(union),
            "server_bound": bound_truth,
            "lost_pods": stats.lost,
            "send_errors": list(stats.send_errors),
            "p99_arrival_to_bind_ms": round(stats.latency_p99_ms()),
            "p50_arrival_to_bind_ms": round(
                stats.arrival_to_bind.get("all", {}).get("p50", 0.0)
                * 1000),
            "duplicates": dups,
            "doubles": len(doubles),
            "lost_watches": (len(rec_missing) + len(rec_extra)
                             + len(rec_stale)),
            "rolled_partitions": sum(
                1 for r in part_records if r["rolled"]),
            "rolled_replicas": len(replica_records),
            "partition_restarts": list(fleet.restarts),
            "replica_restarts": list(rfleet.restarts),
            "rolled_exactly_once": rolled_ok,
            "aborts": sum(r["aborts"] for r in part_records),
            "kill_victim": kill_victim,
            "frozen_ms_max": frozen_ms_max,
            "freeze_budget_ms": freeze_budget_s * 1000.0,
            "roll_wall_s": round(roll_wall_s, 2),
            "epochs": sorted(epochs),
            "v1_negotiated": v1_pins,
            "v1_pin_ok": (all(v == 1 for v in v1_pins.values())
                          and len(v1_pins) == partitions),
            "partition_records": part_records,
            "replica_records": replica_records,
            "freshness": fresh,
            "slo_verdicts_ok": (all(v == "ok" for v in slo.values())
                                if slo else None),
        }
        result.update(counters)
        # ---- fleet trace: scrape every partition's /debug/trace with
        # half-RTT skew correction, absorb the in-parent ring (replica
        # schedulers + coordinator + replay engine all record there),
        # merge, and attribute the per-pod cross-process critical path
        try:
            from kubernetes_tpu.observability import get_tracer
            from kubernetes_tpu.observability.fleettrace import (
                collect_fleet_trace,
            )

            doc, cp = collect_fleet_trace(
                remote=[(f"apiserver-{i}", u)
                        for i, u in enumerate(fleet.urls)],
                local=[("scheduler", get_tracer())],
                token=SCHEDULER_TOKEN, max_pods=25)
            result["fleet_trace_doc"] = doc
            result["critical_path"] = cp
        except Exception:  # noqa: BLE001 — tracing must not fail a row
            pass
        return result
    finally:
        if engine is not None:
            try:
                engine.finish()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        if rfleet is not None:
            rfleet.stop()
        for c in clients:
            try:
                c._stop_watches()
                c._drop_conn()
            except Exception:  # noqa: BLE001
                pass
        fleet.teardown()
        import gc

        gc.collect()


# ---------------------------------------------------------------------------
# the committed row + diag


def _upgrade_ok(res: dict) -> Tuple[bool, str]:
    checks = {
        "lost_pods": res["lost_pods"] == 0,
        "all_bound": res["ever_bound"] >= res["injected"] > 0,
        "send_errors": not res["send_errors"],
        "duplicates": res["duplicates"] == 0,
        "doubles": res["doubles"] == 0,
        "lost_watches": res["lost_watches"] == 0,
        "unmoved_relists": res["unmoved_relists"] == 0,
        "rv_regressions": res["rv_regressions"] == 0,
        "rolled_exactly_once": res["rolled_exactly_once"],
        "one_epoch": len(res["epochs"]) == 1,
        "freeze_budget": (res["frozen_ms_max"]
                          <= res["freeze_budget_ms"]),
        "codec_failures": res["codec_failures"] == 0,
        "v1_pin": res["v1_pin_ok"],
        "slo": res["slo_verdicts_ok"] is not False,
    }
    bad = [k for k, ok in checks.items() if not ok]
    return not bad, " ".join(bad)


def run_upgrade_row(
    pods: int = 2400,
    qps: float = 100.0,
    seed: int = 16,
    *,
    partitions: int = 3,
    replicas: int = 2,
    node_cpu: int = 32,
    max_batch: int = 256,
    freeze_budget_s: float = FREEZE_BUDGET_S,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """The committed rolling-upgrade row (``bench.py --config
    upgrade``): full-fleet roll at open-loop arrival rate, headline =
    p99 arrival→bind with every robustness invariant as the verdict
    surface, gated by ``perf_report``'s ``upgrade_flags``.

    The defaults are sized to the END-TO-END REST budget of the bench
    host (every arrival is an HTTP create, every bind an HTTP POST,
    across 6+ real processes): the offered rate must be one the
    binding pipeline can actually absorb, or the open-loop backlog —
    not the roll — owns the p99 and the row measures the injector's
    queue instead of the seams. Scale ``qps``/``pods`` up on hardware
    with cores to spare; the invariants are rate-independent."""
    res = run_upgrade_roll(
        partitions=partitions, replicas=replicas, pods=pods, qps=qps,
        seed=seed, scenario="partitions-first", node_cpu=node_cpu,
        max_batch=max_batch, freeze_budget_s=freeze_budget_s,
        wait_timeout=wait_timeout, progress=progress)
    ok, why = _upgrade_ok(res)
    value = (res["ever_bound"] / res["roll_wall_s"]
             if res["roll_wall_s"] > 0 else 0.0)
    row = {
        "metric": (
            f"upgrade_roll[open-loop {qps:.0f}/s "
            f"{partitions}part+{replicas}sched rolling restart, "
            f"{pods}pods seed={seed}, REST fabric]"),
        "value": round(value, 1),
        "unit": "pods/s",
        "offered_rate_pods_per_sec": round(qps, 1),
        "p99_arrival_to_bind_ms": res["p99_arrival_to_bind_ms"],
        "p50_arrival_to_bind_ms": res["p50_arrival_to_bind_ms"],
        "injected": res["injected"],
        "ever_bound": res["ever_bound"],
        "lost_pods": res["lost_pods"],
        "lost_watch_events": res["lost_watches"],
        "duplicated_events": res["doubles"],
        "unmoved_relists": res["unmoved_relists"],
        "rolled_partitions": res["rolled_partitions"],
        "rolled_replicas": res["rolled_replicas"],
        "rolled_exactly_once": res["rolled_exactly_once"],
        "frozen_ms_max": res["frozen_ms_max"],
        "freeze_budget_ms": res["freeze_budget_ms"],
        "codec_renegotiations": res["codec_renegotiations"],
        "codec_failures": res["codec_failures"],
        "handoff_fetches": res["handoff_fetches"],
        "epoch": res["epochs"][-1] if res["epochs"] else 0,
        "invariants_ok": ok,
        "invariants": {"failed": why} if why else {},
    }
    fresh = res.get("freshness") or {}
    if fresh:
        row["freshness"] = fresh
        slo = fresh.get("slo") or {}
        row["slo_verdicts_ok"] = res["slo_verdicts_ok"]
        row["slo_gated"] = sorted(slo)
    cp = res.get("critical_path")
    if cp:
        # phase shares / unattributed_share / max_skew_ms ride the row
        # (perf_report's critpath_flags gates them); the merged Perfetto
        # doc is written aside when the caller names a destination —
        # megabytes of spans don't belong in a bench row
        row["critical_path"] = {k: v for k, v in cp.items()
                                if k != "per_pod"}
        out = os.environ.get("KTPU_FLEET_TRACE_OUT")
        doc = res.get("fleet_trace_doc")
        if out and doc:
            try:
                with open(out, "w") as f:
                    json.dump(doc, f)
                row["fleet_trace"] = os.path.basename(out)
            except OSError:
                pass
    _upgrade_diag(res)
    if progress:
        progress(f"[upgrade] rolled {res['rolled_partitions']}p+"
                 f"{res['rolled_replicas']}s, p99 arrival→bind "
                 f"{res['p99_arrival_to_bind_ms']}ms, lost "
                 f"{res['lost_pods']}, reneg "
                 f"{res['codec_renegotiations']}, "
                 f"{'OK' if ok else 'FAILED: ' + why}")
    return row


def _upgrade_diag(res: dict) -> None:
    import sys

    from kubernetes_tpu.harness import diagfmt

    seg = diagfmt.format_upgrade({
        "rolled": res["rolled_partitions"] + res["rolled_replicas"],
        "frozen_ms_max": res["frozen_ms_max"],
        "reneg": res["codec_renegotiations"],
        "lost": res["lost_pods"] + res["lost_watches"],
        "relists": res["unmoved_relists"],
    })
    if seg:
        print(diagfmt.format_diag([seg]), file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# chaos cells (tools/chaos_matrix.py --suite upgrade)


def run_upgrade_cell(seed: int, nodes: int = 0, pods: int = 400,
                     wait_timeout: float = 240.0,
                     progress: Optional[Callable] = None,
                     scenario: str = "partitions-first") -> Dict:
    """One seeded (scenario × seed) cell: a compressed full roll —
    2 spawned partitions + 1 replica at a few hundred pods — crossing
    roll order × mid-roll SIGKILL on the draining process. Asserts
    rollback-or-complete (every process restarted exactly once, or an
    honest recorded abort) and the zero-lost surface."""
    res = run_upgrade_roll(
        partitions=2, replicas=1, pods=pods, qps=max(100.0, pods / 4.0),
        seed=seed, scenario=scenario, node_cpu=16, max_batch=256,
        freeze_budget_s=FREEZE_BUDGET_S, wait_timeout=wait_timeout,
        progress=progress)
    ok, why = _upgrade_ok(res)
    if scenario.startswith("sigkill-"):
        ok = ok and res["kill_victim"] is not None
        if res["kill_victim"] is None:
            why = (why + " no_kill").strip()
    return {
        "seed": seed, "profile": scenario, "ok": ok,
        "failure": "" if ok else (
            f"{why} lost={res['lost_pods']} "
            f"dups={res['duplicates']} doubles={res['doubles']} "
            f"relists={res['unmoved_relists']} "
            f"restarts={res['partition_restarts']}"
            f"+{res['replica_restarts']} epochs={res['epochs']}"),
        "stats": {
            "injected": res["injected"],
            "ever_bound": res["ever_bound"],
            "rolled": (res["rolled_partitions"]
                       + res["rolled_replicas"]),
            "aborts": res["aborts"],
            "kill_victim": res["kill_victim"],
            "frozen_ms_max": res["frozen_ms_max"],
            "reneg": res["codec_renegotiations"],
            "p99_arrival_to_bind_ms": res["p99_arrival_to_bind_ms"],
            "epoch": res["epochs"][-1] if res["epochs"] else 0,
        },
    }


def run_chaos_upgrade(seed: int, nodes: int = 0, pods: int = 400,
                      wait_timeout: float = 240.0,
                      progress: Optional[Callable] = None,
                      scenario: str = "partitions-first") -> Dict:
    """chaos_matrix entry point: one (scenario × seed) cell."""
    if scenario not in UPGRADE_SCENARIOS:
        raise ValueError(f"unknown upgrade scenario {scenario!r} "
                         f"(have: {', '.join(UPGRADE_SCENARIOS)})")
    return run_upgrade_cell(seed, nodes=nodes, pods=pods,
                            wait_timeout=wait_timeout,
                            progress=progress, scenario=scenario)


# ---------------------------------------------------------------------------
# tier-1 mini-cell (tests/test_upgrade.py::TestRollingMiniCell)


def run_upgrade_mini_cell(
    nodes: int = 200,
    pods: int = 160,
    partitions: int = 2,
    settle_s: float = 1.2,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """CI-fast rolling upgrade: ``partitions`` in-process apiservers
    (restart seam modeled as a NEW server on the surviving store — the
    WAL-restored equivalence without spawn cost) + ONE scheduler
    replica, all rolled under a sustained writer, with one client
    pinned to the OLD codec stamp for the duration. Asserted by the
    caller: informer ≡ server truth at quiesce, 0 lost watches, 0
    relists of unmoved slices, the v1 pin honored across every seam."""
    from kubernetes_tpu.apiserver.partition import PartitionTopology
    from kubernetes_tpu.apiserver.reshard import ReshardCoordinator
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.client import SharedInformerFactory
    from kubernetes_tpu.kubemark import HollowFleet

    servers = [APIServer(store=ClusterStore(),
                         partition=(i, partitions)).start()
               for i in range(partitions)]
    urls = [s.url for s in servers]
    topo = PartitionTopology.default(partitions, urls=urls)
    for s in servers:
        s.install_topology(topo)

    client = RestClusterClient(urls[0], partition_urls=urls,
                               watch_kinds=("Pod", "Node"))
    # the OLD-VERSION witness: informers ride this v1-pinned client
    # through every restart seam — legacy 3-tuple frames all the way
    v1_client = RestClusterClient(urls[0], partition_urls=urls,
                                  watch_kinds=("Pod", "Node"),
                                  codec_version=1)
    coordinator = ReshardCoordinator(client, freeze_eta=5.0,
                                     evict_grace_s=0.1)
    factory = None
    fleet = None
    rfleet = None
    part_records: List[dict] = []
    try:
        assert client.enable_topology(poll_interval=0.15)
        assert v1_client.enable_topology(poll_interval=0.15)
        factory = SharedInformerFactory(v1_client)
        pod_lister = factory.lister_for("Pod")
        node_lister = factory.lister_for("Node")
        fleet = HollowFleet(client, interval=30.0)
        fleet.register(nodes, cpu="16", chunk=256)
        fleet.start()
        factory.start()
        factory.wait_for_cache_sync()
        if progress:
            progress(f"upgrade mini-cell: {nodes} hollow nodes up")

        def sched_client(j: int) -> RestClusterClient:
            # evaluated at roll time too: the replacement replica's
            # client must dial the CURRENT fleet, not the pre-roll URLs
            live = [s.url for s in servers]
            c = RestClusterClient(live[0], partition_urls=live,
                                  watch_kinds=("Pod", "Node"))
            assert c.enable_topology(poll_interval=0.15)
            return c

        rfleet = _ReplicaFleet(sched_client, count=1, use_batch=False,
                               progress=progress)
        rfleet.run()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline \
                and min(rfleet.cache_nodes()) < nodes:
            time.sleep(0.05)

        namespaces = [f"upmc-{i}" for i in range(8)]
        stop = threading.Event()
        errors: List[str] = []
        confirmed = [0]

        def writer() -> None:
            i = 0
            while not stop.is_set() and confirmed[0] < pods:
                batch = make_burst_pods(
                    4, cpu_milli=POD_CPU_MILLI, memory=POD_MEMORY,
                    name_prefix="upmc-", uid_prefix="upmcu-",
                    offset=i, namespaces=namespaces)
                try:
                    confirmed[0] += client.create_objects_bulk(
                        "Pod", batch)
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{type(e).__name__}: {e}")
                    return
                i += 4
                time.sleep(0.01)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.3)

        # ---- roll every partition (in-proc make-before-break) ------
        from kubernetes_tpu.apiserver.reshard import ReshardError

        for i in range(partitions):
            t0 = time.monotonic()
            live_topo = coordinator.fetch_topology()
            slots = live_topo.slots_of_partition(i)
            aborted = False
            if slots:
                coordinator._freeze({i: slots}, 5.0)
                time.sleep(0.1)
                try:
                    coordinator._verify_frozen({i: slots})
                except ReshardError:
                    coordinator._unfreeze({i: slots})
                    aborted = True
            if aborted:
                part_records.append({"partition": i, "rolled": False,
                                     "frozen_ms": 0.0})
                continue
            replacement = APIServer(store=servers[i].store,
                                    partition=(i, partitions)).start()
            old = servers[i]
            servers[i] = replacement
            coordinator.reroute_after_restart(i, replacement.url)
            old.shutdown_server()
            part_records.append({
                "partition": i, "rolled": True,
                "frozen_ms": round(
                    (time.monotonic() - t0) * 1000.0, 1)})
            if progress:
                progress(f"upgrade mini-cell: partition {i} rolled")

        # ---- roll the scheduler replica ----------------------------
        replica_record = rfleet.roll(0)

        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if confirmed[0] >= pods \
                    and rfleet.bound_count() >= confirmed[0]:
                break
            time.sleep(0.1)
        stop.set()
        t.join(timeout=5.0)
        rfleet.flush(timeout=15.0)
        time.sleep(settle_s)   # quiesce: informer catches up

        union: Dict[tuple, str] = {}
        duplicates = 0
        bound = 0
        for s in servers:
            for p in s.store.list_pods():
                key = (p.namespace, p.metadata.name)
                if key in union:
                    duplicates += 1
                union[key] = p.metadata.resource_version
                if p.spec.node_name:
                    bound += 1
        inf = {(o.metadata.namespace, o.metadata.name):
               o.metadata.resource_version for o in pod_lister.list()}
        missing = [k for k in union if k not in inf]
        extra = [k for k in inf if k not in union]
        stale = [k for k in union if k in inf and inf[k] != union[k]]
        v1_pins = dict(v1_client.negotiated_codec)
        return {
            "errors": errors,
            "confirmed": confirmed[0],
            "server_pods": len(union),
            "server_bound": bound,
            "scheduled": rfleet.bound_count(),
            "duplicates": duplicates,
            "informer_pods": len(inf),
            "informer_nodes": len(node_lister.list()),
            "missing": missing[:5],
            "extra": extra[:5],
            "stale": stale[:5],
            "lost_watches": len(missing) + len(extra) + len(stale),
            "unmoved_relists": sum(client.stream_relists.values())
            + sum(v1_client.stream_relists.values()),
            "rv_regressions": (list(client.rv_regressions)
                               + list(v1_client.rv_regressions)),
            "partition_records": part_records,
            "replica_record": replica_record,
            "rolled_partitions": sum(
                1 for r in part_records if r["rolled"]),
            "rolled_replicas": rfleet.restarts[0],
            "frozen_ms_max": max(
                (r["frozen_ms"] for r in part_records), default=0.0),
            "v1_negotiated": v1_pins,
            "v1_pin_ok": (all(v == 1 for v in v1_pins.values())
                          and len(v1_pins) == partitions),
            "v1_renegotiations": v1_client.codec_renegotiations,
            "codec_failures": (client.codec_failures
                               + v1_client.codec_failures),
            "epoch": client.topology_epoch,
        }
    finally:
        if rfleet is not None:
            rfleet.stop()
        if factory is not None:
            factory.stop()
        if fleet is not None:
            fleet.stop()
        client._stop_watches()
        client._drop_conn()
        v1_client._stop_watches()
        v1_client._drop_conn()
        for s in servers:
            s.shutdown_server()
