"""Federation storm: cluster-loss failover + saturation spillover.

The reshard/upgrade chaos families proved one partitioned control
plane survives its own seams; this harness is the tier above — K
INDEPENDENT clusters (each its own spawned apiserver + in-parent
scheduler replica, the upgrade harness's cell shape) behind the
federation layer, judged by the cluster-granularity twins of the same
invariants:

- **cluster loss**: SIGKILL an entire cell's process mid-storm → the
  ``ClusterRebalancer`` observes the dead ledger, fires failover, and
  every pod registered to the dead cell re-creates (same NAMES — the
  lost-pod invariant is name-keyed) on survivors; 0 lost fleet-wide,
  re-placement within ``RECOVERY_BUDGET_S``, and the surviving cells'
  watch streams never relist (confinement: only the dead cell's
  stream stops);
- **saturation spillover**: one cluster's capacity pinned far below
  its tenants' demand → overflow lands remotely (the what-if solve
  steers around the saturated column) while the saturated cell's own
  arrival→bind SLO stays green because it never queues what it
  cannot hold;
- **gang atomicity**: a gang is one placement unit; at quiesce every
  gang's members live on exactly one cluster;
- **bounded degradation**: the federation scheduler down → every
  create still routes (home hashing) and every cell keeps binding
  locally; ``run_degradation_differential`` holds the federation-on
  and federation-down arms to bit-identical bound sets at
  single-cluster scope.

``run_federation_row`` commits the bench rows (``bench.py --config
federation``), ``run_chaos_federation`` the seeded matrix cells
(``tools/chaos_matrix.py --suite federation``), and
``run_federation_mini_cell`` / ``run_degradation_differential`` the
tier-1 faces. ``tools/perf_report.py`` gates the committed rows
(``federation_flags``): lost pods, a cross-cluster gang split, a red
per-cluster SLO, or recovery ratio < 0.8 all fail ``--strict``.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.harness.workloads import node_template
from kubernetes_tpu.workloads.trace import Trace, generate_trace

FEDERATION_SCENARIOS = ("spill", "loss-early", "loss-mid", "loss-late",
                        "spill-loss")

FEDERATION_QPS = 300.0
RECOVERY_BUDGET_S = 30.0
P99_PER_CLUSTER_BUDGET_MS = 2500.0
RECOVERY_RATIO_FLOOR = 0.8

# where in the injection window the kill lands, per scenario
_KILL_AT = {"loss-early": 0.25, "loss-mid": 0.5, "loss-late": 0.75,
            "spill-loss": 0.5}


def build_federation_trace(seed: int, pods: int,
                           qps: float = FEDERATION_QPS,
                           namespaces: int = 12,
                           gang_every: int = 10,
                           gang_size: int = 4) -> Trace:
    """Open-loop arrivals fanned across ``namespaces`` tenants (the
    namespace is the federation's placement affinity key), with every
    ``gang_every``-th run of ``gang_size`` consecutive arrivals folded
    into one gang (same namespace — a gang is one tenant's job). No
    lifetimes: zero-lost is exactly "every arrival bound"."""
    from dataclasses import replace

    trace = generate_trace(
        seed, pods, pods / qps, family="federation",
        name_prefix="fed-", cpu_alpha=1.8, cpu_lo=100, cpu_hi=500,
        lifetime_modes=None, burst_factor=1.0, burst_period_s=0.0,
    )
    spread = [f"fed-{i}" for i in range(namespaces)]
    events = [replace(e, namespace=spread[i % len(spread)])
              for i, e in enumerate(trace.events)]
    i = 0
    g = 0
    while i + gang_size <= len(events):
        if (i // gang_size) % gang_every == gang_every - 1:
            gang = f"fg-{g}"
            g += 1
            ns = events[i].namespace
            for j in range(i, i + gang_size):
                events[j] = replace(events[j], gang=gang,
                                    gang_size=gang_size, namespace=ns)
        i += gang_size
    trace.events[:] = events
    return trace


def _cluster_nodes(cid: int, count: int, node_cpu: int) -> List[dict]:
    """Per-cluster node dicts with cluster-prefixed names — the bind
    records' node name is how the harness attributes a bind to a
    cluster."""
    out = []
    for i in range(count):
        d = node_template(i, cpu=str(node_cpu), memory="64Gi")
        name = f"c{cid}-node-{i}"
        d["metadata"]["name"] = name
        d["metadata"]["labels"]["kubernetes.io/hostname"] = name
        out.append(d)
    return out


def _fleet_sizing(trace: Trace, clusters: int, node_cpu: int,
                  scenario: str) -> Dict[int, Tuple[int, int]]:
    """(node count, node cpu cores) per cluster. Loss scenarios:
    survivors alone must absorb the whole trace (capacity is sized
    over K−1). Spill scenarios: cluster 0's capacity is pinned to
    ~45% of its home tenants' demand (tenants fan round-robin, so the
    home share is 1/K of total) — more than half its offered load MUST
    land remotely — while the siblings carry the slack."""
    demand_milli = sum(e.cpu_milli for e in trace.events)
    lossy = scenario in _KILL_AT
    spill = scenario.startswith("spill")
    carriers = max(clusters - 1, 1) if lossy else clusters
    per = max(
        2,
        math.ceil(demand_milli * 1.4 / carriers / (node_cpu * 1000)),
        math.ceil(len(trace.events) * 1.25 / carriers / 110),
    )
    sizing = {cid: (per, node_cpu) for cid in range(clusters)}
    if spill and clusters > 1:
        home_milli = demand_milli / clusters
        count0 = max(1, math.ceil(
            len(trace.events) / clusters * 0.6 / 110))
        cpu0 = max(1, round(home_milli * 0.45 / count0 / 1000))
        sizing[0] = (count0, cpu0)
    return sizing


def _gang_splits(name_cluster: Dict[str, int], trace: Trace) -> int:
    """Count gangs whose members ended on more than one cluster."""
    gangs: Dict[str, set] = {}
    for e in trace.events:
        if e.gang and e.name in name_cluster:
            gangs.setdefault(e.gang, set()).add(name_cluster[e.name])
    return sum(1 for members in gangs.values() if len(members) > 1)


def _per_cluster_latency(engine, clusters: int) -> Dict[str, dict]:
    """Per-cluster bound count + arrival→bind p99 from the engine's
    bind records (node ``c{k}-node-*`` → cluster k)."""
    buckets: Dict[int, List[float]] = {k: [] for k in range(clusters)}
    with engine._lock:
        bind = dict(engine._bind)
        arrival = dict(engine._arrival)
    for name, (t_rel, node) in bind.items():
        if not node.startswith("c"):
            continue
        try:
            cid = int(node.split("-", 1)[0][1:])
        except ValueError:
            continue
        if cid in buckets and name in arrival:
            buckets[cid].append(max(0.0, t_rel - arrival[name]))
    out: Dict[str, dict] = {}
    for cid, lats in buckets.items():
        if not lats:
            out[f"c{cid}"] = {"bound": 0, "p99_ms": 0.0}
            continue
        lats.sort()
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
        out[f"c{cid}"] = {"bound": len(lats),
                          "p99_ms": round(p99 * 1000.0, 1)}
    return out


# ---------------------------------------------------------------------------
# the spawned storm (bench rows + chaos cells)


class _FederationCells:
    """K independent spawned cells — each the upgrade harness's
    1-partition apiserver child (same child main: WAL, tokens, counts
    protocol), plus its in-parent scheduler fleet."""

    def __init__(self, count: int, progress: Optional[Callable] = None):
        import multiprocessing as mp
        import tempfile

        self.count = count
        self.progress = progress
        self.ctx = mp.get_context("spawn")
        self.wal_root = tempfile.mkdtemp(prefix="ktpu-federation-wal-")
        self.children: Dict[int, list] = {}
        self.urls: Dict[int, str] = {}

    def start(self) -> Dict[int, str]:
        import os

        from kubernetes_tpu.harness.upgrade import (
            _upgrade_apiserver_main,
        )

        for cid in range(self.count):
            seg = os.path.join(self.wal_root, f"c{cid}")
            os.makedirs(seg, exist_ok=True)
            parent_conn, child_conn = self.ctx.Pipe()
            proc = self.ctx.Process(
                target=_upgrade_apiserver_main,
                args=(child_conn, 0, 1, seg, False, False),
                daemon=True)
            proc.start()
            self.children[cid] = [parent_conn, proc]
        for cid, (conn, _proc) in self.children.items():
            self.urls[cid] = conn.recv()
        return dict(self.urls)

    def kill(self, cid: int) -> None:
        """SIGKILL the whole cell — the cluster-loss seam."""
        _conn, proc = self.children[cid]
        proc.kill()
        proc.join(timeout=5.0)

    def counts(self, cid: int, timeout: float = 10.0) -> Optional[dict]:
        conn, proc = self.children[cid]
        if not proc.is_alive():
            return None
        try:
            conn.send("counts")
            if conn.poll(timeout):
                return conn.recv()
        except (BrokenPipeError, EOFError, OSError):
            pass
        return None

    def teardown(self) -> None:
        import shutil

        for conn, _proc in self.children.values():
            try:
                conn.send("stop")
            except (BrokenPipeError, OSError):
                pass
        for conn, proc in self.children.values():
            try:
                if conn.poll(3.0):
                    conn.recv()
            except (EOFError, OSError):
                pass
            proc.join(timeout=3.0)
            if proc.is_alive():
                proc.terminate()
        shutil.rmtree(self.wal_root, ignore_errors=True)


def run_federation_storm(
    *,
    clusters: int = 3,
    pods: int = 900,
    qps: float = FEDERATION_QPS,
    seed: int = 18,
    scenario: str = "spill",
    node_cpu: int = 16,
    max_batch: int = 256,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """One federation storm over spawned cells. Returns the raw result
    surface; ``run_federation_row`` shapes the committed row and
    ``run_chaos_federation`` the matrix verdict."""
    from kubernetes_tpu.api.types import Node
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.federation import (
        CapacityLedger,
        ClusterRebalancer,
        FederatedClusterClient,
        FederationScheduler,
        HomeMap,
    )
    from kubernetes_tpu.harness.perf import (
        attach_slo_baseline,
        collect_freshness,
        reset_sli_window,
    )
    from kubernetes_tpu.harness.upgrade import (
        CREATOR_TOKEN,
        SCHEDULER_TOKEN,
        _ReplicaFleet,
    )
    from kubernetes_tpu.observability import get_tracer
    from kubernetes_tpu.observability.devprof import get_devprof
    from kubernetes_tpu.utils.gctune import tune_for_throughput
    from kubernetes_tpu.workloads.replay import ReplayEngine
    from kubernetes_tpu.workloads.trace import events_to_pods

    if scenario not in FEDERATION_SCENARIOS:
        raise ValueError(
            f"unknown federation scenario {scenario!r} "
            f"(have: {', '.join(FEDERATION_SCENARIOS)})")
    tune_for_throughput()
    get_tracer().clear()
    reset_sli_window()
    get_devprof().reset(workload=f"federation/{scenario}")
    rng = random.Random(seed)
    namespaces = 12
    trace = build_federation_trace(seed, pods, qps,
                                   namespaces=namespaces)
    sizing = _fleet_sizing(trace, clusters, node_cpu, scenario)

    cells = _FederationCells(clusters, progress=progress)
    urls = cells.start()
    # RestClusterClient / _ReplicaFleet stay lazy imports (jax-heavy)
    all_clients: List = []
    fleets: Dict[int, object] = {}
    engine = None
    rebalancer = None
    probe_stop = threading.Event()

    def make_client(cid: int, token: str, watch_kinds=()):
        c = RestClusterClient(urls[cid], partition_urls=[urls[cid]],
                              token=token, watch_kinds=watch_kinds,
                              max_retries=4)
        all_clients.append(c)
        return c

    try:
        # per-cell creator clients (the federation's send/watch fabric)
        # and probe clients (the ledger's capacity poll)
        creators = {cid: make_client(cid, CREATOR_TOKEN,
                                     watch_kinds=("Pod",))
                    for cid in range(clusters)}
        probes = {cid: make_client(cid, CREATOR_TOKEN)
                  for cid in range(clusters)}

        for cid in range(clusters):
            nodes = [Node.from_dict(d) for d in
                     _cluster_nodes(cid, *sizing[cid])]
            for lo in range(0, len(nodes), 512):
                creators[cid].create_objects_bulk(
                    "Node", nodes[lo:lo + 512])
        if progress:
            progress(f"federation[{scenario}]: {clusters} cells, "
                     f"nodes per cluster {dict(sizing)}, "
                     f"{len(trace.events)} arrivals @ {qps:.0f}/s")

        # each cell's own scheduler brain (count=1 replica fleet)
        samples = events_to_pods(trace.events[:128])
        for cid in range(clusters):
            fleet = _ReplicaFleet(
                lambda j, _cid=cid: make_client(
                    _cid, SCHEDULER_TOKEN,
                    watch_kinds=("Pod", "Node")),
                count=1, use_batch=True, max_batch=max_batch,
                progress=progress)
            for sched in fleet.replicas:
                attach_slo_baseline(sched)
            fleet.run()
            fleets[cid] = fleet
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if all(min(f.cache_nodes()) >= sizing[cid][0]
                   for cid, f in fleets.items()):
                break
            time.sleep(0.1)
        for fleet in fleets.values():
            fleet.warmup(samples)

        # federation layer: ledger ← probe loop, scheduler, client,
        # rebalancer
        ledger = CapacityLedger()
        home_map = HomeMap(list(range(clusters)), pin={
            f"fed-{i}": i % clusters for i in range(namespaces)})
        fed_sched = FederationScheduler(ledger,
                                        home_of=home_map.home_of)
        fed_client = FederatedClusterClient(
            dict(creators), fed_sched, ledger, home_map=home_map)

        fail_count: Dict[int, int] = {cid: 0 for cid in range(clusters)}

        def probe_loop() -> None:
            while not probe_stop.wait(0.25):
                for cid in list(probes):
                    if not ledger.alive(cid):
                        continue
                    try:
                        ns = probes[cid].list_nodes()
                        ps = probes[cid].list_pods()
                        ledger.refresh_from(cid, ns, ps)
                        fail_count[cid] = 0
                    except Exception:  # noqa: BLE001 — the cell may
                        fail_count[cid] += 1   # be dead; two misses
                        if fail_count[cid] >= 2:   # confirm it
                            ledger.mark_dead(cid)

        # one synchronous probe pass so placement starts informed
        for cid in range(clusters):
            ledger.refresh_from(cid, probes[cid].list_nodes(),
                                probes[cid].list_pods())
        probe = threading.Thread(target=probe_loop, daemon=True,
                                 name="federation-ledger-probe")
        probe.start()
        rebalancer = ClusterRebalancer(fed_client, interval_s=0.3)
        rebalancer.run()

        engine = ReplayEngine(fed_client, trace, time_scale=1.0,
                              expire=False, progress=progress)
        t_start = time.monotonic()
        engine.start()

        # ---- the seam: SIGKILL one whole cell mid-storm --------------
        victim: Optional[int] = None
        t_kill_rel = 0.0
        orphans: List[str] = []
        orphans_unbound: List[str] = []
        if scenario in _KILL_AT:
            at = _KILL_AT[scenario] * trace.duration_s
            while time.monotonic() - t_start < at \
                    and not engine.injection_done.is_set():
                time.sleep(0.05)
            # spill-loss kills a NON-saturated cell: the spillover load
            # and the loss then land on the same survivors
            victim = (rng.randrange(1, clusters)
                      if scenario == "spill-loss" and clusters > 1
                      else rng.randrange(clusters))
            with fed_client._lock:
                orphans = [name for (ns, name), cid
                           in fed_client._route.items()
                           if cid == victim]
            with engine._lock:
                bound_now = set(engine._bind)
            orphans_unbound = [n for n in orphans
                               if n not in bound_now]
            t_kill_rel = time.monotonic() - t_start
            if progress:
                progress(f"federation[{scenario}]: SIGKILL cluster "
                         f"{victim} ({len(orphans)} registered, "
                         f"{len(orphans_unbound)} unbound)")
            cells.kill(victim)
            # the dead cell's brain: stop it in the background — its
            # client calls may block on the dead socket
            threading.Thread(target=fleets.pop(victim).stop,
                             daemon=True).start()
            # the rebalancer observes the dead ledger and fires
            # failover; if the loop misses its window, fail over
            # directly (the invariant is the re-placement, not the
            # messenger)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if any(a["action"]["op"] == "failover"
                       for a in rebalancer.actions):
                    break
                time.sleep(0.1)
            else:
                ledger.mark_dead(victim)
                fed_client.failover_cluster(victim, progress=progress)

        # ---- quiesce: every arrival bound ----------------------------
        want = len(trace.events)
        deadline = time.monotonic() + wait_timeout
        last_note = 0.0
        while time.monotonic() < deadline:
            with engine._lock:
                bound = len(engine._bind)
            if engine.injection_done.is_set() and bound >= want:
                break
            if progress and time.monotonic() - last_note > 10.0:
                last_note = time.monotonic()
                progress(f"federation[{scenario}]: {bound}/{want} "
                         f"bound")
            time.sleep(0.1)
        for fleet in fleets.values():
            fleet.flush()
        per_cluster = _per_cluster_latency(engine, clusters)
        with engine._lock:
            bind_final = dict(engine._bind)
        stats = engine.finish()
        engine = None
        time.sleep(0.5)

        # ---- invariants ----------------------------------------------
        # fleet-wide server truth from the SURVIVING cells
        name_cluster: Dict[str, int] = {}
        server_bound = 0
        for cid in range(clusters):
            if cid == victim:
                continue
            counts = cells.counts(cid)
            if counts is None:
                continue
            for ns, name, _rv, is_bound in counts["pods"]:
                name_cluster[name] = cid
                if is_bound:
                    server_bound += 1
        gang_splits = _gang_splits(name_cluster, trace)
        # recovery: of the victim's pods unbound at the kill, how many
        # re-bound on survivors inside the budget
        recovered = 0
        for n in orphans_unbound:
            rec = bind_final.get(n)
            if rec is not None \
                    and rec[0] - t_kill_rel <= RECOVERY_BUDGET_S:
                recovered += 1
        recovery_ratio = (recovered / len(orphans_unbound)
                          if orphans_unbound else 1.0)
        # relist confinement: the surviving cells' streams never relist
        survivor_relists = 0
        for cid in range(clusters):
            if cid == victim:
                continue
            survivor_relists += sum(
                creators[cid].stream_relists.values())
        for cid, fleet in fleets.items():
            for sched in fleet.replicas:
                survivor_relists += sum(
                    sched.client.stream_relists.values())
        fresh = collect_freshness(
            get_devprof().summary() if get_devprof().enabled else None)
        slo = (fresh or {}).get("slo") or {}
        counters = fed_client.counters()
        result = {
            "scenario": scenario,
            "seed": seed,
            "clusters": clusters,
            "qps": qps,
            "injected": stats.injected,
            "ever_bound": stats.ever_bound,
            "server_bound": server_bound,
            "lost_pods": stats.lost,
            "send_errors": list(stats.send_errors),
            "p99_arrival_to_bind_ms": round(stats.latency_p99_ms()),
            "p50_arrival_to_bind_ms": round(
                stats.arrival_to_bind.get("all", {}).get("p50", 0.0)
                * 1000),
            "last_bind_s": stats.last_bind_s,
            "offered_rate": stats.offered_rate,
            "per_cluster": per_cluster,
            "per_cluster_slo_ok": all(
                v["p99_ms"] <= P99_PER_CLUSTER_BUDGET_MS
                for v in per_cluster.values() if v["bound"] > 0),
            "gangs_total": len(
                {e.gang for e in trace.events if e.gang}),
            "gang_splits": gang_splits,
            "spilled": counters["spilled"],
            "fallback_placements": counters["fallback_placements"],
            "failovers": counters["failovers"],
            "failover_replaced": counters["failover_replaced"],
            "victim": victim,
            "orphans": len(orphans),
            "orphans_unbound_at_kill": len(orphans_unbound),
            "recovered_in_budget": recovered,
            "recovery_budget_s": RECOVERY_BUDGET_S,
            "recovery_ratio": round(recovery_ratio, 3),
            "survivor_relists": survivor_relists,
            "rebalancer_actions": [a["action"]["op"]
                                   for a in rebalancer.actions],
            "freshness": fresh,
            "slo_verdicts_ok": (all(v == "ok" for v in slo.values())
                                if slo else None),
        }
        # ---- fleet trace across the cross-cluster hop ----------------
        try:
            from kubernetes_tpu.observability.fleettrace import (
                collect_fleet_trace,
            )

            doc, cp = collect_fleet_trace(
                remote=[(f"cluster-{cid}", urls[cid])
                        for cid in range(clusters) if cid != victim],
                local=[("federation", get_tracer())],
                token=SCHEDULER_TOKEN, max_pods=25)
            result["fleet_trace_doc"] = doc
            result["critical_path"] = cp
        except Exception:  # noqa: BLE001 — tracing must not fail a row
            pass
        return result
    finally:
        probe_stop.set()
        if rebalancer is not None:
            rebalancer.stop()
        if engine is not None:
            try:
                engine.finish()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        for fleet in fleets.values():
            try:
                fleet.stop()
            except Exception:  # noqa: BLE001
                pass
        for c in all_clients:
            try:
                c._stop_watches()
                c._drop_conn()
            except Exception:  # noqa: BLE001
                pass
        cells.teardown()
        import gc

        gc.collect()


# ---------------------------------------------------------------------------
# the committed rows + diag


def _federation_ok(res: dict) -> Tuple[bool, str]:
    checks = {
        "lost_pods": res["lost_pods"] == 0,
        "all_bound": res["ever_bound"] >= res["injected"] > 0,
        "send_errors": not res["send_errors"],
        "gangs_atomic": res["gang_splits"] == 0,
        "relist_confinement": res["survivor_relists"] == 0,
        "per_cluster_slo": res["per_cluster_slo_ok"],
        "recovery": (res["recovery_ratio"] >= RECOVERY_RATIO_FLOOR
                     if res["victim"] is not None else True),
        "slo": res["slo_verdicts_ok"] is not False,
    }
    if res["scenario"].startswith("spill"):
        checks["spilled"] = res["spilled"] > 0
    if res["victim"] is not None:
        checks["failed_over"] = res["failovers"] >= 1
    bad = [k for k, ok in checks.items() if not ok]
    return not bad, " ".join(bad)


def _federation_diag(res: dict) -> None:
    import sys

    from kubernetes_tpu.harness import diagfmt

    seg = diagfmt.format_federation({
        "clusters": res["clusters"],
        "spilled": res["spilled"],
        "failovers": res["failovers"],
        "lost": res["lost_pods"],
        "recovery": res["recovery_ratio"],
    })
    if seg:
        print(diagfmt.format_diag([seg]), file=sys.stderr, flush=True)


def run_federation_row(
    pods: int = 900,
    qps: float = FEDERATION_QPS,
    seed: int = 18,
    *,
    mode: str = "spill",
    clusters: int = 3,
    node_cpu: int = 16,
    max_batch: int = 256,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """One committed federation row (``bench.py --config federation``
    emits two: ``mode='spill'`` and ``mode='loss'``). Headline =
    rate-normalized throughput + per-cluster p99 arrival→bind, verdict
    surface = lost/gang/relist/recovery invariants, gated by
    ``perf_report``'s ``federation_flags``."""
    scenario = "loss-mid" if mode == "loss" else mode
    res = run_federation_storm(
        clusters=clusters, pods=pods, qps=qps, seed=seed,
        scenario=scenario, node_cpu=node_cpu, max_batch=max_batch,
        wait_timeout=wait_timeout, progress=progress)
    ok, why = _federation_ok(res)
    value = (res["ever_bound"] / res["last_bind_s"]
             if res["last_bind_s"] > 0 else 0.0)
    offered = res["offered_rate"]
    label = ("cluster-loss SIGKILL" if res["victim"] is not None
             else "saturation spillover")
    row = {
        "metric": (
            f"federation_{mode}[open-loop {qps:.0f}/s "
            f"{clusters}clusters {label}, {pods}pods seed={seed}, "
            f"REST fabric]"),
        "value": round(value, 1),
        "unit": "pods/s",
        "offered_rate_pods_per_sec": round(offered, 2),
        "rate_normalized_throughput": round(
            value / offered, 3) if offered > 0 else 0.0,
        "p99_arrival_to_bind_ms": res["p99_arrival_to_bind_ms"],
        "p50_arrival_to_bind_ms": res["p50_arrival_to_bind_ms"],
        "per_cluster": res["per_cluster"],
        "per_cluster_slo_ok": res["per_cluster_slo_ok"],
        "injected": res["injected"],
        "ever_bound": res["ever_bound"],
        "lost_pods": res["lost_pods"],
        "gang_splits": res["gang_splits"],
        "spilled": res["spilled"],
        "failovers": res["failovers"],
        "failover_replaced": res["failover_replaced"],
        "recovery_ratio": res["recovery_ratio"],
        "survivor_relists": res["survivor_relists"],
        "fallback_placements": res["fallback_placements"],
        "invariants_ok": ok,
        "invariants": {"failed": why} if why else {},
    }
    fresh = res.get("freshness") or {}
    if fresh:
        row["freshness"] = fresh
        slo = fresh.get("slo") or {}
        row["slo_verdicts_ok"] = res["slo_verdicts_ok"]
        row["slo_gated"] = sorted(slo)
    cp = res.get("critical_path")
    if cp:
        row["critical_path"] = {k: v for k, v in cp.items()
                                if k != "per_pod"}
    _federation_diag(res)
    if progress:
        progress(f"[federation/{mode}] {res['ever_bound']}/"
                 f"{res['injected']} bound, spilled {res['spilled']}, "
                 f"failovers {res['failovers']}, recovery "
                 f"{res['recovery_ratio']:.2f}, lost "
                 f"{res['lost_pods']}, "
                 f"{'OK' if ok else 'FAILED: ' + why}")
    return row


# ---------------------------------------------------------------------------
# chaos cells (tools/chaos_matrix.py --suite federation)


def run_chaos_federation(seed: int, nodes: int = 0, pods: int = 400,
                         wait_timeout: float = 300.0,
                         progress: Optional[Callable] = None,
                         scenario: str = "loss-mid") -> Dict:
    """One seeded (scenario × seed) cell: kill timing × which-cluster
    (seed-chosen victim) × spillover load, compressed to a few hundred
    pods over 3 spawned cells."""
    if scenario not in FEDERATION_SCENARIOS:
        raise ValueError(
            f"unknown federation scenario {scenario!r} "
            f"(have: {', '.join(FEDERATION_SCENARIOS)})")
    res = run_federation_storm(
        clusters=3, pods=pods, qps=max(100.0, pods / 4.0), seed=seed,
        scenario=scenario, node_cpu=16, max_batch=256,
        wait_timeout=wait_timeout, progress=progress)
    ok, why = _federation_ok(res)
    return {
        "seed": seed, "profile": scenario, "ok": ok,
        "failure": "" if ok else (
            f"{why} lost={res['lost_pods']} "
            f"splits={res['gang_splits']} "
            f"relists={res['survivor_relists']} "
            f"recovery={res['recovery_ratio']}"),
        "stats": {
            "injected": res["injected"],
            "ever_bound": res["ever_bound"],
            "spilled": res["spilled"],
            "failovers": res["failovers"],
            "victim": res["victim"],
            "orphans": res["orphans"],
            "recovery_ratio": res["recovery_ratio"],
            "p99_arrival_to_bind_ms": res["p99_arrival_to_bind_ms"],
        },
    }


# ---------------------------------------------------------------------------
# tier-1 faces: in-process mini-cell + the degradation differential


def _inproc_cluster(cid: int, sizing: Tuple[int, int],
                    max_batch: int, samples) -> dict:
    """One in-process cell: store + gang scheduler + batch sidecar —
    the sustained harness's stack, one per cluster."""
    from kubernetes_tpu.api.types import Node
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.harness.perf import attach_slo_baseline
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler

    store = ClusterStore()
    for d in _cluster_nodes(cid, *sizing):
        store.add_node(Node.from_dict(d))
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": True}),
        provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(sched, max_batch=max_batch)
    attach_slo_baseline(sched)
    sched.start()
    if samples:
        bs.warmup(sample_pods=samples)
    return {"store": store, "sched": sched, "bs": bs}


def _pump_cells(cells: Dict[int, dict], engine, ledger, deadline: float,
                on_tick: Optional[Callable] = None,
                settle_s: float = 1.0) -> None:
    """Round-robin the live cells' batch schedulers until quiesce —
    the sustained pump fanned across clusters, with a ledger refresh
    (and an optional chaos hook) folded into the loop."""
    quiet_since = None
    last_refresh = 0.0
    while time.monotonic() < deadline:
        if on_tick is not None:
            on_tick()
        now = time.monotonic()
        if now - last_refresh >= 0.2:
            last_refresh = now
            for cid, cell in cells.items():
                if ledger.alive(cid):
                    ledger.refresh_from(cid,
                                        cell["store"].list_nodes(),
                                        cell["store"].list_pods())
        progressed = False
        busy = not engine.injection_done.is_set()
        for cid, cell in cells.items():
            if not ledger.alive(cid):
                continue
            cell["sched"].queue.flush_backoff_completed()
            progressed |= bool(
                cell["bs"].run_batch(pop_timeout=0.002))
            busy |= cell["sched"].queue.pending_active_count() > 0
        now = time.monotonic()
        if progressed or busy:
            quiet_since = None
        elif quiet_since is None:
            quiet_since = now
        elif now - quiet_since >= settle_s:
            return
        time.sleep(0.002)
    raise TimeoutError("federation mini-cell did not quiesce")


def run_federation_mini_cell(
    clusters: int = 3,
    pods: int = 240,
    qps: float = 400.0,
    seed: int = 18,
    *,
    scenario: str = "loss-mid",
    node_cpu: int = 16,
    max_batch: int = 64,
    wait_timeout: float = 120.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """CI-fast federation cell: K in-process clusters under the open
    loop, with the cluster-loss seam modeled as "stop the cell's
    scheduler + mark its ledger dead + failover" (the spawned storm
    owns the real SIGKILL). Returns the verdict surface the tier-1
    tests assert on."""
    from kubernetes_tpu.federation import (
        CapacityLedger,
        ClusterRebalancer,
        FederatedClusterClient,
        FederationScheduler,
        HomeMap,
    )
    from kubernetes_tpu.observability import get_tracer
    from kubernetes_tpu.workloads.replay import ReplayEngine
    from kubernetes_tpu.workloads.trace import events_to_pods

    if scenario not in FEDERATION_SCENARIOS:
        raise ValueError(f"unknown federation scenario {scenario!r}")
    get_tracer().clear()
    rng = random.Random(seed)
    namespaces = 6
    trace = build_federation_trace(seed, pods, qps,
                                   namespaces=namespaces)
    sizing = _fleet_sizing(trace, clusters, node_cpu, scenario)
    samples = events_to_pods(trace.events[:64])
    cells = {cid: _inproc_cluster(cid, sizing[cid],
                                  max_batch, samples)
             for cid in range(clusters)}
    ledger = CapacityLedger()
    home_map = HomeMap(list(range(clusters)), pin={
        f"fed-{i}": i % clusters for i in range(namespaces)})
    fed_sched = FederationScheduler(ledger, home_of=home_map.home_of)
    fed_client = FederatedClusterClient(
        {cid: cell["store"] for cid, cell in cells.items()},
        fed_sched, ledger, home_map=home_map)
    for cid, cell in cells.items():
        ledger.refresh_from(cid, cell["store"].list_nodes(),
                            cell["store"].list_pods())
    rebalancer = ClusterRebalancer(fed_client, interval_s=0.1)
    engine = None
    victim: Optional[int] = None
    killed = [False]
    t_kill_rel = [0.0]
    orphans_unbound: List[str] = []
    try:
        engine = ReplayEngine(fed_client, trace, time_scale=1.0,
                              expire=False, progress=progress)
        t_start = time.monotonic()
        kill_at = _KILL_AT.get(scenario)
        if kill_at is not None:
            victim = (rng.randrange(1, clusters)
                      if scenario == "spill-loss" and clusters > 1
                      else rng.randrange(clusters))

        def on_tick() -> None:
            rebalancer.tick()
            if kill_at is None or killed[0]:
                return
            if time.monotonic() - t_start \
                    < kill_at * trace.duration_s \
                    and not engine.injection_done.is_set():
                return
            killed[0] = True
            with fed_client._lock:
                orphans = [name for (ns, name), cid
                           in fed_client._route.items()
                           if cid == victim]
            with engine._lock:
                bound_now = set(engine._bind)
            orphans_unbound[:] = [n for n in orphans
                                  if n not in bound_now]
            t_kill_rel[0] = time.monotonic() - t_start
            cells[victim]["sched"].stop()
            ledger.mark_dead(victim)
            if progress:
                progress(f"mini-cell: cluster {victim} down "
                         f"({len(orphans)} registered)")
            # the rebalancer's next tick observes the death and fires
            # failover through the driver
            rebalancer.tick()

        engine.start()
        _pump_cells(cells, engine, ledger,
                    time.monotonic() + wait_timeout, on_tick=on_tick)
        for cid, cell in cells.items():
            if victim is not None and cid == victim:
                continue
            cell["bs"].flush()
            cell["sched"].wait_for_inflight_bindings(timeout=30.0)
        # the engine observes binds through the watch fan-in, which
        # can lag the store by a delivery tick: settle until the
        # engine's bind ledger catches the server truth (bounded)
        want_bound = sum(
            1 for cid, cell in cells.items() if cid != victim
            for p in cell["store"].list_pods() if p.spec.node_name)
        settle_deadline = time.monotonic() + 10.0
        while time.monotonic() < settle_deadline:
            with engine._lock:
                got = len(engine._bind)
            if got >= want_bound:
                break
            time.sleep(0.02)
        per_cluster = _per_cluster_latency(engine, clusters)
        with engine._lock:
            bind_final = dict(engine._bind)
        stats = engine.finish()
        engine = None
        name_cluster: Dict[str, int] = {}
        for cid, cell in cells.items():
            if cid == victim:
                continue
            for p in cell["store"].list_pods():
                name_cluster[p.metadata.name] = cid
        recovered = sum(
            1 for n in orphans_unbound
            if n in bind_final
            and bind_final[n][0] - t_kill_rel[0] <= RECOVERY_BUDGET_S)
        counters = fed_client.counters()
        return {
            "injected": stats.injected,
            "ever_bound": stats.ever_bound,
            "lost": stats.lost,
            "p99_arrival_to_bind_ms": round(stats.latency_p99_ms()),
            "per_cluster": per_cluster,
            "gang_splits": _gang_splits(name_cluster, trace),
            "spilled": counters["spilled"],
            "failovers": counters["failovers"],
            "failover_replaced": counters["failover_replaced"],
            "fallback_placements": counters["fallback_placements"],
            "victim": victim,
            "orphans_unbound_at_kill": len(orphans_unbound),
            "recovery_ratio": (recovered / len(orphans_unbound)
                               if orphans_unbound else 1.0),
            "rebalancer_actions": [a["action"]["op"]
                                   for a in rebalancer.actions],
        }
    finally:
        if engine is not None:
            try:
                engine.finish()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        for cell in cells.values():
            try:
                cell["sched"].stop()
            except Exception:  # noqa: BLE001
                pass
        import gc

        gc.collect()


def run_degradation_differential(
    pods: int = 160,
    qps: float = 400.0,
    seed: int = 18,
    *,
    node_cpu: int = 16,
    max_batch: int = 64,
    wait_timeout: float = 120.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """The degradation invariant, held differentially: the SAME trace
    through a single-cluster federation with the layer UP and with the
    layer DOWN (every create degrades to home routing). Both arms must
    bind the bit-identical set of pod names — federation changes
    WHERE multi-cluster work lands, never WHETHER work binds."""
    from kubernetes_tpu.federation import (
        CapacityLedger,
        FederatedClusterClient,
        FederationScheduler,
        HomeMap,
    )
    from kubernetes_tpu.workloads.replay import ReplayEngine
    from kubernetes_tpu.workloads.trace import events_to_pods

    trace = build_federation_trace(seed, pods, qps, namespaces=4)
    samples = events_to_pods(trace.events[:64])
    sizing = _fleet_sizing(trace, 1, node_cpu, "spill")

    def arm(down: bool) -> Tuple[List[str], dict]:
        cells = {0: _inproc_cluster(0, sizing[0],
                                    max_batch, samples)}
        ledger = CapacityLedger()
        home_map = HomeMap([0])
        fed_sched = FederationScheduler(ledger,
                                        home_of=home_map.home_of)
        fed_sched.set_down(down)
        fed_client = FederatedClusterClient(
            {0: cells[0]["store"]}, fed_sched, ledger,
            home_map=home_map)
        ledger.refresh_from(0, cells[0]["store"].list_nodes(),
                            cells[0]["store"].list_pods())
        engine = None
        try:
            engine = ReplayEngine(fed_client, trace, time_scale=1.0,
                                  expire=False, progress=progress)
            engine.start()
            _pump_cells(cells, engine, ledger,
                        time.monotonic() + wait_timeout)
            cells[0]["bs"].flush()
            cells[0]["sched"].wait_for_inflight_bindings(timeout=30.0)
            stats = engine.finish()
            engine = None
            bound = sorted(
                p.metadata.name for p in cells[0]["store"].list_pods()
                if p.spec.node_name)
            return bound, {"lost": stats.lost,
                           "fallbacks":
                           fed_client.fallback_placements}
        finally:
            if engine is not None:
                try:
                    engine.finish()
                except Exception:  # noqa: BLE001
                    pass
            cells[0]["sched"].stop()

    bound_on, on_meta = arm(down=False)
    bound_down, down_meta = arm(down=True)
    return {
        "bound_on": bound_on,
        "bound_down": bound_down,
        "identical": bound_on == bound_down,
        "on": on_meta,
        "down": down_meta,
    }
