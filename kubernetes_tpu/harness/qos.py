"""Noisy-tenant QoS bench: multi-tenant overload against the REST
fabric, with API Priority & Fairness as the thing under test.

The row answers the question the headline number dodges: what happens
to the scheduler's 30k-pod burst when it does NOT have the apiserver to
itself? ``run_noisy_tenant_qos`` runs the SchedulingBasic REST workload
twice at the same scale —

- **solo**: the plain ``run_workload_rest`` arm (the REST row's own
  configuration) as the victim's baseline;
- **contended**: the same victim, plus ``tenants`` aggressor processes
  armed at measurement start, each an authenticated workload-level
  tenant mounting the three overload shapes from the chaos suite
  (sustained list storms, watch reconnect herds, bulk-verb abuse) from
  several threads, honoring nothing but its own 429s.

APF routes the victim's control-plane traffic (scheduler binds/status,
masters-exempt creators) past the aggressors' workload level, and fair
queuing inside the workload level keeps the aggressors from starving
each other. The row reports both arms' pods/s and p99, the ratio, and
the server's /debug/apf totals; the acceptance bar is
``p99_contended <= 2 x p99_solo`` with zero pods lost.

Aggressors are separate PROCESSES (spawn, jax-free) speaking raw
``http.client`` — no client-side rate limiting, no decode cost, just
request pressure on the server.
"""

from __future__ import annotations

import http.client
import json
import multiprocessing as mp
import random
import threading
import time
from typing import Callable, Dict, List, Optional

AGGRESSOR_SHAPES = ("liststorm", "watchherd", "bulkabuse")


def tenant_tokens(tenants: int) -> Dict[str, str]:
    return {f"qos-tenant-{i}-token": f"qos-tenant-{i}"
            for i in range(tenants)}


# ---------------------------------------------------------------------------
# aggressor child (spawned; must stay jax-free — see harness/__init__)


def _aggressor_thread(host: str, port: int, token: str, shape: str,
                      seed: int, stop, stats: dict, lock) -> None:
    rng = random.Random(seed)
    headers = {"Authorization": f"Bearer {token}"}
    bin_headers = dict(headers)
    bin_headers["Accept"] = "application/vnd.ktpu.binary"
    conn: Optional[http.client.HTTPConnection] = None
    seq = 0
    while not stop.is_set():
        try:
            if conn is None:
                conn = http.client.HTTPConnection(host, port, timeout=30)
            if shape == "liststorm":
                # sustained expensive lists — the shape width
                # estimation prices by recently served list sizes
                conn.request("GET", rng.choice(
                    ("/api/v1/pods", "/api/v1/nodes")),
                    headers=bin_headers)
                resp = conn.getresponse()
                resp.read()
                status = resp.status
            elif shape == "watchherd":
                # reconnect herd: attach (the headers arrive as soon as
                # the stream attaches — that attach is what charges
                # watch-init seats), linger a moment, drop, repeat
                conn.request(
                    "GET", "/api/v1/pods?watch=1&resourceVersion=0",
                    headers=headers)
                resp = conn.getresponse()
                status = resp.status
                time.sleep(rng.uniform(0.0, 0.02))
                conn.close()
                conn = None
            else:   # bulkabuse: wide bulk verbs, width must scale
                seq += 1
                items = [{"metadata": {
                    "name": f"ld-{seed}-{seq}-{i}",
                    "namespace": "default"}}
                    for i in range(200)]
                body = json.dumps({"kind": "ConfigMapList",
                                   "items": items}).encode()
                h = dict(headers)
                h["Content-Type"] = "application/json"
                h["X-Kubernetes-Request-Items"] = "200"
                conn.request("POST", "/api/v1/configmaps", body=body,
                             headers=h)
                resp = conn.getresponse()
                resp.read()
                status = resp.status
            with lock:
                stats["requests"] += 1
                if status == 429:
                    stats["throttled"] += 1
            if status == 429:
                time.sleep(0.02)    # hostile but not a pure spin
        except Exception:  # noqa: BLE001 — server pushed back hard
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None
            time.sleep(0.02)


def _aggressor_main(url: str, token: str, seed: int, stop,
                    threads: int = 6, ready=None) -> None:
    rest = url.split("://", 1)[1]
    host, _, port = rest.partition(":")
    stats = {"requests": 0, "throttled": 0}
    lock = threading.Lock()
    workers = [
        threading.Thread(
            target=_aggressor_thread,
            args=(host, int(port or 80), token,
                  AGGRESSOR_SHAPES[i % len(AGGRESSOR_SHAPES)],
                  seed * 1000 + i, stop, stats, lock),
            daemon=True)
        for i in range(threads)
    ]
    for w in workers:
        w.start()
    if ready is not None:
        # interpreter spawn costs ~1s+; the parent gates measurement on
        # this signal so the contended arm never measures an
        # uncontended server
        ready.set()
    stop.wait()
    for w in workers:
        w.join(timeout=2.0)


# ---------------------------------------------------------------------------
# the bench row


def _apf_summary(snap: Optional[dict]) -> dict:
    if not snap:
        return {}
    out = {"rejections": 0, "levels": {}}
    for name, lv in (snap.get("levels") or {}).items():
        rejected = sum((lv.get("rejected") or {}).values())
        out["rejections"] += rejected
        out["levels"][name] = {
            "dispatched": lv.get("dispatched_total", 0),
            "seats_dispatched": lv.get("seats_dispatched_total", 0),
            "rejected": rejected,
            "peak_executing_seats": lv.get("peak_executing_seats", 0),
            "capacity": lv.get("capacity", 0),
        }
    return out


def run_noisy_tenant_qos(
    nodes: int,
    measure_pods: int,
    tenants: int = 3,
    qps: Optional[float] = 5000.0,
    max_batch: int = 4096,
    aggressor_threads: int = 6,
    seed: int = 7,
    wait_timeout: float = 1200.0,
    progress: Optional[Callable[[str], None]] = None,
    result_hook=None,
    solo_baseline: Optional[dict] = None,
) -> dict:
    """One QoS bench row (see module doc). Returns the BENCH JSON dict;
    ``qos_ok`` is the acceptance verdict (victim p99 within 2x solo,
    all pods bound in both arms). ``solo_baseline`` (keys
    ``pods_per_sec``, ``p99_latency_ms``) skips the solo arm — the
    default bench matrix passes the adjacent REST row's numbers, which
    measure the identical solo configuration, instead of paying a third
    full-scale run."""
    from kubernetes_tpu.harness.rest_perf import run_workload_rest

    def note(msg: str) -> None:
        if progress:
            progress(f"[qos] {msg}")

    if solo_baseline is not None:
        solo_rate = float(solo_baseline["pods_per_sec"])
        p99_solo = float(solo_baseline["p99_latency_ms"])
        solo_bound = True
        note(f"solo baseline (from the REST row): {solo_rate:.1f} "
             f"pods/s p99 {p99_solo:.0f}ms")
    else:
        note(f"solo arm: SchedulingBasic {nodes} nodes / "
             f"{measure_pods} pods over REST")
        solo = run_workload_rest(
            "SchedulingBasic", nodes=nodes, measure_pods=measure_pods,
            max_batch=min(measure_pods, max_batch), qps=qps,
            wait_timeout=wait_timeout, progress=progress,
            result_hook=result_hook)
        solo_rate = solo.pods_per_second
        p99_solo = solo.metrics.get("Perc99", 0.0)
        solo_bound = solo.metrics.get("server_pods_bound", 0) \
            >= measure_pods

    tokens = tenant_tokens(tenants)
    ctx = mp.get_context("spawn")
    procs: List = []
    stop_evt = ctx.Event()

    def start_aggressors(url: str) -> Callable[[], None]:
        note(f"arming {tenants} aggressor tenants x "
             f"{aggressor_threads} threads (list storms, watch herds, "
             f"bulk abuse)")
        ready_evts = []
        for i, token in enumerate(tokens):
            ready = ctx.Event()
            p = ctx.Process(
                target=_aggressor_main,
                args=(url, token, seed + i, stop_evt, aggressor_threads,
                      ready),
                daemon=True)
            p.start()
            procs.append(p)
            ready_evts.append(ready)
        # block until every aggressor fleet is firing: the measured
        # window must be contended from its first pod
        for ready in ready_evts:
            if not ready.wait(60.0):
                note("WARNING: an aggressor process never came up")
        note("aggressors firing")

        def stop() -> None:
            stop_evt.set()
            for p in procs:
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()

        return stop

    note("contended arm: same victim burst under aggressor load")
    contended = run_workload_rest(
        "SchedulingBasic", nodes=nodes, measure_pods=measure_pods,
        max_batch=min(measure_pods, max_batch), qps=qps,
        wait_timeout=wait_timeout, progress=progress,
        result_hook=result_hook,
        extra_tokens=tokens, on_measure_start=start_aggressors)

    p99_contended = contended.metrics.get("Perc99", 0.0)
    ratio = (p99_contended / p99_solo) if p99_solo > 0 else 0.0
    all_bound = (
        solo_bound
        and contended.metrics.get("server_pods_bound", 0) >= measure_pods)
    apf = _apf_summary(contended.metrics.get("apf"))
    note(f"victim: solo {solo_rate:.1f} pods/s "
         f"p99 {p99_solo:.0f}ms -> contended "
         f"{contended.pods_per_second:.1f} pods/s "
         f"p99 {p99_contended:.0f}ms (ratio {ratio:.2f}); "
         f"apf rejections {apf.get('rejections', 0)}")
    return {
        "metric": f"noisy_tenant_qos[SchedulingBasic {nodes}nodes/"
                  f"{measure_pods}pods, {tenants} aggressor tenants x "
                  f"{aggressor_threads} threads list/watch/bulk]",
        "value": round(contended.pods_per_second, 1),
        "unit": "pods/s",
        "p99_latency_ms": round(p99_contended),
        "solo_pods_per_sec": round(solo_rate, 1),
        "solo_p99_latency_ms": round(p99_solo),
        "p99_ratio_vs_solo": round(ratio, 2),
        "qos_ok": bool(all_bound and (p99_solo <= 0
                                      or p99_contended <= 2.0 * p99_solo)),
        "server_pods_bound": contended.metrics.get("server_pods_bound"),
        "apf": apf,
    }
