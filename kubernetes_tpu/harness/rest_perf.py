"""scheduler_perf over the REAL API fabric (VERDICT r4 missing #1).

The reference's scheduler_perf runs an in-process apiserver + real etcd
and every client goes through REST at QPS/Burst 5000
(``test/integration/scheduler_perf/util.go:61-68``,
``test/integration/util/util.go:57``). The store-direct harness
(``perf.py``) deliberately excludes that cost; this harness includes it:

- **apiserver process**: ClusterStore + WAL (the etcd analog) served by
  ``APIServer`` — authn (bearer tokens), RBAC bootstrap policy,
  admission, watch cache, max-in-flight lanes all live.
- **creator process(es)**: build workload objects from the same
  declarative ops and POST them through ``RestClusterClient`` — bulk
  {Kind}List bodies whose token bucket charges PER OBJECT, so the wire
  discipline is the reference's per-client 5000 QPS regardless of
  batching.
- **scheduler (this process, owns the TPU)**: fed by watch-driven
  list+watch streams over chunked HTTP (server-coalesced binary
  chunks, O(batches) syscalls), binds through bulk BindingList
  requests shipped on the binding pool (cycles never serialize on the
  bind round trip), bulk PodStatusList for status sweeps — all via the
  binary codec. "Scheduled" events ride a SEPARATE client+bucket, the
  reference's own events-client discipline.

Process topology mirrors the reference deployment (apiserver, client,
scheduler are separate processes); it also gives each Python runtime
its own GIL, which is what the reference gets for free from Go.

Throughput is counted from the scheduler's commit metric (successful
REST binds); at the end the apiserver process REPORTS its own
bound-pod count and the two must agree — the measured number is
store-truth, not client-side optimism.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import tempfile
import time
from typing import Callable, List, Optional

from kubernetes_tpu.harness.workloads import make_workload

SCHEDULER_TOKEN = "rest-perf-scheduler-token"
CREATOR_TOKEN = "rest-perf-creator-token"


# ---------------------------------------------------------------------------
# child mains (spawned; must stay jax-free — see harness/__init__)


def _apiserver_main(conn, wal_dir: Optional[str],
                    extra_tokens: Optional[dict] = None) -> None:
    from kubernetes_tpu.apiserver.rbac import provision_bootstrap_policy
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.apiserver.wal import attach_wal
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    profiler = _maybe_profiler("apiserver")
    tune_for_throughput()
    store = ClusterStore()
    # async WAL writer: serialization rides a background thread instead
    # of every request's critical section (etcd pipelines raft appends
    # the same way); bounded loss window on crash, same as fsync=False
    wal = attach_wal(store, wal_dir, snapshot_every=200_000,
                     async_serialize=True) if wal_dir else None
    authz = provision_bootstrap_policy(store)
    authz.add_user_to_group("perf-creator", "system:masters")
    tokens = {SCHEDULER_TOKEN: "system:kube-scheduler",
              CREATOR_TOKEN: "perf-creator"}
    # extra identities (the noisy-tenant QoS harness's aggressor
    # tenants): authenticated but NOT control-plane/masters, so APF
    # routes them to the workload level, one fair-queued flow each.
    # They get a viewer-ish role — enough to mount list storms, watch
    # herds, and bulk ConfigMap abuse, nothing privileged.
    tokens.update(extra_tokens or {})
    if extra_tokens:
        from kubernetes_tpu.api.types import (
            ClusterRole, ClusterRoleBinding, ObjectMeta, PolicyRule,
            RBACSubject, RoleRef,
        )

        store.add_cluster_role(ClusterRole(
            metadata=ObjectMeta(name="qos-tenant"),
            rules=[PolicyRule(verbs=["get", "list", "watch"],
                              resources=["pods", "nodes", "services"]),
                   PolicyRule(verbs=["get", "list", "watch", "create"],
                              resources=["configmaps"])]))
        store.add_cluster_role_binding(ClusterRoleBinding(
            metadata=ObjectMeta(name="qos-tenants"),
            subjects=[RBACSubject(kind="User", name=u)
                      for u in extra_tokens.values()],
            role_ref=RoleRef(kind="ClusterRole", name="qos-tenant")))
    server = APIServer(
        store=store,
        authorizer=authz,
        tokens=tokens,
    ).start()
    conn.send(server.url)
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if msg == "counts":
            pods = store.list_pods()
            if wal is not None:
                wal.drain()   # async writer: count only settled bytes
            conn.send({
                "pods_total": len(pods),
                "pods_bound": sum(1 for p in pods if p.spec.node_name),
                "wal_entries": _wal_lines(wal_dir),
            })
    server.shutdown_server()
    if wal is not None:
        wal.close()
    _stop_profiler(profiler)
    conn.send("stopped")


class _SamplingProfiler:
    """All-threads stack sampler for the spawned fabric children (the
    parent's profiler cannot see them, and cProfile only observes the
    thread that enabled it — useless for a thread-per-connection
    server). Samples ``sys._current_frames()`` on an interval and dumps
    a self-time histogram per function to
    ``$KTPU_PROFILE_REST/<role>.txt`` on shutdown."""

    def __init__(self, role: str, interval: float = 0.002):
        import collections
        import threading

        self.role = role
        self.interval = interval
        self.counts: dict = collections.Counter()
        self.samples = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"profiler-{role}")
        self._thread.start()

    def _run(self) -> None:
        import sys
        import time as _time

        me = self._thread.ident
        while not self._stop.is_set():
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                code = frame.f_code
                key = (f"{code.co_filename.rsplit('/', 1)[-1]}:"
                       f"{code.co_firstlineno}:{code.co_name}")
                self.counts[key] += 1
                self.samples += 1
            _time.sleep(self.interval)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        out = os.environ.get("KTPU_PROFILE_REST", "")
        try:
            os.makedirs(out, exist_ok=True)
            with open(os.path.join(out, f"{self.role}.txt"), "w") as f:
                f.write(f"samples={self.samples}\n")
                for key, n in sorted(self.counts.items(),
                                     key=lambda kv: -kv[1])[:60]:
                    f.write(f"{n:8d}  {key}\n")
        except OSError:
            pass


def _maybe_profiler(role: str):
    if not os.environ.get("KTPU_PROFILE_REST"):
        return None
    return _SamplingProfiler(role)


def _stop_profiler(profiler) -> None:
    if profiler is not None:
        profiler.stop()


def _wal_lines(wal_dir: Optional[str]) -> int:
    if not wal_dir:
        return 0
    path = os.path.join(wal_dir, "wal.jsonl")
    try:
        with open(path, "rb") as f:
            return sum(1 for _ in f)
    except OSError:
        return 0


def _real_failures(resp) -> list:
    """Bulk-create failures that are NOT 409s. The client retries a
    dropped keep-alive; a create applied server-side before the drop
    comes back AlreadyExists on the retry — for a creator whose goal is
    'these pods exist', that IS success, not a row-aborting error."""
    return [f for f in (resp.get("failures") or ())
            if f.get("code") != 409]


def _creator_main(conn, url: str, name: str, nodes: int, init_pods: int,
                  measure_pods: int, qps: Optional[float],
                  n_clients: int) -> None:
    """Executes create ops on demand. ``n_clients`` round-robins pod
    creation across that many QPS-capped clients (each with its OWN
    5000-QPS bucket, the reference's per-client discipline)."""
    from kubernetes_tpu.api.types import Node, Pod
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.harness.burst import stream_arrivals

    profiler = _maybe_profiler(f"creator-{name}")
    clients = [RestClusterClient(url, token=CREATOR_TOKEN, qps=qps)
               for _ in range(max(1, n_clients))]
    ops = make_workload(name, nodes=nodes, init_pods=init_pods,
                        measure_pods=measure_pods)
    CHUNK = 512
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        op_idx = msg
        op = ops[op_idx]
        if op["opcode"] == "createNodes":
            objs = [Node.from_dict(op["nodeTemplate"](i))
                    for i in range(op["count"])]
            for lo in range(0, len(objs), CHUNK):
                chunk = objs[lo:lo + CHUNK]
                code, resp = clients[0]._request(
                    "POST", "/api/v1/nodes",
                    {"kind": "NodeList", "items": chunk},
                    charge=len(chunk))
                if code >= 400 or _real_failures(resp):
                    conn.send(("error", op_idx, str(resp)[:500]))
                    break
            else:
                conn.send(("done", op_idx, len(objs)))
            continue
        if op["opcode"] == "createPods":
            template = op["podTemplate"]
            offset = op.get("offset", 0)
            count = op["count"]
            # the shared open-loop injection helper at rate=∞: lazy
            # per-chunk pod construction, per-chunk client rotation —
            # the same loop the replay engine paces with real due times
            rotation = [0]

            def send(items):
                client = clients[rotation[0] % len(clients)]
                rotation[0] += 1
                code, resp = client._request(
                    "POST", "/api/v1/namespaces/default/pods",
                    {"kind": "PodList", "items": items},
                    charge=len(items))
                if code >= 400 or _real_failures(resp):
                    raise RuntimeError(str(resp)[:500])

            try:
                sent = stream_arrivals(
                    ((0.0, Pod.from_dict(template(offset + i)))
                     for i in range(count)),
                    send, chunk=CHUNK, time_scale=0.0)
                conn.send(("done", op_idx, sent))
            except RuntimeError as e:
                conn.send(("error", op_idx, str(e)))
            continue
        conn.send(("done", op_idx, 0))
    _stop_profiler(profiler)
    conn.send("stopped")


# ---------------------------------------------------------------------------
# parent (scheduler + TPU)


def run_workload_rest(
    name: str,
    nodes: int,
    measure_pods: int,
    init_pods: int = 0,
    max_batch: int = 4096,
    qps: Optional[float] = 5000.0,
    n_creator_clients: int = 2,
    use_batch: bool = True,
    wait_timeout: float = 1200.0,
    wal: bool = True,
    progress: Optional[Callable[[str], None]] = None,
    result_hook: Optional[Callable[[object, object], None]] = None,
    extra_tokens: Optional[dict] = None,
    on_measure_start: Optional[Callable[[str], Callable[[], None]]] = None,
):
    """Run one workload with every byte crossing the REST fabric.
    Returns a ``BenchmarkResult`` whose ``metrics`` carry the apiserver
    process's own final counts for cross-checking."""
    from kubernetes_tpu.api.types import Pod
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.harness.perf import (
        BenchmarkResult,
        ThroughputCollector,
    )
    from kubernetes_tpu.observability import get_tracer
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    # per-row flight-recorder + devprof windows (diag line + the row's
    # ``telemetry`` sub-object; the scheduler — and so the solver —
    # runs in THIS process, only the apiserver/creators are children)
    get_tracer().clear()
    from kubernetes_tpu.harness.perf import (
        attach_slo_baseline,
        collect_critical_path,
        collect_freshness,
        reset_sli_window,
    )
    from kubernetes_tpu.observability.devprof import get_devprof

    get_devprof().reset(workload=f"{name}/rest")
    reset_sli_window()
    ctx = mp.get_context("spawn")
    wal_dir = tempfile.mkdtemp(prefix="ktpu-wal-") if wal else None

    api_conn, api_child = ctx.Pipe()
    api_proc = ctx.Process(target=_apiserver_main,
                           args=(api_child, wal_dir, extra_tokens),
                           daemon=True)
    api_proc.start()
    url = api_conn.recv()

    cre_conn, cre_child = ctx.Pipe()
    cre_proc = ctx.Process(
        target=_creator_main,
        args=(cre_child, url, name, nodes, init_pods, measure_pods, qps,
              n_creator_clients),
        daemon=True)
    cre_proc.start()

    client = RestClusterClient(url, token=SCHEDULER_TOKEN, qps=qps)
    # the recorder's "Scheduled" events ride their OWN client+bucket
    # (the reference scheduler's separate events client): sharing the
    # bind client's bucket would charge ~1 token per scheduled pod
    # against the bind budget — rate the reference never pays
    event_client = RestClusterClient(url, token=SCHEDULER_TOKEN, qps=qps)
    gates = FeatureGates({"TPUBatchScheduler": use_batch})
    sched = Scheduler.create(client, feature_gates=gates,
                             provider="GangSchedulingProvider",
                             event_client=event_client)
    bs = attach_batch_scheduler(sched, max_batch=max_batch) \
        if use_batch else None
    attach_slo_baseline(sched)
    # live SLO evaluation while the fabric runs: the engine's tick
    # thread samples the SLIs so a mid-run burn-rate breach fires its
    # flight-recorder dump DURING the run, not at the postmortem
    from kubernetes_tpu.observability.slo import get_slo_engine

    slo_engine = get_slo_engine()
    if slo_engine.enabled:
        slo_engine.start(interval_s=1.0)
    sched.start()

    def bound_count() -> int:
        s = sched.metrics.e2e_scheduling_duration._series.get(
            ("scheduled",))
        return s[2] if s else 0

    def run_op(op_idx: int) -> int:
        cre_conn.send(op_idx)
        # pump the scheduler while the creator streams objects in
        while not cre_conn.poll(0.0):
            if bs is not None:
                bs.run_batch(pop_timeout=0.01)
            else:
                if not sched.schedule_one(pop_timeout=0.01):
                    time.sleep(0.002)
        status, _idx, n = cre_conn.recv()
        if status == "error":
            raise RuntimeError(f"creator op {op_idx} failed: {n}")
        return n

    def pump_until(target: int, deadline: float) -> None:
        while time.monotonic() < deadline:
            sched.queue.flush_backoff_completed()
            progressed = bs.run_batch(pop_timeout=0.01) if bs is not None \
                else sched.schedule_one(pop_timeout=0.01)
            if bound_count() >= target:
                return
            if not progressed:
                time.sleep(0.002)
        raise TimeoutError(
            f"workload {name}: bound {bound_count()}/{target} "
            f"before deadline")

    def teardown_children() -> None:
        """Always runs — a failed row must not leak an apiserver process
        holding a 30k-pod store (or its WAL tempdir) into the next
        matrix row."""
        try:
            cre_conn.send("stop")
        except (BrokenPipeError, OSError):
            pass
        try:
            api_conn.send("stop")
        except (BrokenPipeError, OSError):
            pass
        for conn, proc in ((cre_conn, cre_proc), (api_conn, api_proc)):
            try:
                if conn.poll(5.0):
                    conn.recv()
            except (EOFError, OSError):
                pass
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.terminate()
        if wal_dir:
            import shutil

            shutil.rmtree(wal_dir, ignore_errors=True)

    collector = None
    measure_start = 0.0
    expected_bound = 0
    created_pods = 0
    federation_instances: List[str] = []
    stop_companions: Optional[Callable[[], None]] = None
    ops = make_workload(name, nodes=nodes, init_pods=init_pods,
                        measure_pods=measure_pods)
    try:
        for i, op in enumerate(ops):
            opcode = op["opcode"]
            if opcode == "createNodes":
                run_op(i)
                # the cache learns nodes via the watch stream; solving
                # before they land would decline the first batches
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline and \
                        sched.cache.node_count() < op["count"]:
                    time.sleep(0.02)
                if progress:
                    progress(f"{name}/rest: {sched.cache.node_count()} "
                             f"nodes")
            elif opcode == "createPods":
                collect = op.get("collectMetrics", False)
                if collect and bs is not None:
                    from kubernetes_tpu.ops.encode import is_host_only

                    template = op["podTemplate"]
                    offset = op.get("offset", 0)
                    samples = [Pod.from_dict(template(offset + j))
                               for j in range(min(200, op["count"]))]
                    samples = [p for p in samples
                               if not is_host_only(p, client)]
                    warm = bs.warmup(sample_pods=samples) if samples \
                        else 0.0
                    if progress and warm > 0.05:
                        progress(f"{name}/rest: solver warmup {warm:.1f}s")
                if collect:
                    if on_measure_start is not None \
                            and stop_companions is None:
                        # companion load (the QoS harness's aggressor
                        # tenants) starts exactly when measurement does
                        # and runs through the whole measured window
                        stop_companions = on_measure_start(url)
                    collector = ThroughputCollector(count_fn=bound_count)
                    measure_start = time.monotonic()
                    collector.start()
                n = run_op(i)
                created_pods += n
                if progress:
                    progress(f"{name}/rest: {created_pods} pods created")
                if not op.get("skipWaitToCompletion", False):
                    expected_bound += n
                    pump_until(expected_bound,
                               time.monotonic() + wait_timeout)
            elif opcode == "barrier":
                pump_until(expected_bound, time.monotonic() + wait_timeout)
        if bs is not None:
            bs.flush()
        sched.wait_for_inflight_bindings(timeout=30.0)
        duration = time.monotonic() - measure_start if measure_start \
            else 0.0
        if stop_companions is not None:
            stop_companions()
            stop_companions = None
        # cross-process metrics, the generic path: scrape the child
        # apiserver's /metrics, parse the exposition, and merge EVERY
        # family into the federation under an ``instance`` label —
        # fold=True also folds the child's counters (the APF rejections
        # among them) into this process's same-name counters by
        # cumulative delta, so bench.py's diag segments keep reading
        # their usual local series with no per-family absorb mapping.
        # The /debug/apf JSON snapshot is fetched ONLY for the diag
        # line's queue-wait/peak-seat numbers (server-side histogram
        # state a counter fold cannot reconstruct).
        apf_snapshot = None
        from kubernetes_tpu.metrics import default_registry
        from kubernetes_tpu.metrics.apf_metrics import apf_metrics
        from kubernetes_tpu.metrics.federation import metrics_federation

        # the fold lands only on counters THIS process has declared —
        # instantiate the APF families before scraping (the legacy
        # absorb path did this implicitly)
        apfm = apf_metrics()
        fed = metrics_federation()
        # each row spawns a FRESH apiserver under the same instance
        # name: forget the previous child's series AND fold baselines
        # so this child's totals fold in full (not as a bogus delta)
        fed.forget_instance("apiserver")
        fed.forget_instance("scheduler")
        fed.scrape(url, instance="apiserver", token=SCHEDULER_TOKEN,
                   fold=True)
        # the parent is a component too: mirror its registry through
        # the same render→parse path so the merged view is complete —
        # independently of the child scrape, which is best-effort (a
        # dying child must not erase the parent from the merged view)
        fed.absorb_registry(default_registry(), instance="scheduler")
        federation_instances = sorted(fed.instances())
        try:
            code, snap = client._request("GET", "/debug/apf")
            if code == 200 and isinstance(snap, dict):
                apf_snapshot = snap
                apfm.last_snapshot = snap
        except Exception:  # noqa: BLE001 — introspection is best-effort
            pass
        # fleet trace: scrape the child's /debug/trace ring (with the
        # half-RTT clock-offset handshake) while it is still alive,
        # merge with this process's ring, and attribute the sampled
        # pods' critical path — best-effort like the metrics scrape
        critpath, fleet_doc = collect_critical_path(
            remote=[("apiserver", url)], token=SCHEDULER_TOKEN)
        trace_out = os.environ.get("KTPU_FLEET_TRACE_OUT")
        if trace_out and fleet_doc is not None:
            try:
                with open(trace_out, "w") as f:
                    json.dump(fleet_doc, f)
            except Exception:  # noqa: BLE001
                pass
        if result_hook is not None:
            result_hook(sched, bs)
    except BaseException:
        if stop_companions is not None:
            stop_companions()
        teardown_children()
        raise
    finally:
        if collector:
            collector.stop()
        if slo_engine.enabled:
            slo_engine.stop()
        sched.stop()

    # cross-check against the apiserver's own truth (and WAL durability)
    try:
        api_conn.send("counts")
        server_counts = api_conn.recv()
    finally:
        teardown_children()

    measured = sum(op["count"] for op in ops
                   if op["opcode"] == "createPods"
                   and op.get("collectMetrics"))
    e2e = sched.metrics.e2e_scheduling_duration
    metrics = {
        "Perc50": e2e.quantile(0.50, "scheduled") * 1000,
        "Perc90": e2e.quantile(0.90, "scheduled") * 1000,
        "Perc99": e2e.quantile(0.99, "scheduled") * 1000,
        "server_pods_bound": server_counts["pods_bound"],
        "server_pods_total": server_counts["pods_total"],
        "wal_entries": server_counts["wal_entries"],
        "scheduler_bound": bound_count(),
        "apf": apf_snapshot,
        "federation_instances": federation_instances,
    }
    if server_counts["pods_bound"] < expected_bound:
        raise RuntimeError(
            f"store truth disagrees: server bound "
            f"{server_counts['pods_bound']} < expected {expected_bound}")
    dp = get_devprof()
    telemetry = dp.summary() if dp.enabled else {}
    return BenchmarkResult(
        name=f"{name}/rest",
        total_pods=created_pods,
        measured_pods=measured,
        duration_seconds=duration,
        pods_per_second=(measured / duration) if duration > 0 else 0.0,
        throughput=collector.summary() if collector else {},
        metrics=metrics,
        telemetry=telemetry,
        freshness=collect_freshness(telemetry),
        critical_path=critpath,
    )
