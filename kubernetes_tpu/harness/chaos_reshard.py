"""Seeded chaos cells for live partition resharding.

Three scenario families (``tools/chaos_matrix.py --suite reshard``):

- ``midstorm`` — slice migrations (move → split → move) run while
  seeded writer threads storm creates/status-writes/deletes through an
  elastic client. Invariants: zero lost pods, zero duplicated objects
  across partitions, NO double-delivered watch events ((type, key, rv)
  observed at most once by a raw recording watcher), recorder state ≡
  server truth at quiesce, one topology epoch fleet-wide.

- ``sigkill`` — a REAL partition server process is SIGKILLed at a
  seeded phase of a live migration (after the copy, or just before the
  flip; source or destination). The coordinator must ROLL BACK or
  COMPLETE — never leave a torn routing table. The corpse restarts
  from its WAL segment, ``reroute_after_restart`` re-points the
  topology, and clients ride their cursors through the gap. Invariants:
  every confirmed pod present exactly once, a single max epoch on
  every live server, zero duplicates.

- ``rebalance`` — the PartitionRebalancer under a hot-namespace storm:
  it must ACT (split the tenant), placement must actually spread, and
  the zero-loss/no-dup invariants hold throughout.

Cells are compressed (seconds each); the hotspot bench row is the
full-scale proof.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.harness.burst import make_burst_pods

RESHARD_SCENARIOS = ("midstorm", "sigkill", "rebalance")

POD_CPU_MILLI = 100
POD_MEMORY = "50Mi"

SCHEDULER_TOKEN = "reshard-scheduler-token"
CREATOR_TOKEN = "reshard-creator-token"


# ---------------------------------------------------------------------------
# shared plumbing


def _spin_inproc_servers(n: int):
    """In-process apiserver threads (real HTTP; loopback trust)."""
    from kubernetes_tpu.apiserver.partition import PartitionTopology
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore

    servers = [APIServer(store=ClusterStore(), partition=(i, n)).start()
               for i in range(n)]
    urls = [s.url for s in servers]
    topo = PartitionTopology.default(n, urls=urls)
    for s in servers:
        s.install_topology(topo)
    return servers, urls


class _Recorder:
    """Raw watch consumer counting (type, key, rv) deliveries — the
    no-double-delivery invariant's witness — and folding them into a
    state map (the cache≡store check)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.seen: Dict[tuple, int] = {}
        self.state: Dict[tuple, str] = {}

    def on_events(self, events) -> None:
        with self.lock:
            for e in events:
                key = (getattr(e.obj.metadata, "namespace", ""),
                       e.obj.metadata.name)
                sig = (e.type, key, e.obj.metadata.resource_version)
                self.seen[sig] = self.seen.get(sig, 0) + 1
                if e.type == "DELETED":
                    self.state.pop(key, None)
                else:
                    self.state[key] = e.obj.metadata.resource_version

    def doubles(self) -> List[tuple]:
        with self.lock:
            return [s for s, n in self.seen.items() if n > 1]


def _server_union(servers) -> Tuple[Dict[tuple, str], int]:
    union: Dict[tuple, str] = {}
    dups = 0
    for s in servers:
        for p in s.store.list_pods():
            key = (p.namespace, p.metadata.name)
            if key in union:
                dups += 1
            union[key] = p.metadata.resource_version
    return union, dups


# ---------------------------------------------------------------------------
# midstorm: migrations under a seeded write/update/delete storm


def run_reshard_midstorm(seed: int, nodes: int = 20, pods: int = 120,
                         wait_timeout: float = 120.0,
                         progress: Optional[Callable] = None) -> Dict:
    from kubernetes_tpu.apiserver.reshard import ReshardCoordinator
    from kubernetes_tpu.client.restcluster import RestClusterClient

    rng = random.Random(seed)
    servers, urls = _spin_inproc_servers(3)
    writer_client = RestClusterClient(urls[0], partition_urls=urls,
                                      watch_kinds=("Pod",))
    watch_client = RestClusterClient(urls[0], partition_urls=urls,
                                     watch_kinds=("Pod",))
    recorder = _Recorder()
    stats = {"created": 0, "deleted": 0, "statuses": 0, "failures": 0}
    alive: Dict[tuple, bool] = {}
    alive_lock = threading.Lock()
    try:
        writer_client.enable_topology(poll_interval=0.1)
        watch_client.enable_topology(poll_interval=0.1)
        watch_client.watch(lambda e: recorder.on_events([e]),
                           batch_fn=recorder.on_events)
        time.sleep(0.3)
        coordinator = ReshardCoordinator(writer_client, freeze_eta=5.0,
                                         evict_grace_s=0.05)
        namespaces = [f"storm-{i}" for i in range(10)]
        stop = threading.Event()
        errors: List[str] = []

        def writer(tid: int) -> None:
            wrng = random.Random(seed * 100 + tid)
            i = 0
            while not stop.is_set():
                op = wrng.random()
                try:
                    if op < 0.65 or stats["created"] < 10:
                        ns = wrng.choice(namespaces)
                        pod = make_burst_pods(
                            1, cpu_milli=POD_CPU_MILLI,
                            memory=POD_MEMORY,
                            name_prefix=f"st{tid}-",
                            uid_prefix=f"su{tid}-", offset=i,
                            namespaces=[ns])[0]
                        writer_client.create_object("Pod", pod)
                        with alive_lock:
                            alive[(ns, pod.metadata.name)] = True
                            stats["created"] += 1
                        i += 1
                    else:
                        with alive_lock:
                            keys = list(alive)
                        if not keys:
                            continue
                        key = wrng.choice(keys)
                        if op < 0.85:
                            writer_client.set_pod_phase(
                                key[0], key[1], "Running")
                            stats["statuses"] += 1
                        else:
                            writer_client.delete_pod(key[0], key[1])
                            with alive_lock:
                                alive.pop(key, None)
                                stats["deleted"] += 1
                except Exception as e:  # noqa: BLE001 — storms may
                    # race a delete; count, don't die
                    stats["failures"] += 1
                    errors.append(f"{type(e).__name__}: {e}")
                    if len(errors) > 50:
                        return
                time.sleep(0.002)

        threads = [threading.Thread(target=writer, args=(t,),
                                    daemon=True) for t in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)

        # three migrations mid-storm, seeded shapes
        topo = coordinator.fetch_topology()
        slots0 = topo.slots_of_partition(0)
        moved = rng.sample(slots0, min(8, len(slots0)))
        rep1 = coordinator.move_slots({s: 1 for s in moved})
        time.sleep(0.3)
        hot_ns = rng.choice(namespaces)
        rep2 = coordinator.spread_namespace(hot_ns)
        time.sleep(0.3)
        topo = coordinator.fetch_topology()
        slots1 = topo.slots_of_partition(1)
        back = rng.sample(slots1, min(6, len(slots1)))
        rep3 = coordinator.move_slots({s: 2 for s in back})
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        time.sleep(1.0)   # quiesce

        union, dups = _server_union(servers)
        with alive_lock:
            expected = dict(alive)
        missing = [k for k in expected if k not in union]
        unexpected = [k for k in union if k not in expected]
        # recorder ≡ store at quiesce
        rec_missing = [k for k in union if k not in recorder.state]
        rec_stale = [k for k, rv in union.items()
                     if recorder.state.get(k) not in (None, rv)]
        rec_extra = [k for k in recorder.state if k not in union]
        doubles = recorder.doubles()
        epochs = {s.partition_topology.epoch for s in servers
                  if s.partition_topology is not None}
        ok = (not missing and not unexpected and dups == 0
              and not doubles and not rec_missing and not rec_stale
              and not rec_extra and len(epochs) == 1
              and stats["failures"] == 0
              and writer_client.rv_regressions == [])
        return {
            "seed": seed, "profile": "midstorm", "ok": ok,
            "failure": "" if ok else (
                f"missing={len(missing)} unexpected={len(unexpected)} "
                f"dups={dups} doubles={len(doubles)} "
                f"rec_missing={len(rec_missing)} "
                f"rec_stale={len(rec_stale)} "
                f"rec_extra={len(rec_extra)} epochs={sorted(epochs)} "
                f"failures={stats['failures']} "
                f"errs={errors[:2]}"),
            "stats": {
                "created": stats["created"],
                "deleted": stats["deleted"],
                "statuses": stats["statuses"],
                "moved": (rep1["moved_objects"] + rep2["moved_objects"]
                          + rep3["moved_objects"]),
                "migrations": 3,
                "frozen_ms": round(rep1["frozen_ms"]
                                   + rep2["frozen_ms"]
                                   + rep3["frozen_ms"], 1),
            },
        }
    finally:
        watch_client._stop_watches()
        writer_client._stop_watches()
        watch_client._drop_conn()
        writer_client._drop_conn()
        for s in servers:
            s.shutdown_server()


# ---------------------------------------------------------------------------
# sigkill: a partition process dies mid-migration (real processes + WAL)


def _chaos_apiserver_main(conn, index: int, count: int, wal_dir: str,
                          restore: bool) -> None:
    """Partition server child with SYNCHRONOUS WAL (a SIGKILL must not
    lose acknowledged writes) and restore support (the failover
    path)."""
    from kubernetes_tpu.apiserver.rbac import provision_bootstrap_policy
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.apiserver.wal import attach_wal, restore_store
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    store = ClusterStore()
    if restore:
        restore_store(wal_dir, store)
    wal = attach_wal(store, wal_dir, snapshot_every=100_000,
                     async_serialize=False)
    authz = provision_bootstrap_policy(store)
    authz.add_user_to_group("reshard-creator", "system:masters")
    tokens = {SCHEDULER_TOKEN: "system:kube-scheduler",
              CREATOR_TOKEN: "reshard-creator"}
    server = APIServer(store=store, authorizer=authz, tokens=tokens,
                       partition=(index, count)).start()
    conn.send(server.url)
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if msg == "counts":
            pods = [(p.namespace, p.metadata.name,
                     p.metadata.resource_version)
                    for p in store.list_pods()]
            conn.send({
                "partition": index,
                "pods": pods,
                "epoch": server.partition_topology.epoch
                if server.partition_topology is not None else 0,
            })
    server.shutdown_server()
    wal.close()
    conn.send("stopped")


def run_reshard_sigkill(seed: int, nodes: int = 20, pods: int = 80,
                        wait_timeout: float = 180.0,
                        progress: Optional[Callable] = None) -> Dict:
    import multiprocessing as mp
    import tempfile

    from kubernetes_tpu.apiserver.partition import PartitionTopology
    from kubernetes_tpu.apiserver.reshard import (
        ReshardCoordinator,
        ReshardError,
    )
    from kubernetes_tpu.client.restcluster import RestClusterClient

    rng = random.Random(seed)
    ctx = mp.get_context("spawn")
    wal_root = tempfile.mkdtemp(prefix="ktpu-reshard-chaos-")
    partitions = 3
    servers: List[list] = []   # [conn, proc] — mutated on restart
    urls: List[str] = []
    import os
    import shutil

    for i in range(partitions):
        seg = os.path.join(wal_root, f"p{i}")
        os.makedirs(seg, exist_ok=True)
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(target=_chaos_apiserver_main,
                           args=(child_conn, i, partitions, seg, False),
                           daemon=True)
        proc.start()
        servers.append([parent_conn, proc])
        urls.append(parent_conn.recv())

    client = RestClusterClient(urls[0], partition_urls=urls,
                               token=CREATOR_TOKEN, qps=None,
                               watch_kinds=("Pod",))
    coordinator = ReshardCoordinator(client, freeze_eta=4.0,
                                     evict_grace_s=0.05)

    def teardown() -> None:
        for conn, proc in servers:
            try:
                conn.send("stop")
            except (BrokenPipeError, OSError):
                pass
        for conn, proc in servers:
            try:
                if conn.poll(2.0):
                    conn.recv()
            except (EOFError, OSError):
                pass
            proc.join(timeout=3.0)
            if proc.is_alive():
                proc.terminate()
        shutil.rmtree(wal_root, ignore_errors=True)

    try:
        topo = PartitionTopology.default(partitions, urls=urls)
        coordinator.install_topology(topo)
        client.enable_topology(poll_interval=0.2)

        namespaces = [f"sk-{i}" for i in range(8)]
        confirmed: Dict[tuple, bool] = {}
        conf_lock = threading.Lock()
        stop = threading.Event()

        def writer() -> None:
            i = 0
            while not stop.is_set():
                ns = namespaces[i % len(namespaces)]
                pod = make_burst_pods(
                    1, cpu_milli=POD_CPU_MILLI, memory=POD_MEMORY,
                    name_prefix="sk-", uid_prefix="sku-", offset=i,
                    namespaces=[ns])[0]
                deadline = time.monotonic() + 30.0
                while not stop.is_set() \
                        and time.monotonic() < deadline:
                    try:
                        client.create_object("Pod", pod)
                        break
                    except ValueError:
                        break   # 409: an earlier timed-out try landed
                    except Exception:  # noqa: BLE001 — dead shard:
                        time.sleep(0.1)   # retry until failover heals
                else:
                    return
                with conf_lock:
                    confirmed[(ns, pod.metadata.name)] = True
                i += 1
                time.sleep(0.01)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.5)

        # seeded kill plan: which party dies, at which phase
        kill_dest = rng.random() < 0.5
        phase = rng.choice(["copied", "pre_flip"])
        topo = coordinator.fetch_topology()
        src, dest = 0, 1
        moving = rng.sample(topo.slots_of_partition(src),
                            min(6, len(topo.slots_of_partition(src))))
        victim = dest if kill_dest else src
        killed = {"done": False}

        def kill_hook(at: str) -> None:
            if at == phase and not killed["done"]:
                killed["done"] = True
                servers[victim][1].kill()
                servers[victim][1].join(timeout=3.0)
                if progress:
                    progress(f"sigkill: killed partition {victim} "
                             f"at {at}")

        outcome = "completed"
        try:
            coordinator.move_slots({s: dest for s in moving},
                                   kill_hook=kill_hook)
        except ReshardError as e:
            outcome = "committed-then-resolved" \
                if getattr(e, "committed", False) else "rolled-back"
        except Exception as e:  # noqa: BLE001
            outcome = f"rolled-back({type(e).__name__})"
        if progress:
            progress(f"sigkill: migration {outcome}")

        # failover: restart the corpse from its WAL at a fresh URL
        seg = os.path.join(wal_root, f"p{victim}")
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(target=_chaos_apiserver_main,
                           args=(child_conn, victim, partitions, seg,
                                 True),
                           daemon=True)
        proc.start()
        servers[victim] = [parent_conn, proc]
        new_url = parent_conn.recv()
        coordinator.reroute_after_restart(victim, new_url)
        if progress:
            progress(f"sigkill: partition {victim} restored at "
                     f"{new_url}")
        time.sleep(1.0)   # writes resume through the healed fleet
        stop.set()
        t.join(timeout=10.0)
        time.sleep(0.5)

        # -- invariants (per-server truth over the pipe) --------------
        union: Dict[tuple, str] = {}
        dups = 0
        epochs = set()
        for conn, _proc in servers:
            conn.send("counts")
            counts = conn.recv()
            epochs.add(counts["epoch"])
            for ns, name, rv in counts["pods"]:
                key = (ns, name)
                if key in union:
                    dups += 1
                union[key] = rv
        with conf_lock:
            expected = dict(confirmed)
        missing = [k for k in expected if k not in union]
        ok = (not missing and dups == 0 and len(epochs) == 1
              and killed["done"])
        return {
            "seed": seed, "profile": f"sigkill-{phase}",
            "ok": ok,
            "failure": "" if ok else (
                f"missing={len(missing)} dups={dups} "
                f"epochs={sorted(epochs)} outcome={outcome} "
                f"killed={killed['done']}"),
            "stats": {
                "confirmed": len(expected),
                "server_pods": len(union),
                "outcome": outcome,
                "victim": victim,
                "kill_phase": phase,
                "epoch": sorted(epochs)[-1] if epochs else 0,
            },
        }
    finally:
        client._stop_watches()
        client._drop_conn()
        teardown()


# ---------------------------------------------------------------------------
# rebalance under storm: the controller must act, correctly


def run_reshard_rebalance(seed: int, nodes: int = 20, pods: int = 300,
                          wait_timeout: float = 120.0,
                          progress: Optional[Callable] = None) -> Dict:
    from kubernetes_tpu.apiserver.reshard import ReshardCoordinator
    from kubernetes_tpu.autoscaler.partitions import (
        PartitionGroup,
        PartitionRebalancer,
        RebalancePolicy,
        RestElasticDriver,
    )
    from kubernetes_tpu.client.restcluster import RestClusterClient

    rng = random.Random(seed)
    servers, urls = _spin_inproc_servers(3)
    client = RestClusterClient(urls[0], partition_urls=urls,
                               watch_kinds=("Pod",))
    recorder = _Recorder()
    rebalancer = None
    try:
        client.enable_topology(poll_interval=0.1)
        client.watch(lambda e: recorder.on_events([e]),
                     batch_fn=recorder.on_events)
        time.sleep(0.2)
        coordinator = ReshardCoordinator(client, freeze_eta=4.0,
                                         evict_grace_s=0.05)
        # in-proc servers share this process's registry: folding it
        # into itself would compound counters (see RestElasticDriver)
        driver = RestElasticDriver(coordinator, federate=False)
        # the fleet is pinned at 3 partitions: the cell's subject is
        # the SPLIT decision, so idle-retire and buy are fenced off
        rebalancer = PartitionRebalancer(
            driver, group=PartitionGroup(min_partitions=3,
                                         max_partitions=3,
                                         cooldown_s=0.5),
            policy=RebalancePolicy(min_rate=10.0, sustain_ticks=2),
            interval_s=0.25)
        rebalancer.run()

        hot_ns = "hot-tenant"
        cold = [f"cold-{i}" for i in range(6)]
        confirmed = [0]
        conf_lock = threading.Lock()
        stop = threading.Event()
        errors: List[str] = []

        def writer(tid: int) -> None:
            # storms until told to stop — ``pods`` is the FLOOR the
            # quiesce waits for, not a cap: the rebalancer needs a
            # sustained hot signal across several observation ticks
            wrng = random.Random(seed * 31 + tid)
            i = 0
            while not stop.is_set():
                ns = hot_ns if wrng.random() < 0.8 \
                    else wrng.choice(cold)
                batch = make_burst_pods(
                    4, cpu_milli=POD_CPU_MILLI, memory=POD_MEMORY,
                    name_prefix=f"rb{tid}-", uid_prefix=f"rbu{tid}-",
                    offset=i, namespaces=[ns])
                try:
                    got = client.create_objects_bulk("Pod", batch)
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{type(e).__name__}: {e}")
                    return
                with conf_lock:
                    confirmed[0] += got
                i += 4
                time.sleep(0.005)

        threads = [threading.Thread(target=writer, args=(t,),
                                    daemon=True) for t in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + wait_timeout
        while time.monotonic() < deadline:
            with conf_lock:
                made = confirmed[0]
            if rebalancer.actions and made >= pods:
                break
            time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        if rebalancer is not None:
            rebalancer.stop()
        time.sleep(1.0)

        union, dups = _server_union(servers)
        acted = [a["action"]["op"] for a in rebalancer.actions]
        # placement actually spread: the hot namespace's pods land on
        # more than one partition once the split committed
        hot_parts = {
            i for i, s in enumerate(servers)
            if any(p.namespace == hot_ns for p in s.store.list_pods())}
        doubles = recorder.doubles()
        ok = (len(union) == confirmed[0] and dups == 0
              and not errors and not doubles
              and "split" in acted and len(hot_parts) > 1)
        return {
            "seed": seed, "profile": "rebalance", "ok": ok,
            "failure": "" if ok else (
                f"union={len(union)} confirmed={confirmed[0]} "
                f"dups={dups} doubles={len(doubles)} acted={acted} "
                f"hot_parts={sorted(hot_parts)} errs={errors[:2]}"),
            "stats": {
                "created": confirmed[0],
                "actions": acted,
                "hot_partitions": len(hot_parts),
                "epoch": client.topology_epoch,
            },
        }
    finally:
        if rebalancer is not None:
            rebalancer.stop()
        client._stop_watches()
        client._drop_conn()
        for s in servers:
            s.shutdown_server()


def run_chaos_reshard(seed: int, nodes: int = 20, pods: int = 120,
                      wait_timeout: float = 180.0,
                      progress: Optional[Callable] = None,
                      scenario: str = "midstorm") -> Dict:
    """chaos_matrix entry point: one (scenario × seed) cell."""
    if scenario == "midstorm":
        return run_reshard_midstorm(seed, nodes=nodes, pods=pods,
                                    wait_timeout=wait_timeout,
                                    progress=progress)
    if scenario == "sigkill":
        return run_reshard_sigkill(seed, nodes=nodes, pods=pods,
                                   wait_timeout=wait_timeout,
                                   progress=progress)
    if scenario == "rebalance":
        return run_reshard_rebalance(seed, nodes=nodes, pods=pods,
                                     wait_timeout=wait_timeout,
                                     progress=progress)
    raise ValueError(f"unknown reshard scenario {scenario!r} "
                     f"(have: {', '.join(RESHARD_SCENARIOS)})")
