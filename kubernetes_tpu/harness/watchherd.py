"""Watch-herd bench: the read tier under informer fan-out at scale.

The read-path scale-out story (ROADMAP item 3) lives or dies on one
measurement: when hundreds of informers hang list+watch streams off the
control plane, does adding read replicas (``apiserver/readtier.py``)
scale delivered-event throughput WITHOUT perturbing the write path and
WITHOUT weakening the watch contract (zero lost events, zero duplicate
applies, relists only where a process actually died)? This harness is
that measurement, end to end over real processes and real sockets:

- **owner** — one spawned partition apiserver with a synchronous WAL
  (the subscription stream's resume window across restarts).
- **replicas** — N spawned ``ReadReplica`` processes, each seeded via
  ``?snapshot=1`` and tailing the owner's commit stream, serving lists
  and watches from its OWN store/watch-cache/dispatch threads.
- **herd** — K spawned children × M ``_MiniInformer`` threads, each a
  raw HTTP list+watch loop pinned to one endpoint (its replica) with
  the sibling replicas and the owner as failover targets. The informer
  carries the same RV-monotonic per-key filter the elastic client uses
  (``_deliver``): a failover to a LAGGING sibling re-lists against a
  stale snapshot and re-receives events it already applied — those are
  SUPPRESSED by high-water RV, never double-applied, and counted as
  ``dup_suppressed`` (the cursor-handoff contract, observable).
- **writer** — a paced open-loop create/delete stream into the owner
  (writes NEVER ride replicas), seeded so every arm commits the
  byte-identical operation sequence: the replicas-off arm is a true
  differential control (same final truth hash, or the row fails).
- **hollow nodes** — a ``HollowFleet`` heartbeating through the same
  client, so the fan-out rides a cluster that is also doing node-lease
  work (lease renewals bypass the RV counter, preserving determinism).

Headline per arm: delivered events/s from writer start to the instant
EVERY informer's state hash equals the owner's truth hash. The scaling
row judges read fan-out per OWNER CPU-SECOND (events delivered fleet-
wide divided by the owner process's rusage delta over the window): the
bench host time-shares all processes on the same cores, so wall-clock
aggregate throughput measures the host's core count, not the
architecture — what the read tier actually scales is how much serving
one owner CPU-second buys, because the partition owner is the one
process that cannot be replicated (it owns the write path). On R=0 the
owner pays for every frame to every informer; on R=4 it pays for four
subscription copies. Wall-clock rates are committed alongside so the
row hides nothing. ``tools/perf_report.py --strict``
(``readtier_flags``) gates scaling ≥1.5×, write throughput flat vs the
replicas-off arm, replication-lag p99 inside the budget, zero
lost/duplicated events, zero relists outside a killed process.

Chaos cells (``tools/chaos_matrix.py --suite readtier``):

- ``replica_kill`` — SIGKILL one replica mid-herd: its informers
  fail over and re-list ONCE each; informers on surviving replicas
  must not relist at all; zero lost fleet-wide at quiesce.
- ``owner_restart`` — SIGKILL the owner with replicas live, restart on
  the same port from the WAL: replicas resume their subscription from
  their cursor (``resumes >= 1``, ``reseeds == 0`` — the WAL tail, not
  a full re-seed) and their watchers' streams NEVER break (0 relists).
- ``lag_fence`` — one replica applies with an injected delay until its
  replication lag blows the budget: the fence trips, its streams and
  lists self-sever, its informers re-route, relists stay confined.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import os
import random
import shutil
import socket
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

from kubernetes_tpu.client.restcluster import RestClusterClient

READTIER_SCENARIOS = ("replica_kill", "owner_restart", "lag_fence")

DEFAULT_LAG_BUDGET_S = 0.5
READ_SCALING_FLOOR_X = 1.5
WRITE_FLAT_TOLERANCE = 0.15


def _state_hash(items: Sequence[Tuple[str, str, int]]) -> str:
    """Canonical digest of a (namespace, name, resourceVersion) set —
    computed identically by the owner-truth side (parent) and every
    informer (herd children), so convergence is one string compare."""
    return hashlib.sha1(
        json.dumps(sorted(items)).encode()).hexdigest()[:16]


def _host_port(url: str) -> Tuple[str, int]:
    p = urlparse(url)
    return p.hostname or "127.0.0.1", int(p.port or 80)


# ---------------------------------------------------------------------------
# spawned children (mirrors the upgrade harness's process idiom)


def _owner_main(conn, port: int, wal_dir: str, restore: bool) -> None:
    """Owner partition apiserver child. ``restore=True`` is the
    post-SIGKILL respawn: rebuild the store from the WAL directory and
    PRESERVE the log — a fresh snapshot would truncate the very tail
    the replicas' subscription cursors resume from."""
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.apiserver.wal import attach_wal, restore_store
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    store = ClusterStore()
    if restore:
        restore_store(wal_dir, store)
    wal = attach_wal(store, wal_dir, snapshot_every=1_000_000,
                     async_serialize=False, preserve_log=restore)
    server = None
    for _ in range(40):
        # a restart reuses the dead owner's port so replica and client
        # URLs stay valid; the kernel may briefly hold it
        try:
            server = APIServer(store=store, port=port).start()
            break
        except OSError:
            time.sleep(0.25)
    if server is None:
        conn.send("bind-failed")
        return
    server.wal_dir = wal_dir  # 410-resume path reads the log tail
    conn.send(server.url)
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if isinstance(msg, tuple) and msg[0] == "topology":
            from kubernetes_tpu.apiserver.partition import PartitionTopology

            server.install_topology(PartitionTopology.from_dict(msg[1]))
            conn.send(server.partition_topology.epoch)
        elif msg == "counts":
            import resource

            ru = resource.getrusage(resource.RUSAGE_SELF)
            pods = sorted(
                (p.namespace, p.metadata.name,
                 int(p.metadata.resource_version))
                for p in store.list_pods())
            conn.send({"rv": store.current_rv(), "pods": pods,
                       "nodes": len(store.list_nodes()),
                       "cpu_s": ru.ru_utime + ru.ru_stime})
    server.shutdown_server()
    if wal is not None:
        wal.close()
    conn.send("stopped")


def _replica_main(conn, owner_url: str, replica_id: str,
                  lag_budget_s: float, apply_delay: float) -> None:
    """Read-replica child: one ``ReadReplica`` (mirror store + read-only
    apiserver + subscription tail). ``apply_delay`` is the lag-fence
    chaos hook — a per-event apply stall that drives replication lag
    past the budget."""
    from kubernetes_tpu.apiserver.readtier import ReadReplica
    from kubernetes_tpu.metrics.freshness_metrics import freshness_metrics
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    rep = ReadReplica(owner_url, partition=(0, 1), replica_id=replica_id,
                      lag_budget_s=lag_budget_s, apply_delay=apply_delay)
    try:
        rep.start(seed_timeout=30.0)
    except Exception as exc:  # noqa: BLE001 — surfaced to the parent
        conn.send(f"error: {exc}")
        return
    conn.send(rep.url)
    hist = freshness_metrics().replication_lag_seconds
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if msg == "stats":
            st = rep.stats()
            rid = rep.repl.replica_id
            st["lag_p99_ms"] = round(
                hist.quantile(0.99, rid) * 1000, 2) \
                if hist.count(rid) else 0.0
            conn.send(st)
    rep.stop()
    conn.send("stopped")


# ---------------------------------------------------------------------------
# the informer herd


class _MiniInformer(threading.Thread):
    """One raw-HTTP list+watch consumer: JSON list, then a chunked
    ``?watch=1&resourceVersion=`` stream, against an endpoint list
    (primary replica first, siblings and owner as failover). Carries
    the elastic client's per-key RV high-water filter so a failover to
    a lagging sibling suppresses — never double-applies — events it
    already saw, and a stale list cannot resurrect a deleted object or
    drop one newer than the snapshot."""

    def __init__(self, index: int, urls: Sequence[str],
                 stop: threading.Event, kind_path: str = "pods"):
        super().__init__(daemon=True, name=f"informer-{index}")
        self.index = index
        self.endpoints = [_host_port(u) for u in urls]
        self.ep = 0
        self.kind_path = kind_path
        self._halt = stop
        self._conn_lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._klock = threading.Lock()
        self.known: Dict[Tuple[str, str], int] = {}
        self.high: Dict[Tuple[str, str], int] = {}
        self.delivered = 0
        self.dup_suppressed = 0
        self.lists = 0
        self.reroutes = 0
        self.errors = 0
        self.synced = threading.Event()

    # -- lifecycle ----------------------------------------------------
    def run(self) -> None:
        backoff = 0.05
        while not self._halt.is_set():
            try:
                rv = self._list()
                backoff = 0.05
                self._watch(rv)
                # clean end-of-stream (server flush/close): retry the
                # SAME endpoint — the next list probe decides whether
                # this endpoint is actually gone (fenced lists 503)
            except (OSError, ValueError, KeyError, AttributeError):
                if self._halt.is_set():
                    break  # the stop-path sever, not a real failure
                self.errors += 1
                self._advance()
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)

    def sever(self) -> None:
        """Unblock a readline parked on a live stream (stop path)."""
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            try:
                if conn.sock is not None:
                    conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    def _track(self, conn) -> None:
        with self._conn_lock:
            self._conn = conn

    def _advance(self) -> None:
        if len(self.endpoints) > 1:
            self.ep = (self.ep + 1) % len(self.endpoints)
            self.reroutes += 1

    # -- list+watch ---------------------------------------------------
    def _list(self) -> int:
        host, port = self.endpoints[self.ep]
        conn = http.client.HTTPConnection(host, port, timeout=15)
        self._track(conn)
        try:
            conn.request("GET", f"/api/v1/{self.kind_path}")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise OSError(f"list status {resp.status}")
            doc = json.loads(body)
        finally:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass
        list_rv = int(doc.get("resourceVersion") or 0)
        fresh: Dict[Tuple[str, str], int] = {}
        for item in doc.get("items", ()):
            m = item.get("metadata", item)
            key = (m.get("namespace") or "", m["name"])
            rv = int(m.get("resourceVersion") or 0)
            # a snapshot older than an already-applied DELETE must not
            # resurrect the object
            if rv >= self.high.get(key, -1):
                fresh[key] = rv
        with self._klock:
            # keep anything newer than the snapshot itself (a lagging
            # sibling's list predates events this informer already has)
            for key, rv in self.known.items():
                if rv > list_rv:
                    fresh[key] = rv
            self.known = fresh
            self.lists += 1
        return list_rv

    def _watch(self, rv: int) -> None:
        host, port = self.endpoints[self.ep]
        conn = http.client.HTTPConnection(host, port, timeout=300)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._track(conn)
        try:
            conn.request(
                "GET",
                f"/api/v1/{self.kind_path}?watch=1&resourceVersion={rv}")
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                raise OSError(f"watch status {resp.status}")
            self.synced.set()
            while not self._halt.is_set():
                line = resp.readline()
                if not line:
                    return
                try:
                    msg = json.loads(line)
                    wire = msg["object"]
                except (ValueError, KeyError, TypeError):
                    return  # torn frame: relist
                self._apply(msg.get("type"), wire)
        finally:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    def _apply(self, etype: Optional[str], wire: dict) -> None:
        m = wire.get("metadata", wire)
        key = (m.get("namespace") or "", m["name"])
        rv = int(m.get("resourceVersion") or 0)
        with self._klock:
            if rv <= self.high.get(key, -1):
                # cursor handoff: a frame this informer already applied
                # before failing over — suppressed, never re-applied
                self.dup_suppressed += 1
                return
            self.high[key] = rv
            if etype == "DELETED":
                self.known.pop(key, None)
            else:
                self.known[key] = rv
            self.delivered += 1

    # -- observation --------------------------------------------------
    def snapshot(self) -> dict:
        with self._klock:
            items = [(ns, name, rv)
                     for (ns, name), rv in self.known.items()]
            return {
                "hash": _state_hash(items),
                "objects": len(items),
                "delivered": self.delivered,
                "dup_suppressed": self.dup_suppressed,
                "relists": max(0, self.lists - 1),
                "reroutes": self.reroutes,
                "errors": self.errors,
                "endpoint": self.ep,
            }


def _herd_main(conn, informer_urls: List[List[str]]) -> None:
    """Herd child: one thread-herd of ``_MiniInformer``s, observable
    over the pipe ("synced" / "snapshot") and stopped with a final
    snapshot so the parent gets exact terminal counters."""
    stop = threading.Event()
    informers = [_MiniInformer(i, urls, stop)
                 for i, urls in enumerate(informer_urls)]
    for inf in informers:
        inf.start()
    conn.send("ready")
    while True:
        msg = conn.recv()
        if msg == "synced":
            conn.send(sum(1 for i in informers if i.synced.is_set()))
        elif msg == "snapshot":
            conn.send([i.snapshot() for i in informers])
        elif msg == "stop":
            stop.set()
            for inf in informers:
                inf.sever()
            for inf in informers:
                inf.join(timeout=2.0)
            conn.send([i.snapshot() for i in informers])
            break


# ---------------------------------------------------------------------------
# fleet orchestration (parent side)


class _ReadTierFleet:
    """Owner + read replicas + herd children as real processes."""

    def __init__(self, progress: Optional[Callable] = None):
        import multiprocessing as mp

        self.ctx = mp.get_context("spawn")
        self.progress = progress
        self.wal_root = tempfile.mkdtemp(prefix="ktpu-readtier-wal-")
        self.owner: Optional[list] = None      # [conn, proc]
        self.owner_url = ""
        self.owner_port = 0
        self.replicas: List[Optional[list]] = []
        self.replica_urls: List[str] = []
        self.herds: List[list] = []
        self.herd_primaries: List[List[Optional[int]]] = []

    def _say(self, msg: str) -> None:
        if self.progress:
            self.progress(msg)

    # -- owner --------------------------------------------------------
    def start_owner(self, port: int = 0, restore: bool = False,
                    timeout: float = 60.0) -> str:
        wal_dir = os.path.join(self.wal_root, "owner")
        os.makedirs(wal_dir, exist_ok=True)
        parent_conn, child_conn = self.ctx.Pipe()
        proc = self.ctx.Process(
            target=_owner_main,
            args=(child_conn, port, wal_dir, restore), daemon=True)
        proc.start()
        if not parent_conn.poll(timeout):
            raise RuntimeError("owner child did not come up")
        url = parent_conn.recv()
        if url == "bind-failed":
            raise RuntimeError("owner child could not bind its port")
        self.owner = [parent_conn, proc]
        self.owner_url = url
        self.owner_port = _host_port(url)[1]
        return url

    def kill_owner(self) -> None:
        _conn, proc = self.owner
        proc.kill()
        proc.join(timeout=5.0)

    def restart_owner(self, timeout: float = 60.0) -> str:
        """Respawn on the SAME port from the (possibly torn) WAL."""
        return self.start_owner(port=self.owner_port, restore=True,
                                timeout=timeout)

    def owner_counts(self) -> dict:
        conn, _proc = self.owner
        conn.send("counts")
        if not conn.poll(30.0):
            raise RuntimeError("owner counts timed out")
        return conn.recv()

    def advertise(self) -> int:
        """Install a topology doc on the owner advertising the live
        replica URLs — the path ``RestClusterClient`` discovers the
        read tier through (``refresh_topology`` → ``replicas`` field →
        ``_set_read_replicas``)."""
        from kubernetes_tpu.apiserver.partition import PartitionTopology

        topo = PartitionTopology.default(1, urls=[self.owner_url])
        urls = [u for u in self.replica_urls if u]
        if urls:
            topo = topo.evolve(replicas={0: urls})
        conn, _proc = self.owner
        conn.send(("topology", topo.to_dict()))
        if not conn.poll(10.0):
            raise RuntimeError("topology install timed out")
        return conn.recv()

    # -- replicas -----------------------------------------------------
    def start_replicas(self, count: int,
                       lag_budget_s: float = DEFAULT_LAG_BUDGET_S,
                       apply_delays: Sequence[float] = (),
                       timeout: float = 60.0) -> List[str]:
        for i in range(count):
            parent_conn, child_conn = self.ctx.Pipe()
            delay = apply_delays[i] if i < len(apply_delays) else 0.0
            proc = self.ctx.Process(
                target=_replica_main,
                args=(child_conn, self.owner_url, f"r{i}",
                      lag_budget_s, delay), daemon=True)
            proc.start()
            self.replicas.append([parent_conn, proc])
        for i, (conn, _proc) in enumerate(self.replicas):
            if not conn.poll(timeout):
                raise RuntimeError(f"replica r{i} did not come up")
            url = conn.recv()
            if isinstance(url, str) and url.startswith("error:"):
                raise RuntimeError(f"replica r{i} failed: {url}")
            self.replica_urls.append(url)
        self._say(f"[readtier] {count} replicas seeded")
        return list(self.replica_urls)

    def kill_replica(self, i: int) -> None:
        _conn, proc = self.replicas[i]
        proc.kill()
        proc.join(timeout=5.0)
        self.replicas[i] = None

    def replica_stats(self) -> List[dict]:
        out = []
        for entry in self.replicas:
            if entry is None:
                continue
            conn, proc = entry
            if not proc.is_alive():
                continue
            try:
                conn.send("stats")
                if conn.poll(10.0):
                    out.append(conn.recv())
            except (BrokenPipeError, EOFError, OSError):
                pass
        return out

    # -- herd ---------------------------------------------------------
    def endpoints_for(self, i: int) -> Tuple[List[str], Optional[int]]:
        """Informer ``i``'s endpoint list (primary first) and the index
        of its primary replica (None = pinned to the owner)."""
        n = len(self.replica_urls)
        if n == 0:
            return [self.owner_url], None
        primary = i % n
        order = [self.replica_urls[(primary + j) % n] for j in range(n)]
        order.append(self.owner_url)
        return order, primary

    def start_herd(self, informers: int, children: int,
                   timeout: float = 60.0) -> None:
        per = [informers // children +
               (1 if c < informers % children else 0)
               for c in range(children)]
        base = 0
        for c in range(children):
            urls, primaries = [], []
            for i in range(base, base + per[c]):
                eps, primary = self.endpoints_for(i)
                urls.append(eps)
                primaries.append(primary)
            base += per[c]
            parent_conn, child_conn = self.ctx.Pipe()
            proc = self.ctx.Process(
                target=_herd_main, args=(child_conn, urls), daemon=True)
            proc.start()
            self.herds.append([parent_conn, proc])
            self.herd_primaries.append(primaries)
        for c, (conn, _proc) in enumerate(self.herds):
            if not conn.poll(timeout):
                raise RuntimeError(f"herd child {c} did not come up")
            conn.recv()

    def wait_synced(self, total: int, timeout: float = 60.0) -> int:
        deadline = time.monotonic() + timeout
        synced = 0
        while time.monotonic() < deadline:
            synced = 0
            for conn, _proc in self.herds:
                conn.send("synced")
                if conn.poll(10.0):
                    synced += conn.recv()
            if synced >= total:
                break
            time.sleep(0.1)
        return synced

    def herd_snapshots(self) -> List[dict]:
        """Flat per-informer snapshots, annotated with each informer's
        pinned primary replica (the confinement checks key off it)."""
        out: List[dict] = []
        for c, (conn, _proc) in enumerate(self.herds):
            conn.send("snapshot")
            if not conn.poll(30.0):
                raise RuntimeError(f"herd child {c} snapshot timed out")
            for i, snap in enumerate(conn.recv()):
                snap["primary"] = self.herd_primaries[c][i]
                out.append(snap)
        return out

    def stop_herd(self) -> List[dict]:
        out: List[dict] = []
        for c, (conn, proc) in enumerate(self.herds):
            try:
                conn.send("stop")
                if conn.poll(15.0):
                    for i, snap in enumerate(conn.recv()):
                        snap["primary"] = self.herd_primaries[c][i]
                        out.append(snap)
            except (BrokenPipeError, EOFError, OSError):
                pass
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.terminate()
        self.herds = []
        self.herd_primaries = []
        return out

    # -- teardown -----------------------------------------------------
    def stop(self) -> None:
        self.stop_herd()
        for entry in self.replicas:
            if entry is None:
                continue
            conn, proc = entry
            if proc.is_alive():
                try:
                    conn.send("stop")
                    if conn.poll(5.0):
                        conn.recv()
                except (BrokenPipeError, EOFError, OSError):
                    pass
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.terminate()
        self.replicas = []
        if self.owner is not None:
            conn, proc = self.owner
            if proc.is_alive():
                try:
                    conn.send("stop")
                    if conn.poll(5.0):
                        conn.recv()
                except (BrokenPipeError, EOFError, OSError):
                    pass
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.terminate()
            self.owner = None
        shutil.rmtree(self.wal_root, ignore_errors=True)


# ---------------------------------------------------------------------------
# the writer (parent side — writes always hit the owner)


def _run_writer(client, creates: int, qps: float, seed: int,
                namespaces: int = 8, delete_frac: float = 0.2,
                offset: int = 0, live: Optional[list] = None) -> dict:
    """Paced open-loop create/delete stream. Seeded, and pacing never
    changes WHICH operations run, so every arm of the bench commits an
    identical op sequence → identical final truth and RVs (the
    differential-arm contract)."""
    from kubernetes_tpu.harness.burst import make_burst_pods

    rng = random.Random(seed * 7919 + 11)
    ns_names = [f"herd-{i}" for i in range(namespaces)]
    pods = make_burst_pods(
        creates, cpu_milli=100, memory="64Mi",
        name_prefix=f"wh{seed}-", uid_prefix=f"whu{seed}-",
        offset=offset, namespaces=ns_names)
    live = live if live is not None else []
    deletes = 0
    ops = 0
    t0 = time.monotonic()
    for pod in pods:
        target = t0 + ops / qps
        now = time.monotonic()
        if target > now:
            time.sleep(target - now)
        client.create_pod(pod)
        ops += 1
        live.append((pod.namespace, pod.metadata.name))
        if len(live) > 20 and rng.random() < delete_frac:
            ns, name = live.pop(rng.randrange(len(live)))
            client.delete_pod(ns, name)
            deletes += 1
            ops += 1
    wall = max(time.monotonic() - t0, 1e-6)
    return {"creates": creates, "deletes": deletes,
            "events": creates + deletes, "wall_s": round(wall, 3),
            "offered_qps": qps,
            "achieved_qps": round((creates + deletes) / wall, 1)}


def _aggregate(snaps: List[dict], truth_hash: str) -> dict:
    agg = {
        "informers": len(snaps),
        "delivered_total": sum(s["delivered"] for s in snaps),
        "dup_suppressed": sum(s["dup_suppressed"] for s in snaps),
        "relists": sum(s["relists"] for s in snaps),
        "reroutes": sum(s["reroutes"] for s in snaps),
        "errors": sum(s["errors"] for s in snaps),
        "unconverged": sum(1 for s in snaps if s["hash"] != truth_hash),
    }
    agg["lost_events"] = agg["unconverged"]
    return agg


def _poll_converged(fleet: _ReadTierFleet, truth_hash: str,
                    deadline: float) -> None:
    while time.monotonic() < deadline:
        snaps = fleet.herd_snapshots()
        if all(s["hash"] == truth_hash for s in snaps):
            return
        time.sleep(0.1)


# ---------------------------------------------------------------------------
# bench arms (bench.py --config watchherd)


def _run_watchherd_arm(seed: int, replicas: int, informers: int,
                       herd_children: int, creates: int, qps: float,
                       nodes: int, lag_budget_s: float,
                       wait_timeout: float,
                       progress: Optional[Callable]) -> dict:
    from kubernetes_tpu.kubemark import HollowFleet

    fleet = _ReadTierFleet(progress=progress)
    client = None
    hollow = None
    try:
        owner_url = fleet.start_owner()
        client = RestClusterClient(owner_url)
        if nodes:
            hollow = HollowFleet(client, interval=5.0)
            hollow.register(nodes, chunk=500)
            hollow.start()
        replica_reads = 0
        if replicas:
            fleet.start_replicas(replicas, lag_budget_s=lag_budget_s)
            fleet.advertise()
            # the advertised path end-to-end: the client discovers the
            # replica set through the topology doc and its next read
            # must ride a replica, not the owner
            client.refresh_topology()
            client.list_pods()
            replica_reads = client.replica_reads
        fleet.start_herd(informers, herd_children)
        synced = fleet.wait_synced(informers,
                                   timeout=min(60.0, wait_timeout))
        if progress:
            progress(f"[watchherd] R={replicas}: {synced}/{informers} "
                     f"informers synced, writing {creates} pods")
        cpu0 = fleet.owner_counts()["cpu_s"]
        t0 = time.monotonic()
        wres = _run_writer(client, creates, qps, seed)
        truth = fleet.owner_counts()
        truth_hash = _state_hash(truth["pods"])
        _poll_converged(fleet, truth_hash,
                        t0 + min(wait_timeout, wres["wall_s"] + 120.0))
        converged_wall = time.monotonic() - t0
        # the owner's CPU spend over the whole window, write start to
        # herd convergence — the scale-out denominator: on R=0 it
        # includes every watch-frame send to every informer; on R>0
        # only the writes, the WAL, and one subscription copy per
        # replica (the unreplicatable partition owner is what the read
        # tier exists to offload)
        owner_cpu_s = fleet.owner_counts()["cpu_s"] - cpu0
        rstats = fleet.replica_stats()
        snaps = fleet.stop_herd()
        agg = _aggregate(snaps, truth_hash)
        lag_p99 = max((s.get("lag_p99_ms") or 0.0 for s in rstats),
                      default=0.0)
        res = {
            "replicas": replicas,
            "streams": informers + len(rstats),
            "synced": synced,
            "writer": wres,
            "truth_rv": truth["rv"],
            "truth_objects": len(truth["pods"]),
            "state_hash": truth_hash,
            "replica_reads": replica_reads,
            "convergence_wall_s": round(converged_wall, 3),
            "fanout_events_per_s": round(
                agg["delivered_total"] / max(converged_wall, 1e-6), 1),
            "owner_cpu_s": round(owner_cpu_s, 3),
            "fanout_per_owner_cpu_s": round(
                agg["delivered_total"] / max(owner_cpu_s, 1e-6), 1),
            "replication_lag_p99_ms": lag_p99,
            "fences": sum(int(s.get("fences") or 0) for s in rstats),
            "resumes": sum(int(s.get("resumes") or 0) for s in rstats),
            "reseeds": sum(int(s.get("reseeds") or 0) for s in rstats),
            "replica_stats": rstats,
        }
        res.update(agg)
        return res
    finally:
        if hollow is not None:
            hollow.stop()
        fleet.stop()


def _arm_invariants(res: dict, lag_budget_s: float) -> Tuple[bool, str]:
    why = []
    if res["unconverged"]:
        why.append(f"{res['unconverged']} informers never converged")
    if res["dup_suppressed"]:
        why.append(f"{res['dup_suppressed']} duplicate frames on "
                   "steady streams")
    if res["relists"]:
        why.append(f"{res['relists']} relists with no process killed")
    if res["fences"]:
        why.append(f"{res['fences']} fences inside the lag budget")
    if res["replicas"] and res["replica_reads"] < 1:
        why.append("no read rode a replica after the advertisement")
    if res["replication_lag_p99_ms"] > lag_budget_s * 1000:
        why.append(f"replication lag p99 "
                   f"{res['replication_lag_p99_ms']}ms over budget")
    return (not why), "; ".join(why)


def _readtier_diag(res: dict) -> None:
    import sys

    from kubernetes_tpu.harness import diagfmt

    seg = diagfmt.format_readtier({
        "replicas": res.get("replicas", 0),
        "streams": res.get("streams", 0),
        "lag_p99_ms": res.get("replication_lag_p99_ms", 0.0),
        "fenced": res.get("fences", 0),
        "relists": res.get("relists", 0),
    })
    if seg:
        print(diagfmt.format_diag([seg]), file=sys.stderr, flush=True)


def _arm_row(res: dict, seed: int, creates: int, qps: float,
             lag_budget_s: float) -> dict:
    ok, why = _arm_invariants(res, lag_budget_s)
    wres = res["writer"]
    slo_ok = res["replication_lag_p99_ms"] <= lag_budget_s * 1000
    row = {
        "metric": (f"watchherd[{res['informers']} informers R="
                   f"{res['replicas']}, {wres['events']} events "
                   f"open-loop {qps:.0f}/s seed={seed}, REST fabric]"),
        "value": res["fanout_events_per_s"],
        "unit": "events/s",
        "informers": res["informers"],
        "replicas": res["replicas"],
        "streams": res["streams"],
        "events_committed": wres["events"],
        "delivered_total": res["delivered_total"],
        "lost_events": res["lost_events"],
        "unconverged_informers": res["unconverged"],
        "dup_suppressed": res["dup_suppressed"],
        "relists": res["relists"],
        "reroutes": res["reroutes"],
        "replica_reads": res["replica_reads"],
        "write_qps_offered": wres["offered_qps"],
        "write_qps_achieved": wres["achieved_qps"],
        "convergence_wall_s": res["convergence_wall_s"],
        "owner_cpu_s": res["owner_cpu_s"],
        "fanout_per_owner_cpu_s": res["fanout_per_owner_cpu_s"],
        "replication_lag_p99_ms": res["replication_lag_p99_ms"],
        "lag_budget_ms": round(lag_budget_s * 1000, 1),
        "fences": res["fences"],
        "state_hash": res["state_hash"],
        "truth_rv": res["truth_rv"],
        "invariants_ok": ok,
        "invariants": {"failed": why} if why else {},
        "freshness": {
            "replication_lag_p99_ms": res["replication_lag_p99_ms"],
            "slo": {"replication_lag":
                    "ok" if slo_ok else "violated"},
        },
    }
    return row


def run_watchherd_row(
    informers: int = 320,
    creates: int = 240,
    qps: float = 12.0,
    seed: int = 16,
    *,
    replica_arms: Sequence[int] = (0, 1, 4),
    herd_children: int = 4,
    nodes: int = 100,
    lag_budget_s: float = DEFAULT_LAG_BUDGET_S,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
) -> List[dict]:
    """The committed watch-herd rows (``bench.py --config watchherd``):
    one arm per replica count with the SAME seeded op sequence (the
    replicas-off arm is the differential control), a scaling summary
    row, and the replica-kill cell. Gated by ``perf_report``'s
    ``readtier_flags``.

    The defaults are sized to the bench host, upgrade-row style: 320
    informers is ≥10× the widest stream count any earlier committed
    row carried, and at that width the herd saturates the host — so
    the write rate is OPEN-LOOP at a rate every arm can sustain (the
    flat-write gate compares achieved rates; an offered rate beyond
    the saturated host's write capacity would measure scheduler
    starvation of the injector, not the read tier). Scale ``qps`` and
    ``creates`` up on hardware with cores to spare; the invariants
    and the per-owner-cpu scaling metric are rate-independent."""
    rows: List[dict] = []
    arms: Dict[int, dict] = {}
    for replicas in replica_arms:
        res = _run_watchherd_arm(
            seed, replicas, informers, herd_children, creates, qps,
            nodes, lag_budget_s, wait_timeout, progress)
        arms[replicas] = res
        rows.append(_arm_row(res, seed, creates, qps, lag_budget_s))
        _readtier_diag(res)
        if progress:
            progress(f"[watchherd] R={replicas}: "
                     f"{res['fanout_events_per_s']:.0f} ev/s fan-out, "
                     f"write {res['writer']['achieved_qps']:.0f}/s, "
                     f"lost {res['lost_events']}, "
                     f"lag p99 {res['replication_lag_p99_ms']}ms")
    base = arms.get(replica_arms[0]) or next(iter(arms.values()))
    top_r = max(replica_arms)
    top = arms[top_r]
    # Read scaling is judged on fan-out per OWNER CPU-second, not on
    # fleet wall-clock: the bench host time-shares every process on
    # the same cores, so wall-clock aggregate throughput measures the
    # host, not the architecture. What the read tier scales is how
    # many delivered events one owner CPU-second buys — on R=0 the
    # owner pays for every copy to every informer; on R=4 it pays for
    # four subscription copies and the replicas fan out the rest. On a
    # fleet with real per-process cores this IS wall-clock scaling;
    # both rates are committed side by side so the row hides nothing.
    scaling = (top["fanout_per_owner_cpu_s"] /
               max(base["fanout_per_owner_cpu_s"], 1e-6))
    wall_scaling = (top["fanout_events_per_s"] /
                    max(base["fanout_events_per_s"], 1e-6))
    write_ratio = (top["writer"]["achieved_qps"] /
                   max(base["writer"]["achieved_qps"], 1e-6))
    hashes = {r: a["state_hash"] for r, a in arms.items()}
    differential_match = len(set(hashes.values())) == 1
    rows.append({
        "metric": (f"watchherd_scaling[R={top_r} vs R="
                   f"{replica_arms[0]}, {informers} informers "
                   f"seed={seed}, per owner-cpu-second]"),
        "value": round(scaling, 2),
        "unit": "x",
        "baseline_events_per_owner_cpu_s":
            base["fanout_per_owner_cpu_s"],
        "scaled_events_per_owner_cpu_s":
            top["fanout_per_owner_cpu_s"],
        "baseline_events_per_s": base["fanout_events_per_s"],
        "scaled_events_per_s": top["fanout_events_per_s"],
        "wall_clock_scaling_x": round(wall_scaling, 2),
        "read_scaling_x": round(scaling, 2),
        "read_scaling_floor_x": READ_SCALING_FLOOR_X,
        "write_ratio": round(write_ratio, 3),
        "write_flat_ok": write_ratio >= 1.0 - WRITE_FLAT_TOLERANCE,
        "differential_match": differential_match,
        "state_hashes": {str(k): v for k, v in hashes.items()},
        "invariants_ok": (scaling >= READ_SCALING_FLOOR_X
                          and write_ratio >= 1.0 - WRITE_FLAT_TOLERANCE
                          and differential_match),
    })
    if progress:
        progress(f"[watchherd] read scaling {scaling:.2f}x at "
                 f"R={top_r}, write ratio {write_ratio:.2f}, "
                 f"differential "
                 f"{'match' if differential_match else 'MISMATCH'}")
    cell = run_readtier_cell(seed, scenario="replica_kill",
                             wait_timeout=wait_timeout,
                             progress=progress)
    rows.append(_cell_row(cell))
    return rows


def _cell_row(cell: dict) -> dict:
    return {
        "metric": (f"watchherd_cell[{cell['profile']} "
                   f"seed={cell['seed']}]"),
        "value": 1 if cell["ok"] else 0,
        "unit": "ok",
        **{k: v for k, v in cell.items()
           if k not in ("replica_stats",)},
        "invariants_ok": cell["ok"],
        "invariants": ({"failed": cell["failure"]}
                       if cell["failure"] else {}),
    }


# ---------------------------------------------------------------------------
# chaos cells (tools/chaos_matrix.py --suite readtier)


def run_readtier_cell(
    seed: int,
    *,
    scenario: str = "replica_kill",
    informers: int = 48,
    creates: int = 240,
    qps: float = 120.0,
    replicas: int = 2,
    wait_timeout: float = 240.0,
    progress: Optional[Callable] = None,
) -> dict:
    """One (scenario × seed) chaos cell over the spawned fleet: fault
    mid-herd, then judge confinement and loss at quiesce."""
    if scenario not in READTIER_SCENARIOS:
        raise ValueError(f"unknown readtier scenario {scenario!r} "
                         f"(have: {', '.join(READTIER_SCENARIOS)})")
    lag_budget_s = 0.15 if scenario == "lag_fence" else \
        DEFAULT_LAG_BUDGET_S
    # lag_fence arms replica r1 with a per-event apply stall that must
    # blow the 150ms budget under the write stream
    delays = (0.0, 0.06) if scenario == "lag_fence" else ()
    fleet = _ReadTierFleet(progress=progress)
    client = None
    try:
        owner_url = fleet.start_owner()
        client = RestClusterClient(owner_url)
        fleet.start_replicas(replicas, lag_budget_s=lag_budget_s,
                             apply_delays=delays)
        fleet.advertise()
        fleet.start_herd(informers, children=2)
        fleet.wait_synced(informers, timeout=60.0)
        live: list = []
        w1 = _run_writer(client, creates // 2, qps, seed, live=live)
        faulted = None
        if scenario == "replica_kill":
            faulted = 0
            fleet.kill_replica(0)
        elif scenario == "owner_restart":
            fleet.kill_owner()
            fleet.restart_owner()
        w2 = _run_writer(client, creates - creates // 2, qps,
                         seed + 1, offset=creates // 2, live=live)
        if scenario == "lag_fence":
            faulted = 1
        truth = fleet.owner_counts()
        truth_hash = _state_hash(truth["pods"])
        deadline = time.monotonic() + min(wait_timeout, 120.0)
        _poll_converged(fleet, truth_hash, deadline)
        rstats = fleet.replica_stats()
        snaps = fleet.stop_herd()
        agg = _aggregate(snaps, truth_hash)
        relists_on_faulted = sum(
            s["relists"] for s in snaps if s["primary"] == faulted)
        relists_beyond = agg["relists"] - relists_on_faulted
        fences = sum(int(s.get("fences") or 0) for s in rstats)
        resumes = sum(int(s.get("resumes") or 0) for s in rstats)
        reseeds = sum(int(s.get("reseeds") or 0) for s in rstats)
        why = []
        if agg["unconverged"]:
            why.append(f"{agg['unconverged']} informers lost events")
        if scenario == "replica_kill":
            if relists_beyond:
                why.append(f"{relists_beyond} relists beyond the "
                           "killed replica")
            if relists_on_faulted < 1:
                why.append("killed replica's informers never relisted")
        elif scenario == "owner_restart":
            if agg["relists"]:
                why.append(f"{agg['relists']} relists across an owner "
                           "restart (replica streams must hold)")
            if resumes < 1:
                why.append("no replica resumed its subscription")
            if reseeds:
                why.append(f"{reseeds} full reseeds (WAL resume "
                           "window lost)")
        elif scenario == "lag_fence":
            if fences < 1:
                why.append("lagging replica never fenced")
            if relists_beyond:
                why.append(f"{relists_beyond} relists beyond the "
                           "fenced replica")
        ok = not why
        cell = {
            "seed": seed,
            "profile": scenario,
            "ok": ok,
            "failure": "; ".join(why),
            "informers": informers,
            "replicas": replicas,
            "events_committed": w1["events"] + w2["events"],
            "delivered_total": agg["delivered_total"],
            "lost_events": agg["lost_events"],
            "dup_suppressed": agg["dup_suppressed"],
            "relists": agg["relists"],
            "relists_on_faulted": relists_on_faulted,
            "relists_beyond_faulted": relists_beyond,
            "reroutes": agg["reroutes"],
            "fences": fences,
            "resumes": resumes,
            "reseeds": reseeds,
            "state_hash": truth_hash,
            "replica_stats": rstats,
        }
        _readtier_diag({
            "replicas": replicas, "streams": informers,
            "replication_lag_p99_ms": max(
                (s.get("lag_p99_ms") or 0.0 for s in rstats),
                default=0.0),
            "fences": fences, "relists": agg["relists"],
        })
        if progress:
            progress(f"[readtier] {scenario} seed={seed}: "
                     f"{'OK' if ok else 'FAILED: ' + cell['failure']}")
        return cell
    finally:
        fleet.stop()


def run_chaos_readtier(seed: int, nodes: int = 0, pods: int = 240,
                       wait_timeout: float = 240.0,
                       progress: Optional[Callable] = None,
                       scenario: str = "replica_kill") -> Dict:
    """chaos_matrix entry point: one (scenario × seed) cell."""
    del nodes  # the read-tier cells are pod-stream cells
    return run_readtier_cell(seed, scenario=scenario,
                             creates=max(80, int(pods)),
                             wait_timeout=wait_timeout,
                             progress=progress)


# ---------------------------------------------------------------------------
# tier-1 mini-cell (tests/test_readtier.py)


def run_readtier_mini_cell(
    informers: int = 10,
    creates: int = 120,
    qps: float = 400.0,
    seed: int = 7,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """CI-fast read-tier cell, all in-process: one owner apiserver, two
    ``ReadReplica``s, a mini informer herd pinned across them, a live
    writer — and one replica HARD-KILLED mid-stream. Asserted by the
    caller: every informer ≡ owner truth at quiesce, zero lost and
    zero double-applied events, relists confined to the killed
    replica's informers, and the surviving replica's store identical
    to the owner's."""
    from kubernetes_tpu.apiserver.readtier import ReadReplica
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore

    store = ClusterStore()
    owner = APIServer(store=store).start()
    reps = [ReadReplica(owner.url, replica_id=f"mini-r{i}")
            for i in range(2)]
    client = None
    stop = threading.Event()
    herd: List[_MiniInformer] = []
    try:
        for rep in reps:
            rep.start(seed_timeout=10.0)
        urls = [rep.url for rep in reps]
        primaries = []
        for i in range(informers):
            primary = i % 2
            eps = [urls[primary], urls[1 - primary], owner.url]
            inf = _MiniInformer(i, eps, stop)
            herd.append(inf)
            primaries.append(primary)
            inf.start()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and \
                not all(i.synced.is_set() for i in herd):
            time.sleep(0.05)
        client = RestClusterClient(owner.url)
        live: list = []
        _run_writer(client, creates // 2, qps, seed, live=live)
        reps[0].kill()  # hard kill: live sockets severed mid-stream
        _run_writer(client, creates - creates // 2, qps, seed + 1,
                    offset=creates // 2, live=live)
        truth = sorted((p.namespace, p.metadata.name,
                        int(p.metadata.resource_version))
                       for p in store.list_pods())
        truth_hash = _state_hash(truth)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            snaps = [i.snapshot() for i in herd]
            if all(s["hash"] == truth_hash for s in snaps):
                break
            time.sleep(0.1)
        snaps = [i.snapshot() for i in herd]
        for s, primary in zip(snaps, primaries):
            s["primary"] = primary
        agg = _aggregate(snaps, truth_hash)
        # the surviving replica must converge to owner truth too
        deadline = time.monotonic() + 10.0
        replica_truth: list = []
        while time.monotonic() < deadline:
            replica_truth = sorted(
                (p.namespace, p.metadata.name,
                 int(p.metadata.resource_version))
                for p in reps[1].store.list_pods())
            if replica_truth == truth:
                break
            time.sleep(0.05)
        relists_on_killed = sum(
            s["relists"] for s in snaps if s["primary"] == 0)
        agg.update({
            "truth_objects": len(truth),
            "state_hash": truth_hash,
            "replica_truth_match": replica_truth == truth,
            "relists_on_killed": relists_on_killed,
            "relists_beyond_killed": agg["relists"] - relists_on_killed,
            "killed_informers": sum(1 for p in primaries if p == 0),
            "survivor_stats": reps[1].stats(),
        })
        if progress:
            progress(f"[readtier-mini] lost={agg['lost_events']} "
                     f"relists={agg['relists']} "
                     f"(killed={relists_on_killed})")
        return agg
    finally:
        stop.set()
        for inf in herd:
            inf.sever()
        for inf in herd:
            inf.join(timeout=2.0)
        for rep in reps:
            try:
                rep.stop()
            except Exception:  # noqa: BLE001
                pass
        owner.shutdown_server()
