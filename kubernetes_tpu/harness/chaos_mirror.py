"""Seeded chaos cells for the device-resident cluster mirror
(``tools/chaos_matrix.py --suite mirror``).

Every cell runs the SAME seeded event sequence twice — mirror on
(scatter path) and ``KTPU_MIRROR=off`` (the PR 12 delta-encode
reference) — and passes only when the two arms land a BIT-IDENTICAL
placement set with zero lost pods. The scenarios aim the faults at the
mirror's seams:

- ``node_kill`` — a node dies inside the scatter window: a solve is
  dispatched and still in flight when the node is deleted, so the
  suspect-batch discard and the node-set epoch bump both cross the
  resident planes mid-sequence.
- ``mesh_resize`` — the sharded backend is torn down and re-attached
  at a different mesh width with pods in flight: the new session must
  cold-seed the mirror from store truth and keep the differential.
- ``event_storm`` — a mutation storm overflows the delta journal ring
  between two solves: the window reads as a gap, which MUST surface as
  a reseed (full host encode + mirror re-seed), never as silently
  missing deltas. The cell fails if the storm did not force a reseed —
  a quiet cell proves nothing.
"""

from __future__ import annotations

import copy
import gc
import os
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

MIRROR_SCENARIOS = ("node_kill", "mesh_resize", "event_storm")

# ring capacity the event-storm cell shrinks the LIVE journal to: small
# enough that the storm below overflows it between two solves, large
# enough that the quiet phases of the cell never gap
STORM_RING_CAP = 96
STORM_UPDATES = 3 * STORM_RING_CAP


def _pump(sched, bs, timeout: float = 60.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        if bs.run_batch(pop_timeout=0.0):
            continue
        if sched.queue.pending_active_count() == 0 and \
                bs._pending is None:
            break
        time.sleep(0.01)
    bs.flush()
    sched.wait_for_inflight_bindings()


def _bound_set(store) -> List[Tuple[str, Optional[str]]]:
    return sorted((p.metadata.name, p.spec.node_name)
                  for p in store.list_pods())


def _set_node_cpu(store, name: str, cpu: str) -> None:
    from kubernetes_tpu.api.resource import Quantity

    node = copy.deepcopy(store.get_node(name))
    node.status.allocatable["cpu"] = Quantity(cpu)
    node.status.capacity["cpu"] = Quantity(cpu)
    store.update_node(node)


def _make_sched(store, *, max_batch=64, backend=None):
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler

    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": True}),
        provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(sched, max_batch=max_batch,
                                adaptive_chunk=False, backend=backend)
    sched.start()
    return sched, bs


def _drive(scenario: str, seed: int, mirror_on: bool, *,
           nodes: int, pods: int, wait_timeout: float,
           progress: Optional[Callable[[str], None]] = None) -> Dict:
    """One arm of a cell: drive the seeded sequence and return the
    final placement set plus the mirror counters (None on the off
    arm)."""
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.testing import MakeNode, MakePod

    prev = os.environ.get("KTPU_MIRROR")
    os.environ["KTPU_MIRROR"] = "on" if mirror_on else "off"
    scheds = []
    try:
        rng = np.random.default_rng(seed)
        store = ClusterStore()
        for i in range(nodes):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "8",
                                      "memory": "16Gi"}).obj())
        w0 = max(8, int(pods * 0.4))
        w1 = max(8, int(pods * 0.35))
        w2 = max(4, pods - w0 - w1)
        created = 0
        deleted = 0

        def make_wave(w: int, count: int):
            nonlocal created
            created += count
            return [
                MakePod().name(f"w{w}-p{i}").uid(f"u{w}-{i}")
                .req({"cpu": f"{int(rng.integers(1, 5)) * 100}m"})
                .obj()
                for i in range(count)
            ]

        def churn() -> None:
            # scatterable deltas: allocatable-only node updates plus
            # bound-pod deletes — the fault must cross a mirror that
            # has actually scattered, not a freshly-seeded one
            nonlocal deleted
            picks = rng.choice(nodes, size=2, replace=False)
            _set_node_cpu(store, f"n{picks[0]}", "6")
            _set_node_cpu(store, f"n{picks[1]}", "10")
            bound = [p for p in store.list_pods() if p.spec.node_name]
            if len(bound) >= 4:
                for p in rng.choice(bound, size=4, replace=False):
                    store.delete_pod(p.metadata.namespace,
                                     p.metadata.name)
                    deleted += 1

        backend = None
        widths = (None, None)
        if scenario == "mesh_resize":
            import jax

            from kubernetes_tpu.parallel import ShardedBackend, make_mesh

            avail = len(jax.devices())
            widths = (2, 4) if avail >= 4 else (1, max(1, avail))
            backend = ShardedBackend(make_mesh(widths[0], batch_axis=1))
        sched, bs = _make_sched(store, backend=backend)
        scheds.append(sched)

        store.create_pods(make_wave(0, w0))
        _pump(sched, bs, timeout=wait_timeout)

        if scenario == "node_kill":
            # churned deltas scatter on the next dispatch; the node
            # dies while that solve is still in flight — the scatter
            # window
            churn()
            store.create_pods(make_wave(1, w1))
            bs.run_batch(pop_timeout=0.1)
            store.delete_node(f"n{int(rng.integers(0, nodes))}")
            _pump(sched, bs, timeout=wait_timeout)
        elif scenario == "event_storm":
            churn()
            store.create_pods(make_wave(1, w1))
            _pump(sched, bs, timeout=wait_timeout)
            journal = getattr(bs.session, "_journal", None)
            if journal is not None:
                with journal._lock:
                    journal._recs = deque(journal._recs,
                                          maxlen=STORM_RING_CAP)
            # the storm: allocatable churn far past the ring capacity
            # between two solves — the next catch-up window MUST read
            # as a gap, never as "nothing happened"
            for _ in range(STORM_UPDATES):
                pick = int(rng.integers(0, nodes))
                cpu = str(int(rng.choice([6, 8, 10, 12])))
                _set_node_cpu(store, f"n{pick}", cpu)
        elif scenario == "mesh_resize":
            from kubernetes_tpu.parallel import ShardedBackend, make_mesh

            # pods in flight across the resize: solve dispatched, then
            # the backend torn down and re-attached one width up; more
            # churn lands on the re-seeded mirror afterwards
            churn()
            store.create_pods(make_wave(1, w1))
            bs.run_batch(pop_timeout=0.1)
            sched.stop()
            backend = ShardedBackend(make_mesh(widths[1], batch_axis=1))
            sched, bs = _make_sched(store, backend=backend)
            scheds.append(sched)
            _pump(sched, bs, timeout=wait_timeout)
            # a small wave guarantees a post-resize solve (the mirror
            # seeds on its first solve), so the churn below scatters
            # instead of folding into the cold seed
            store.create_pods(make_wave(3, 8))
            _pump(sched, bs, timeout=wait_timeout)
            churn()
        else:
            raise ValueError(f"unknown mirror scenario {scenario!r}")

        store.create_pods(make_wave(2, w2))
        _pump(sched, bs, timeout=wait_timeout)

        info = None
        if getattr(bs.session, "_mirror", None) is not None:
            info = bs.session._mirror.info()
        if progress:
            arm = "on" if mirror_on else "off"
            progress(f"[mirror/{scenario}] arm={arm} created={created} "
                     f"mirror={info}")
        return {"bound": _bound_set(store), "mirror": info,
                "created": created, "deleted": deleted}
    finally:
        for s in scheds:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        if prev is None:
            os.environ.pop("KTPU_MIRROR", None)
        else:
            os.environ["KTPU_MIRROR"] = prev
        gc.collect()


def run_chaos_mirror(
    seed: int,
    *,
    scenario: str,
    nodes: int = 20,
    pods: int = 120,
    wait_timeout: float = 120.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """One (scenario × seed) cell: both arms, differential verdict."""
    if scenario not in MIRROR_SCENARIOS:
        raise ValueError(f"unknown mirror scenario {scenario!r} "
                         f"(have: {', '.join(MIRROR_SCENARIOS)})")
    on = _drive(scenario, seed, True, nodes=nodes, pods=pods,
                wait_timeout=wait_timeout, progress=progress)
    off = _drive(scenario, seed, False, nodes=nodes, pods=pods,
                 wait_timeout=wait_timeout, progress=progress)
    match = on["bound"] == off["bound"]
    lost = ((on["created"] - on["deleted"] - len(on["bound"]))
            + (off["created"] - off["deleted"] - len(off["bound"])))
    info = on["mirror"] or {}
    problems = []
    if on["mirror"] is None:
        problems.append("mirror-on arm built no mirror")
    if not match:
        problems.append("differential mismatch: mirror-on placements "
                        "diverged from the delta-encode reference")
    if lost:
        problems.append(f"lost_pods={lost}")
    if on["mirror"] is not None and not info.get("events"):
        problems.append("no deltas were ever scattered (the fault "
                        "crossed a mirror the cell never exercised)")
    if scenario == "event_storm" and not info.get("reseeds"):
        problems.append("storm never forced a reseed (the journal-gap "
                        "path went untested — a quiet cell proves "
                        "nothing)")
    return {
        "seed": seed,
        "profile": scenario,
        "ok": not problems,
        "failure": "; ".join(problems),
        "differential_match": match,
        "lost_pods": lost,
        "stats": {
            "faults_injected": (STORM_UPDATES
                                if scenario == "event_storm" else 1),
            "events": info.get("events"),
            "catch_ups": info.get("catch_ups"),
            "reseeds": info.get("reseeds"),
        },
    }
