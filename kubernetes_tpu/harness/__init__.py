"""Benchmark harness package.

Lazy exports (PEP 562): ``perf`` transitively imports the TPU solver
(jax); the REST harness's creator/apiserver child processes import only
``workloads`` and must stay jax-free — a device-initialized child
spawned beside the scheduler process would fight it for the chip.
"""

from kubernetes_tpu.harness.burst import (
    BurstResult,
    make_burst_pods,
    run_pending_burst,
    wait_all_bound,
)
from kubernetes_tpu.harness.workloads import WORKLOADS, make_workload

__all__ = [
    "WORKLOADS", "make_workload",
    "BenchmarkResult", "run_workload", "ThroughputCollector",
    "run_workload_rest",
    "BurstResult", "make_burst_pods", "run_pending_burst",
    "wait_all_bound",
    "run_autoscale_bench", "run_scale_cell",
    "run_sustained_row", "run_sustained_cell",
]


def __getattr__(name):
    if name in ("run_sustained_row", "run_sustained_cell"):
        # lazy: sustained transitively imports the jax solver
        from kubernetes_tpu.harness import sustained

        return getattr(sustained, name)
    if name in ("BenchmarkResult", "run_workload", "ThroughputCollector"):
        from kubernetes_tpu.harness import perf

        return getattr(perf, name)
    if name == "run_workload_rest":
        from kubernetes_tpu.harness.rest_perf import run_workload_rest

        return run_workload_rest
    if name in ("run_autoscale_bench", "run_scale_cell"):
        # lazy: elastic transitively imports the jax solver
        from kubernetes_tpu.harness import elastic

        return getattr(elastic, name)
    raise AttributeError(name)
