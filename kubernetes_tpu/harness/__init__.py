from kubernetes_tpu.harness.perf import (
    BenchmarkResult,
    run_workload,
    ThroughputCollector,
)
from kubernetes_tpu.harness.workloads import WORKLOADS, make_workload
