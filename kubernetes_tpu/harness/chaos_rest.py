"""Chaos over REST: the wire-level half of the chaos ring.

``tests/test_chaos.py`` kills in-process components over a shared store;
this harness attacks the PROCESS-BOUNDARY fabric instead (reference
``test/e2e/chaosmonkey``): the apiserver runs as a separate process over
a WAL, the FaultGate injects wire faults (resets, 429 bursts, latency,
watch drops) armed at runtime through ``/debug/faults``, and the
apiserver process is SIGKILLed and restarted from WAL restore
mid-workload while a real scheduler keeps binding through
``RestClusterClient``'s resilience stack (jittered backoff, retry
budget, circuit breaker → degraded mode).

Invariants checked after quiescence:

- **all bound, exactly once**: every created pod exists and is bound;
  the store's bind transaction refuses double-binds, so a bound pod on
  a live node with no node oversubscribed proves exactly-once;
- **no oversubscription**: per-node summed cpu requests within
  allocatable — the invariant a confused post-relist cache would break;
- **durability**: a WAL restore in the test process reproduces the
  live pod→node assignment the server reported;
- **resourceVersion monotonicity**: no client ever observed a list RV
  regress across the kill/restart (the restored server must continue
  the revision counter, never rewind it).

The WAL is attached with synchronous serialization: every mutation is
on disk before its watch event — and therefore before any client
response — is visible, so a SIGKILL can never lose state a client
already observed.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import random
import shutil
import tempfile
import time
from typing import Callable, Dict, List, Optional


# ---------------------------------------------------------------------------
# apiserver child (spawned; must stay jax-free — see harness/__init__)


def _apiserver_main(conn, wal_dir: str, port: int) -> None:
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.apiserver.wal import attach_wal, restore_store

    has_state = os.path.exists(os.path.join(wal_dir, "snapshot.json")) \
        or os.path.exists(os.path.join(wal_dir, "wal.jsonl"))
    store = restore_store(wal_dir) if has_state else ClusterStore()
    # sync WAL: durability strictly precedes visibility (see module doc)
    wal = attach_wal(store, wal_dir)
    server = APIServer(store=store, port=port).start()
    conn.send(("ready", server.url))
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if msg == "assignments":
            conn.send({p.uid: p.spec.node_name for p in store.list_pods()})
        elif msg == "counts":
            pods = store.list_pods()
            conn.send({
                "pods_total": len(pods),
                "pods_bound": sum(1 for p in pods if p.spec.node_name),
            })
    server.shutdown_server()
    wal.close()
    conn.send("stopped")


class ChaosApiServer:
    """A kill-and-restartable apiserver subprocess over one WAL dir.
    ``kill()`` is SIGKILL — no goodbye to clients, no WAL close;
    ``restart()`` restores from the WAL on the SAME port so client
    URLs stay valid across the crash."""

    def __init__(self, wal_dir: Optional[str] = None):
        self._ctx = mp.get_context("spawn")
        self._owns_wal = wal_dir is None
        self.wal_dir = wal_dir or tempfile.mkdtemp(prefix="ktpu-chaos-")
        self.port = 0          # first start picks; restarts reuse
        self.url: Optional[str] = None
        self._proc = None
        self._conn = None

    def start(self, timeout: float = 90.0) -> "ChaosApiServer":
        conn, child = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_apiserver_main, args=(child, self.wal_dir, self.port),
            daemon=True)
        proc.start()
        if not conn.poll(timeout):
            proc.terminate()
            raise TimeoutError("apiserver child did not come up")
        _tag, url = conn.recv()
        self.url = url
        self.port = int(url.rsplit(":", 1)[1])
        self._proc, self._conn = proc, conn
        return self

    def kill(self) -> None:
        self._proc.kill()
        self._proc.join(timeout=10.0)
        self._conn.close()
        self._proc = self._conn = None

    def restart(self, timeout: float = 90.0) -> "ChaosApiServer":
        if self._proc is not None:
            self.kill()
        return self.start(timeout)

    def ask(self, msg: str, timeout: float = 30.0):
        self._conn.send(msg)
        if not self._conn.poll(timeout):
            raise TimeoutError(f"apiserver did not answer {msg!r}")
        return self._conn.recv()

    def stop(self, cleanup: bool = True) -> None:
        if self._proc is not None:
            try:
                self._conn.send("stop")
                if self._conn.poll(10.0):
                    self._conn.recv()
            except (OSError, EOFError, BrokenPipeError):
                pass
            self._proc.join(timeout=10.0)
            if self._proc.is_alive():
                self._proc.terminate()
            self._proc = self._conn = None
        if cleanup and self._owns_wal:
            shutil.rmtree(self.wal_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# fault profiles (the seeded matrix tools/chaos_matrix.py walks)


def default_fault_spec(seed: int) -> Dict:
    """The mixed profile the acceptance run uses: resets + 429 bursts +
    latency on every resource, plus watch drops on the pod stream."""
    return {
        "seed": seed,
        "rules": [
            {"fault": "reset", "probability": 0.03},
            {"fault": "error", "probability": 0.05, "code": 429,
             "retry_after": 0.05},
            {"fault": "latency", "probability": 0.10, "latency": 0.01},
            {"fault": "watch_drop", "verb": "GET", "resource": "pods",
             "probability": 0.02},
        ],
    }


FAULT_PROFILES: Dict[str, Callable[[int], Dict]] = {
    "mixed": default_fault_spec,
    "resets": lambda seed: {"seed": seed, "rules": [
        {"fault": "reset", "probability": 0.08},
        {"fault": "truncate", "probability": 0.04, "truncate_bytes": 80},
    ]},
    "pushback": lambda seed: {"seed": seed, "rules": [
        {"fault": "error", "probability": 0.15, "code": 429,
         "retry_after": 0.05},
        {"fault": "error", "probability": 0.05, "code": 503,
         "retry_after": 60.0},   # hostile Retry-After: the cap must bite
    ]},
    "watchstorm": lambda seed: {"seed": seed, "rules": [
        {"fault": "watch_drop", "probability": 0.05},
        {"fault": "watch_stall", "probability": 0.05, "duration": 0.2},
        {"fault": "latency", "probability": 0.10, "latency": 0.01},
    ]},
}


# ---------------------------------------------------------------------------
# the seeded chaos run


def _tolerable(resp) -> bool:
    """A bulk create whose only failures are 409s succeeded: the retry
    of a request the server applied before dropping the connection."""
    if not isinstance(resp, dict):
        return False
    return all(f.get("code") == 409 for f in resp.get("failures") or ())


def run_chaos_rest(
    seed: int,
    nodes: int = 20,
    pods: int = 120,
    node_cpu: int = 16,
    pod_cpu_milli: int = 500,
    waves: int = 6,
    kill_at_wave: Optional[int] = None,
    fault_profile: str = "mixed",
    qps: Optional[float] = 2000.0,
    wait_timeout: float = 120.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """One seeded chaos run; returns ``{"ok", "invariants", "stats"}``.
    Deterministic per (seed, profile): the workload interleaving, the
    kill point, and the server's fault decisions all derive from it."""
    from kubernetes_tpu.apiserver.wal import restore_store
    from kubernetes_tpu.client.backoff import RetryBudget
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    def note(msg: str) -> None:
        if progress:
            progress(f"chaos[{seed}/{fault_profile}]: {msg}")

    rng = random.Random(seed)
    spec_fn = FAULT_PROFILES[fault_profile]
    fm = fabric_metrics()
    retries_before = sum(v for _, _, v in fm.client_retries_total.collect())
    degraded_before = fm.degraded_mode_seconds.get()

    api = ChaosApiServer().start()
    sched = None
    faults_injected = 0
    invariants: Dict[str, bool] = {}
    failure = ""
    try:
        # generous budgets: the profiles inject faults for the WHOLE
        # run, and the restart window alone eats several retries
        creator = RestClusterClient(
            api.url, qps=qps, watch_kinds=(),
            max_retries=8, retry_after_cap=0.5, retry_seed=seed,
            retry_budget=RetryBudget(budget=64, refill_per_second=8.0))
        sched_client = RestClusterClient(
            api.url, qps=qps,
            max_retries=8, retry_after_cap=0.5, retry_seed=seed + 1,
            retry_budget=RetryBudget(budget=64, refill_per_second=8.0))

        def arm_gate() -> None:
            code, resp = creator._request(
                "POST", "/debug/faults", spec_fn(seed), body_binary=False)
            if code != 200:
                raise RuntimeError(f"arming fault gate failed: {resp}")

        def gate_injected() -> int:
            code, snap = creator._request("GET", "/debug/faults")
            if code != 200:
                return 0
            return sum((snap.get("injected") or {}).values())

        # nodes land BEFORE the gate is armed (the chaos targets the
        # steady workload, not cluster bootstrap)
        node_objs = [
            MakeNode().name(f"n{i}").capacity(
                {"cpu": str(node_cpu), "memory": "64Gi", "pods": "110"}
            ).obj()
            for i in range(nodes)
        ]
        code, resp = creator._request(
            "POST", "/api/v1/nodes",
            {"kind": "NodeList", "items": node_objs}, charge=nodes)
        if code >= 400 or not _tolerable(resp):
            raise RuntimeError(f"node create failed: {resp}")

        sched = Scheduler.create(sched_client)
        sched.run()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and sched.cache.node_count() < nodes:
            time.sleep(0.02)
        arm_gate()
        note(f"{nodes} nodes up, gate armed")

        kill_wave = kill_at_wave if kill_at_wave is not None \
            else rng.randrange(1, waves)
        per_wave = pods // waves
        created = 0
        for w in range(waves):
            count = per_wave if w < waves - 1 else pods - created
            items = [
                MakePod().name(f"c{w}-{i}").uid(f"u{w}-{i}")
                .req({"cpu": f"{pod_cpu_milli}m"}).obj()
                for i in range(count)
            ]
            # a wave must land even across the restart window: retry the
            # bulk POST (409-only failures = an earlier attempt applied)
            wave_deadline = time.monotonic() + 60
            while True:
                try:
                    code, resp = creator._request(
                        "POST", "/api/v1/namespaces/default/pods",
                        {"kind": "PodList", "items": items}, charge=count)
                    if code < 400 and _tolerable(resp):
                        break
                    err: object = resp
                except (OSError, RuntimeError) as e:
                    err = e
                if time.monotonic() > wave_deadline:
                    raise RuntimeError(f"wave {w} create failed: {err}")
                time.sleep(0.2)
            created += count
            if w == kill_wave:
                faults_injected += gate_injected()
                note(f"killing apiserver after wave {w}")
                api.kill()
                time.sleep(rng.uniform(0.1, 0.5))
                api.restart()
                arm_gate()   # fresh process: re-arm over the wire
                note("apiserver restarted from WAL")
            time.sleep(rng.uniform(0.0, 0.2))

        # quiescence: every created pod bound
        deadline = time.monotonic() + wait_timeout
        pods_live: List = []
        while time.monotonic() < deadline:
            try:
                pods_live = creator.list_pods()
            except (OSError, RuntimeError):
                time.sleep(0.5)
                continue
            if len(pods_live) >= created \
                    and all(p.spec.node_name for p in pods_live):
                break
            time.sleep(0.25)
        # final reads under still-active faults: a one-off transport
        # failure here must not abort the whole verdict
        deadline = time.monotonic() + 30
        while True:
            try:
                nodes_live = creator.list_nodes()
                pods_live = creator.list_pods()
                break
            except (OSError, RuntimeError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        faults_injected += gate_injected()

        unbound = [p.metadata.name for p in pods_live
                   if not p.spec.node_name]
        invariants["all_bound"] = (
            len(pods_live) == created and not unbound)
        if not invariants["all_bound"]:
            failure = (f"{len(pods_live)}/{created} pods, "
                       f"unbound: {unbound[:8]}")
        node_names = {n.name for n in nodes_live}
        invariants["bound_nodes_exist"] = all(
            p.spec.node_name in node_names
            for p in pods_live if p.spec.node_name)
        used: Dict[str, int] = {}
        for p in pods_live:
            if p.spec.node_name:
                used[p.spec.node_name] = used.get(p.spec.node_name, 0) + sum(
                    int(c.resources.requests["cpu"].milli_value())
                    for c in p.spec.containers
                    if "cpu" in c.resources.requests)
        invariants["no_oversubscription"] = all(
            milli <= int({n.name: n for n in nodes_live}[name]
                         .status.allocatable["cpu"].milli_value())
            for name, milli in used.items())

        # durability: the server's live assignment must equal a WAL
        # restore performed in THIS process after a graceful stop
        live_assign = api.ask("assignments")
        sched.stop()
        sched = None
        api.stop(cleanup=False)
        restored = restore_store(api.wal_dir)
        got = {p.uid: p.spec.node_name for p in restored.list_pods()}
        invariants["wal_matches_live"] = got == live_assign
        if not invariants["wal_matches_live"] and not failure:
            diff = {u for u in set(got) ^ set(live_assign)} or {
                u for u in got if got[u] != live_assign.get(u)}
            failure = f"WAL restore diverged for {len(diff)} pods"

        invariants["no_rv_regression"] = (
            not creator.rv_regressions and not sched_client.rv_regressions)
        if not invariants["no_rv_regression"] and not failure:
            failure = (f"rv regressions: creator="
                       f"{creator.rv_regressions[:3]} scheduler="
                       f"{sched_client.rv_regressions[:3]}")
    finally:
        if sched is not None:
            sched.stop()
        api.stop(cleanup=True)

    retries = sum(v for _, _, v in fm.client_retries_total.collect()) \
        - retries_before
    degraded_seconds = fm.degraded_mode_seconds.get() - degraded_before
    return {
        "seed": seed,
        "profile": fault_profile,
        "ok": all(invariants.values()),
        "invariants": invariants,
        "failure": failure,
        "stats": {
            "pods": pods,
            "faults_injected": faults_injected,
            "client_retries": retries,
            "degraded_seconds": round(degraded_seconds, 3),
            "entered_degraded": degraded_seconds > 0,
        },
    }
