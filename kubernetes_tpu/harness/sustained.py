"""Sustained-arrival bench row: the streaming scheduler's proof
surface.

Every store-direct row before this one pre-created its pods in one
burst, so per-pod latency was batch-amortized and the solve loop's
barrier never showed up in a committed number. This harness drives the
headline-shaped workload OPEN-LOOP through the PR 11 replay engine —
pods arrive on a clock at a target QPS (default 5k/s, the REST rows'
client discipline), binds are observed on the engine's own watch
stream, and the row's headline is **p99 arrival→bind latency**: the
number a submitting user experiences, which the old drain→encode→
solve→commit barrier quantized at whole-cycle granularity.

The row also carries the pipeline's own verdict surface:

- ``telemetry.overlap_share`` — the fraction of the in-flight device
  window hidden under host work (devprof's per-cycle ``overlap_s``;
  0.0 would mean the pipeline degenerated back to the barrier);
- ``freshness.slo.snapshot_staleness`` — PR 8's staleness SLI stays
  green only if the pipeline's deeper in-flight window never lets the
  solve run against a stale mirror;
- ``lost_pods`` — the replay engine's zero-lost quiesce invariant.

``run_sustained_cell`` is the tier-1 face: a small, time-compressed
cell asserting overlap actually occurs and the staleness SLO holds,
cheap enough for the fast suite. ``tools/perf_report.py`` gates the
committed rows (``sustained_flags``): p99 arrival→bind > 500 ms, lost
pods, or a red staleness verdict all fail ``--strict``.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.harness.workloads import node_template
from kubernetes_tpu.workloads.trace import Trace, generate_trace

SUSTAINED_QPS = 5000.0
P99_ARRIVAL_TO_BIND_BUDGET_MS = 500.0


def build_sustained_trace(seed: int, pods: int,
                          qps: float = SUSTAINED_QPS) -> Trace:
    """Open-loop steady arrival trace: ``pods`` Poisson arrivals at
    ``qps`` (no burst epochs — the row isolates the pipeline, not the
    burst absorber), lightly heavy-tailed cpu sizes so pad buckets see
    realistic occupancy, NO lifetimes (zero-lost is then exactly
    "every arrival bound"). Deterministic per (seed, pods, qps) — the
    trace.py contract."""
    return generate_trace(
        seed, pods, pods / qps, family="sustained",
        name_prefix="su-", cpu_alpha=1.8, cpu_lo=100, cpu_hi=500,
        lifetime_modes=None, burst_factor=1.0, burst_period_s=0.0,
    )


def sustained_nodes(trace: Trace, node_cpu: int = 32,
                    headroom: float = 1.25) -> List[dict]:
    """A fleet sized from the trace itself: total cpu demand ×
    ``headroom``, so every arrival fits (the row measures latency, not
    bin-packing pressure) while the cluster stays small enough that
    plane encode/solve cost reflects a realistic node:pod ratio."""
    demand_milli = sum(e.cpu_milli for e in trace.events)
    n = max(
        8,
        math.ceil(demand_milli * headroom / (node_cpu * 1000)),
        # node_template caps max-pods at 110/node: the pods resource
        # must fit every arrival too, or the tail parks unschedulable
        # forever and the run never quiesces
        math.ceil(len(trace.events) * headroom / 110),
    )
    return [node_template(i, cpu=str(node_cpu), memory="64Gi")
            for i in range(n)]


def _pump_to_quiesce(sched, bs, engine, deadline: float,
                     settle_s: float = 1.0) -> None:
    """Drive the scheduler until the replay is over (same loop as the
    replay rows: injection done, queues drained, quiet for a settle
    window — arrivals keep re-waking the queue, so 'drained' must hold
    for a window, not an instant)."""
    quiet_since = None
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        progressed = bs.run_batch(pop_timeout=0.01)
        now = time.monotonic()
        if progressed:
            quiet_since = None
            continue
        busy = (not engine.injection_done.is_set()
                or sched.queue.pending_active_count() > 0)
        if busy:
            quiet_since = None
        elif quiet_since is None:
            quiet_since = now
        elif now - quiet_since >= settle_s:
            return
        time.sleep(0.005)
    raise TimeoutError("sustained replay did not quiesce before deadline")


def run_sustained_once(
    trace: Trace,
    *,
    node_cpu: int = 32,
    max_batch: int = 4096,
    pipeline: Optional[bool] = None,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
):
    """One open-loop run against an in-process store. Returns
    ``(stats, extras)`` — the replay engine's postmortem plus the
    telemetry/freshness/pipeline sub-objects. ``pipeline=False`` is
    the barrier arm (the ``KTPU_PIPELINE=off`` loop)."""
    from kubernetes_tpu.api.types import Node
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.harness.perf import (
        attach_slo_baseline,
        collect_freshness,
        reset_sli_window,
    )
    from kubernetes_tpu.observability import get_tracer
    from kubernetes_tpu.observability.devprof import get_devprof
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler
    from kubernetes_tpu.utils.gctune import tune_for_throughput
    from kubernetes_tpu.workloads.replay import ReplayEngine
    from kubernetes_tpu.workloads.trace import events_to_pods

    tune_for_throughput()
    get_tracer().clear()
    get_devprof().reset(workload="sustained")
    reset_sli_window()
    store = ClusterStore()
    for d in sustained_nodes(trace, node_cpu=node_cpu):
        store.add_node(Node.from_dict(d))
    gates = FeatureGates({"TPUBatchScheduler": True})
    sched = Scheduler.create(store, feature_gates=gates,
                             provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(sched, max_batch=max_batch,
                                pipeline=pipeline)
    attach_slo_baseline(sched)
    sched.start()
    engine = None
    try:
        samples = events_to_pods(trace.events[:128])
        warm = bs.warmup(sample_pods=samples) if samples else 0.0
        if progress and warm > 0.05:
            progress(f"sustained: solver warmup {warm:.1f}s")
        engine = ReplayEngine(store, trace, time_scale=1.0,
                              expire=False, progress=progress)
        t0 = time.monotonic()
        engine.start()
        _pump_to_quiesce(sched, bs, engine,
                         time.monotonic() + wait_timeout)
        bs.flush()
        sched.wait_for_inflight_bindings(timeout=30.0)
        wall = time.monotonic() - t0
        stats = engine.finish()
        engine = None
        dp = get_devprof()
        telemetry = dp.summary() if dp.enabled else {}
        extras: Dict = {
            "wall_s": round(wall, 2),
            "telemetry": telemetry,
            "freshness": collect_freshness(telemetry),
            "pipeline": bs.pipeline_info(telemetry),
            "mirror": bs.mirror_info(telemetry),
            "session": {
                "incremental_hits": bs.session.incremental_hits,
                "rebuilds": bs.session.rebuilds,
                "carry_chained": bs.session.carry_chained,
            },
        }
        return stats, extras
    finally:
        if engine is not None:
            try:
                engine.finish()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        sched.stop()
        # tune_for_throughput defers collection: reclaim this run's
        # device/plane garbage NOW instead of leaving a multi-hundred-
        # ms GC pause for whatever runs next in the process (the same
        # discipline bench.py applies between rows)
        import gc

        gc.collect()


def run_sustained_row(
    pods: int = 30_000,
    qps: float = SUSTAINED_QPS,
    seed: int = 14,
    *,
    node_cpu: int = 32,
    max_batch: int = 4096,
    wait_timeout: float = 900.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """The committed sustained-arrival row (``bench.py --config
    sustained``). Headline = arrival→bind p99 next to rate-normalized
    throughput; verdict surface = zero lost + staleness SLO + overlap
    actually occurring."""
    trace = build_sustained_trace(seed, pods, qps)
    n_nodes = len(sustained_nodes(trace, node_cpu=node_cpu))
    if progress:
        progress(f"[sustained] {len(trace.events)} arrivals over "
                 f"{trace.duration_s:.1f}s (offered "
                 f"{trace.offered_rate:.0f} pods/s), {n_nodes} nodes, "
                 f"seed {seed}")
    stats, extras = run_sustained_once(
        trace, node_cpu=node_cpu, max_batch=max_batch,
        wait_timeout=wait_timeout, progress=progress)
    _sustained_diag(extras)
    offered = stats.offered_rate
    value = (stats.ever_bound / stats.last_bind_s
             if stats.last_bind_s > 0 else 0.0)
    zero_lost = (stats.lost == 0
                 and stats.injected == stats.expected
                 and not stats.send_errors)
    row = {
        "metric": (
            f"sustained_arrival[open-loop {offered:.0f}/s "
            f"{n_nodes}nodes/{len(trace.events)}pods seed={seed}, "
            f"store-direct replay engine]"),
        "value": round(value, 1),
        "unit": "pods/s",
        "offered_rate_pods_per_sec": round(offered, 2),
        "rate_normalized_throughput": round(
            value / offered, 3) if offered > 0 else 0.0,
        "p99_arrival_to_bind_ms": round(stats.latency_p99_ms()),
        "p50_arrival_to_bind_ms": round(
            stats.arrival_to_bind.get("all", {}).get("p50", 0.0)
            * 1000),
        "injected": stats.injected,
        "ever_bound": stats.ever_bound,
        "pending_at_end": stats.pending_at_end,
        "lost_pods": stats.lost,
        "invariants": {"zero_lost_pods": zero_lost},
        "invariants_ok": zero_lost,
        "pipeline": extras.get("pipeline"),
        "mirror": extras.get("mirror"),
        "session": extras.get("session"),
    }
    if extras.get("telemetry"):
        row["telemetry"] = extras["telemetry"]
    fresh = extras.get("freshness") or {}
    if fresh:
        row["freshness"] = fresh
        slo = fresh.get("slo") or {}
        # every SLO gates this row — a sustained 5k/s open-loop run
        # with a sub-500ms latency bar has no excuse for a red verdict
        row["slo_verdicts_ok"] = (
            all(v == "ok" for v in slo.values()) if slo else None)
        row["slo_gated"] = sorted(slo)
    if progress:
        pipe = extras.get("pipeline") or {}
        progress(f"[sustained] {stats.ever_bound}/{stats.injected} "
                 f"bound, p99 arrival→bind "
                 f"{row['p99_arrival_to_bind_ms']}ms, lost "
                 f"{stats.lost}, overlap_share "
                 f"{pipe.get('overlap', 0.0):.2f}, depth "
                 f"{pipe.get('depth', 0)}")
    return row


def _sustained_diag(extras: Dict) -> None:
    import sys

    from kubernetes_tpu.harness import diagfmt

    segs = [diagfmt.format_pipeline(extras.get("pipeline")),
            diagfmt.format_mirror(extras.get("mirror"))]
    segs = [s for s in segs if s]
    if segs:
        print(diagfmt.format_diag(segs), file=sys.stderr, flush=True)


def run_sustained_cell(
    pods: int = 600,
    qps: float = 400.0,
    seed: int = 14,
    *,
    node_cpu: int = 16,
    max_batch: int = 64,
    pipeline: Optional[bool] = None,
    wait_timeout: float = 120.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """The tier-1 mini-cell: a small open-loop run (compressed scale,
    small pad bucket so several pipeline cycles occur) returning just
    the verdict surface — overlap share, staleness SLO verdict, lost
    count, p99. The fast suite asserts ``overlap_share > 0`` (the
    pipeline genuinely overlaps) and the staleness verdict stays
    green, inside the tier-1 time budget."""
    trace = build_sustained_trace(seed, pods, qps)
    stats, extras = run_sustained_once(
        trace, node_cpu=node_cpu, max_batch=max_batch,
        pipeline=pipeline, wait_timeout=wait_timeout,
        progress=progress)
    telemetry = extras.get("telemetry") or {}
    slo = (extras.get("freshness") or {}).get("slo") or {}
    return {
        "injected": stats.injected,
        "ever_bound": stats.ever_bound,
        "lost": stats.lost,
        "p99_arrival_to_bind_ms": round(stats.latency_p99_ms()),
        "overlap_share": telemetry.get("overlap_share", 0.0),
        "overlapped_cycles": telemetry.get("overlapped_cycles", 0),
        "staleness_verdict": slo.get("snapshot_staleness"),
        "max_staleness_s": telemetry.get("max_staleness_s"),
        "encode_share": telemetry.get("encode_share", 0.0),
        "pipeline": extras.get("pipeline"),
        "mirror": extras.get("mirror"),
        "session": extras.get("session"),
    }
