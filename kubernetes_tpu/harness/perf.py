"""scheduler_perf-style benchmark runner (reference
``test/integration/scheduler_perf/``): executes an op list against an
in-process store + scheduler (no kubelets — binding is the finish line,
SURVEY.md section 3.5), samples scheduling throughput at 1 Hz
(``util.go:220-280`` throughputCollector), scrapes the scheduler
histograms, and emits DataItems-shaped JSON (``util.go:101-129``)."""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.api.types import Node
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler


def _percentile(samples: List[float], q: float) -> float:
    # delegates to the shared jax-free copy (harness/burst.py) — one
    # implementation of the exact-sample percentile across harnesses
    from kubernetes_tpu.harness.burst import sample_percentile

    return sample_percentile(samples, q)


class ThroughputCollector:
    """Samples scheduled-pod count at 1 Hz (util.go throughputCollector).

    ``count_fn`` overrides the counting source — the REST harness counts
    from the scheduler's own commit metric instead of scanning a store
    it doesn't share a process with."""

    def __init__(self, store: Optional[ClusterStore] = None,
                 interval: float = 1.0,
                 count_fn: Optional[Callable[[], int]] = None):
        self.store = store
        self.count_fn = count_fn
        self.interval = interval
        self.samples: List[float] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _count_scheduled(self) -> int:
        if self.count_fn is not None:
            return self.count_fn()
        return sum(1 for p in self.store.list_pods() if p.spec.node_name)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        last = self._count_scheduled()
        while not self._stop.wait(self.interval):
            now = self._count_scheduled()
            self.samples.append((now - last) / self.interval)
            last = now

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def summary(self) -> Dict[str, float]:
        # every 1 Hz sample counts, including idle ones (util.go appends
        # unconditionally) — dropping zeros would overstate burst paths
        samples = list(self.samples) or [0.0]
        return {
            "Average": sum(samples) / len(samples),
            "Perc50": _percentile(samples, 0.50),
            "Perc90": _percentile(samples, 0.90),
            "Perc99": _percentile(samples, 0.99),
        }


@dataclass
class BenchmarkResult:
    name: str
    total_pods: int
    measured_pods: int
    duration_seconds: float
    pods_per_second: float
    throughput: Dict[str, float]
    metrics: Dict[str, float] = field(default_factory=dict)
    # devprof per-row summary (compile count, dispatch-vs-block split,
    # pad waste, max-cycle attribution) — bench.py attaches this to the
    # row JSON as the ``telemetry`` sub-object
    telemetry: Dict[str, object] = field(default_factory=dict)
    # freshness SLI summary (watch-delivery p99, max snapshot staleness,
    # SLO verdicts) — bench.py attaches this to the row JSON as the
    # ``freshness`` sub-object
    freshness: Dict[str, object] = field(default_factory=dict)
    # fleet critical-path attribution (per-phase shares of sampled pods'
    # end-to-end latency, unattributed share, max clock skew) — bench.py
    # attaches this to the row JSON as the ``critical_path`` sub-object
    critical_path: Dict[str, object] = field(default_factory=dict)

    def data_items(self) -> dict:
        """DataItems JSON shape (util.go:101-129)."""
        return {
            "version": "v1",
            "dataItems": [
                {
                    "data": self.throughput,
                    "unit": "pods/s",
                    "labels": {"Name": self.name, "Metric": "SchedulingThroughput"},
                },
                {
                    "data": {"Average": self.pods_per_second},
                    "unit": "pods/s",
                    "labels": {"Name": self.name, "Metric": "OverallRate"},
                },
                {
                    "data": self.metrics,
                    "unit": "ms",
                    "labels": {"Name": self.name, "Metric": "SchedulingLatency"},
                },
            ],
        }


def reset_sli_window() -> None:
    """Fresh freshness-SLI + SLO evaluation window per bench row
    (mirrors the tracer clear and the devprof reset): each row's
    ``freshness`` sub-object and SLO verdicts must describe THAT row,
    not the process lifetime. Shared by the store-direct and REST
    harnesses."""
    try:
        from kubernetes_tpu.metrics.freshness_metrics import (
            freshness_metrics,
        )
        from kubernetes_tpu.observability.slo import get_slo_engine

        freshness_metrics().reset_window()
        get_slo_engine().reset(extra_registries=[])
    except Exception:  # noqa: BLE001 — SLIs must never fail a row
        pass


def attach_slo_baseline(sched) -> None:
    """Point the SLO engine at this row's scheduler registry (the e2e
    latency SLI lives there) and take the baseline sample — window
    deltas for cumulative series (the folded APF counters) start from
    here, so a quiet row can never inherit an earlier row's bad
    events."""
    try:
        from kubernetes_tpu.observability.slo import get_slo_engine

        engine = get_slo_engine()
        if engine.enabled:
            engine.add_registry(sched.metrics.registry)
            engine.tick()
    except Exception:  # noqa: BLE001
        pass


def collect_freshness(devprof_summary=None) -> dict:
    """The row's ``freshness`` sub-object: watch-delivery p99, max
    snapshot staleness, and the final SLO verdicts for the window
    opened by ``reset_sli_window``."""
    try:
        from kubernetes_tpu.metrics.freshness_metrics import (
            freshness_row_summary,
        )
        from kubernetes_tpu.observability.slo import get_slo_engine

        engine = get_slo_engine()
        slos = engine.evaluate().get("slos") if engine.enabled else None
        return freshness_row_summary(devprof_summary, slos)
    except Exception:  # noqa: BLE001
        return {}


def collect_critical_path(remote=(), token: str = "", max_pods: int = 25):
    """The row's ``critical_path`` sub-object plus the merged fleet
    trace doc. Always absorbs this process's tracer ring under the
    ``scheduler`` instance; ``remote`` adds (instance, url) apiserver
    children to scrape with skew correction. Returns ``({}, None)``
    when tracing is off or nothing was sampled — attribution must
    never fail a row."""
    try:
        from kubernetes_tpu.observability.fleettrace import (
            collect_fleet_trace,
        )
        from kubernetes_tpu.observability.tracer import get_tracer

        tracer = get_tracer()
        if not tracer.enabled:
            return {}, None
        doc, cp = collect_fleet_trace(
            remote=remote, local=[("scheduler", tracer)],
            token=token, max_pods=max_pods)
        if not cp.get("pods"):
            return {}, None
        row_cp = {k: v for k, v in cp.items() if k != "per_pod"}
        return row_cp, doc
    except Exception:  # noqa: BLE001 — attribution must never fail a row
        return {}, None


def run_workload(
    name: str,
    ops: List[dict],
    use_batch: bool = False,
    max_batch: int = 4096,
    wait_timeout: float = 600.0,
    progress: Optional[Callable[[str], None]] = None,
    backend_factory: Optional[Callable[[], object]] = None,
    result_hook: Optional[Callable[[object, object], None]] = None,
    adaptive_chunk: bool = True,
) -> BenchmarkResult:
    """Execute one workload (scheduler_perf_test.go:309 runWorkload).

    ``backend_factory`` overrides the solver backend (e.g. the
    mesh-sharded planes backend for the multi-chip scaling bench);
    ``result_hook(sched, bs)`` runs after the workload completes, before
    teardown — the scaling bench reads solver-segment histograms there."""
    from kubernetes_tpu.observability import get_tracer
    from kubernetes_tpu.observability.devprof import get_devprof
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    # fresh flight-recorder + devprof window per row: the result_hook's
    # diag line and the row's ``telemetry`` sub-object read from rings
    # that must describe THIS workload
    get_tracer().clear()
    get_devprof().reset(workload=name)
    reset_sli_window()
    store = ClusterStore()
    gates = FeatureGates({"TPUBatchScheduler": use_batch})
    # gang scheduling is first-class in this harness (BASELINE config #5):
    # the coscheduling wiring is always on — its queue sort degrades to
    # exactly PrioritySort when no pod declares a gang
    sched = Scheduler.create(store, feature_gates=gates,
                             provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(
        sched, max_batch=max_batch,
        backend=backend_factory() if backend_factory else None,
        adaptive_chunk=adaptive_chunk,
    ) if use_batch else None
    attach_slo_baseline(sched)
    sched.start()

    def pump_until_quiescent(deadline: float, wait_names=None) -> None:
        """Drive scheduling until done. With ``wait_names`` (the
        reference's waitForPodsScheduled: an op waits for ITS pods to be
        scheduled), done = every named pod bound — robust both to pods
        from earlier ops that legitimately pend (Unschedulable's
        impossible pods) and to mid-run victim deletion by preemption
        (victims are other ops' pods). Without names, done = full
        quiescence (queues drained, no bindings in flight). The store
        scan runs at most once per pump iteration, after progress or
        when idle — not in a tight loop against the bind path's lock."""
        def op_done() -> bool:
            bound = sum(
                1 for p in store.list_pods()
                if p.spec.node_name and p.metadata.name in wait_names
            )
            return bound >= len(wait_names)

        while time.monotonic() < deadline:
            sched.queue.flush_backoff_completed()
            if bs is not None:
                progressed = bs.run_batch(pop_timeout=0.01)
            else:
                progressed = sched.schedule_one(pop_timeout=0.01)
            if wait_names is not None and op_done():
                return
            if progressed:
                continue
            if sched.queue.pending_active_count() == 0:
                # async bind failures re-queue; settle them, then re-check
                sched.wait_for_inflight_bindings(timeout=10.0)
                sched.queue.flush_backoff_completed()
                if sched.queue.pending_active_count() == 0 and (
                    wait_names is None or op_done()
                ):
                    return
            time.sleep(0.005)
        raise TimeoutError(
            f"workload {name}: not all pods scheduled before deadline"
        )

    collector: Optional[ThroughputCollector] = None
    measure_start = 0.0
    measured_pods = 0
    created_nodes = 0
    created_pods = 0
    try:
        for op in ops:
            opcode = op["opcode"]
            if opcode == "createNodes":
                for i in range(op["count"]):
                    store.add_node(Node.from_dict(op["nodeTemplate"](created_nodes)))
                    created_nodes += 1
                if progress:
                    progress(f"{name}: {created_nodes} nodes")
            elif opcode == "createPods":
                template = op["podTemplate"]
                offset = op.get("offset", 0)
                collect = op.get("collectMetrics", False)
                if collect and bs is not None:
                    from kubernetes_tpu.ops.encode import is_host_only

                    # compile/cache-load the solver outside the measured
                    # window (JIT warm-up is setup, like the reference's
                    # informer warm-up before scheduler_perf collects).
                    # Warm with a representative SAMPLE of this op's pods:
                    # the compiled shape depends on the deduped constraint/
                    # term/profile space, and workload templates commonly
                    # cycle through modulo-k groups (one pod would warm a
                    # 1-term shape while the real batches carry k terms).
                    samples = [
                        Pod.from_dict(template(offset + i))
                        for i in range(min(200, op["count"]))
                    ]
                    # host-only pods (unbound PVCs, host ports) never
                    # take the batch path — don't compile device shapes
                    # for them (bound-PVC pods DO batch, so the client
                    # must inform the check or their shape stays cold)
                    samples = [
                        p for p in samples if not is_host_only(p, store)
                    ]
                    warm = bs.warmup(sample_pods=samples) if samples else 0.0
                    if progress and warm > 0.05:
                        progress(f"{name}: solver warmup {warm:.1f}s")
                if collect:
                    collector = ThroughputCollector(store)
                    measure_start = time.monotonic()
                    measured_pods = op["count"]
                    collector.start()
                op_names = set()
                new_pods = [
                    Pod.from_dict(template(offset + i))
                    for i in range(op["count"])
                ]
                op_names.update(p.metadata.name for p in new_pods)
                # bulk admission: one store lock + one batched watch
                # delivery (queue.add_many) for the whole op
                store.create_pods(new_pods)
                created_pods += len(new_pods)
                if progress:
                    progress(f"{name}: {created_pods} pods created")
                if not op.get("skipWaitToCompletion", False):
                    # an op waits for ITS pods (scheduler_perf
                    # waitForPodsScheduled), not global quiescence
                    pump_until_quiescent(
                        time.monotonic() + wait_timeout,
                        wait_names=op_names,
                    )
            elif opcode == "setup":
                op["fn"](store)
            elif opcode == "barrier":
                pump_until_quiescent(time.monotonic() + wait_timeout)
            else:
                raise ValueError(f"unknown opcode {opcode!r}")
        if bs is not None:
            # the wait_names early-return can leave one solved batch of
            # earlier ops' retried pods uncommitted in the pipeline;
            # commit it before declaring the run over
            bs.flush()
        sched.wait_for_inflight_bindings(timeout=30.0)
        duration = time.monotonic() - measure_start if measure_start else 0.0
        if result_hook is not None:
            result_hook(sched, bs)
    finally:
        if collector:
            collector.stop()
        sched.stop()

    e2e = sched.metrics.e2e_scheduling_duration
    metrics = {
        "Perc50": e2e.quantile(0.50, "scheduled") * 1000,
        "Perc90": e2e.quantile(0.90, "scheduled") * 1000,
        "Perc99": e2e.quantile(0.99, "scheduled") * 1000,
    }
    dp = get_devprof()
    telemetry = dp.summary() if dp.enabled else {}
    # single-process rows: every span already lives in this tracer, so
    # the fleet merge degenerates to one skew-free "scheduler" track
    critpath, _ = collect_critical_path()
    return BenchmarkResult(
        name=name,
        total_pods=created_pods,
        measured_pods=measured_pods,
        duration_seconds=duration,
        pods_per_second=(measured_pods / duration) if duration > 0 else 0.0,
        throughput=collector.summary() if collector else {},
        metrics=metrics,
        telemetry=telemetry,
        freshness=collect_freshness(telemetry),
        critical_path=critpath,
    )


def write_json(result: BenchmarkResult, path: str) -> None:
    with open(path, "w") as f:
        json.dump(result.data_items(), f, indent=2)
