"""Hotspot bench + live-resharding cells for the elastic control plane.

The production failure mode the static PR 9 layout cannot answer: ONE
namespace takes most of the write load, so one partition process
saturates while its siblings idle. This harness measures it and the
recovery:

- ``run_hotspot_row`` (``bench.py --config hotspot``) runs three arms
  at the same scale over REAL partition server processes:

  * **balanced** — writes spread uniformly (the fleet's honest
    ceiling);
  * **hotspot** — 80% of writes to one namespace, rebalancer OFF (the
    failure mode, measured);
  * **rebalanced** — same skew with the ``PartitionRebalancer`` live:
    it observes the per-slot/per-namespace write ledgers, SPLITS the
    hot namespace across the keyspace mid-run (writers ride the
    freeze window as ordinary 429 pushback), and throughput recovers.

  The row's verdict is ``recovery_ratio`` — the rebalanced arm's
  post-action steady-state rate over the balanced arm's rate (≥ 0.8
  is the acceptance bar) — plus hard invariants: zero lost pods, zero
  lost watch events (a live informer's final state is compared against
  server truth), and zero relists of unmoved slices.

- ``run_reshard_mini_cell`` is the tier-1-fast live-split cell: 2→3
  partitions at ~200 hollow nodes with writes and an informer active
  THROUGH the migration, asserting the informer's final state equals
  server truth and that no unmoved slice relisted.

Child mains are jax-free (harness/__init__ contract).
"""

from __future__ import annotations

import multiprocessing as mp
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.harness.burst import make_burst_pods
from kubernetes_tpu.harness.scale import (
    CREATOR_TOKEN,
    SCHEDULER_TOKEN,
    _scale_apiserver_main,
)

HOT_NS = "hot-tenant"
POD_CPU_MILLI = 100
POD_MEMORY = "50Mi"


def _cold_namespaces(n: int = 9) -> List[str]:
    return [f"cold-{i}" for i in range(n)]


# ---------------------------------------------------------------------------
# creator child (spawned; jax-free): skewed open-throttle writes


def _hotspot_creator_main(conn, urls: List[str], seed: int) -> None:
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    client = RestClusterClient(urls[0], partition_urls=urls,
                               token=CREATOR_TOKEN, qps=None)
    try:
        client.enable_topology(poll_interval=0.25)
    except Exception:  # noqa: BLE001 — static servers: stay static
        pass
    rng = random.Random(seed)
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        _cmd, count, offset, hot_share, namespaces, chunk = msg
        confirmed = 0
        made = 0
        try:
            while made < count:
                n = min(chunk, count - made)
                # draw the skew, then group per namespace so each
                # bulk POST is one partition-splittable batch
                per_ns: Dict[str, int] = {}
                for _ in range(n):
                    ns = HOT_NS if rng.random() < hot_share \
                        else rng.choice(namespaces)
                    per_ns[ns] = per_ns.get(ns, 0) + 1
                pods = []
                for ns, k in per_ns.items():
                    pods.extend(make_burst_pods(
                        k, cpu_milli=POD_CPU_MILLI, memory=POD_MEMORY,
                        name_prefix=f"hs{seed}-", uid_prefix=f"hu{seed}-",
                        offset=offset + made + len(pods),
                        namespaces=[ns]))
                confirmed += client.create_objects_bulk("Pod", pods)
                made += n
            conn.send(("done", confirmed))
        except Exception as e:  # noqa: BLE001 — surface the real error
            conn.send(("error", f"{type(e).__name__}: {e}"[:500]))
    client._stop_watches()
    client._drop_conn()
    conn.send("stopped")


# ---------------------------------------------------------------------------
# one measured arm over real partition processes


def run_hotspot_arm(
    pods: int,
    partitions: int = 3,
    hot_share: float = 0.8,
    rebalance: bool = False,
    creator_clients: int = 3,
    chunk: int = 64,
    namespaces: Optional[List[str]] = None,
    wait_timeout: float = 600.0,
    sample_s: float = 0.25,
    rebalance_interval_s: float = 0.4,
    sustain_ticks: int = 2,
    cooldown_s: float = 2.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """One arm: P apiserver processes, skewed creator children, a live
    elastic informer in the parent, and (``rebalance=True``) the
    PartitionRebalancer driving splits/moves through the coordinator."""
    from kubernetes_tpu.apiserver.partition import PartitionTopology
    from kubernetes_tpu.apiserver.reshard import ReshardCoordinator
    from kubernetes_tpu.autoscaler.partitions import (
        PartitionGroup,
        PartitionRebalancer,
        RebalancePolicy,
        RestElasticDriver,
    )
    from kubernetes_tpu.client import SharedInformerFactory
    from kubernetes_tpu.client.restcluster import RestClusterClient

    namespaces = namespaces or _cold_namespaces()
    ctx = mp.get_context("spawn")
    servers = []
    urls: List[str] = []
    for i in range(partitions):
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(target=_scale_apiserver_main,
                           args=(child_conn, i, partitions, None),
                           daemon=True)
        proc.start()
        servers.append((parent_conn, proc))
        urls.append(parent_conn.recv())

    creators = []
    for c in range(creator_clients):
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(target=_hotspot_creator_main,
                           args=(child_conn, urls, 1000 + c),
                           daemon=True)
        proc.start()
        creators.append((parent_conn, proc))

    control = RestClusterClient(urls[0], partition_urls=urls,
                                token=SCHEDULER_TOKEN, qps=None,
                                watch_kinds=("Pod",))
    # the freeze budget must comfortably cover the worst-case slice
    # copy (a late split moves 2/3 of the hot tenant): an eta that
    # expires MID-copy thaws writers into the seam the freeze exists
    # to close
    coordinator = ReshardCoordinator(control, freeze_eta=15.0,
                                     evict_grace_s=0.2)
    rebalancer = None
    factory = None
    row: Dict = {}

    def teardown() -> None:
        for conn, _proc in creators + servers:
            try:
                conn.send("stop")
            except (BrokenPipeError, OSError):
                pass
        for conn, proc in creators + servers:
            try:
                if conn.poll(3.0):
                    conn.recv()
            except (EOFError, OSError):
                pass
            proc.join(timeout=3.0)
            if proc.is_alive():
                proc.terminate()

    try:
        # install the live topology (epoch 1) fleet-wide
        topo = PartitionTopology.default(partitions, urls=urls)
        coordinator.install_topology(topo)
        control.enable_topology(poll_interval=0.25)

        # the watch consumer whose zero-loss is the row's invariant
        factory = SharedInformerFactory(control)
        pod_lister = factory.lister_for("Pod")
        factory.start()
        factory.wait_for_cache_sync()

        if rebalance:
            driver = RestElasticDriver(coordinator)
            rebalancer = PartitionRebalancer(
                driver, group=PartitionGroup(
                    min_partitions=partitions,
                    max_partitions=partitions, cooldown_s=cooldown_s),
                policy=RebalancePolicy(min_rate=30.0,
                                       sustain_ticks=sustain_ticks),
                interval_s=rebalance_interval_s)
            rebalancer.run()

        # -- measured injection --------------------------------------
        share = pods // len(creators)
        t0 = time.monotonic()
        for c, (conn, _proc) in enumerate(creators):
            n = share if c < len(creators) - 1 \
                else pods - share * (len(creators) - 1)
            conn.send(("pods", n, c * (pods + 16), hot_share,
                       namespaces, chunk))
        series: List[Tuple[float, int]] = []
        done = 0
        confirmed = 0
        deadline = time.monotonic() + wait_timeout
        last_note = 0.0
        while done < len(creators) and time.monotonic() < deadline:
            total = 0
            for p in range(len(control.partition_urls)):
                try:
                    got = coordinator._admin_get(p)
                    total += int(got.get("mutations") or 0)
                except Exception:  # noqa: BLE001 — mid-migration blip
                    pass
            series.append((time.monotonic() - t0, total))
            for conn, _proc in creators:
                if conn.poll(0.0):
                    status, n = conn.recv()
                    if status == "error":
                        raise RuntimeError(f"creator failed: {n}")
                    confirmed += n
                    done += 1
            if progress and time.monotonic() - last_note > 5:
                last_note = time.monotonic()
                progress(f"hotspot[{'rebal' if rebalance else 'static'}"
                         f" {hot_share:.0%}]: t={series[-1][0]:.1f}s "
                         f"mutations={series[-1][1]}")
            time.sleep(sample_s)
        if done < len(creators):
            raise TimeoutError(
                f"hotspot arm: {done}/{len(creators)} creators done "
                f"before deadline")
        elapsed = time.monotonic() - t0
        if rebalancer is not None:
            rebalancer.stop()
        time.sleep(1.5)   # quiesce: streams drain, informer catches up

        # -- server truth (key-level union across partitions) --------
        # ``confirmed`` is a client-side LOWER bound: a bulk create
        # whose response is lost re-sends, and the retry reports only
        # the items that were still new — so raw count comparisons
        # would misread retry under-counting as duplication. Key-level
        # union is exact: a real duplicate is one key on two servers.
        union: Dict[Tuple[str, str], str] = {}
        dup_pods = 0
        per_part: List[int] = []
        for p in range(len(control.partition_urls)):
            objs, _rv = control._list_with_rv("Pod", partition=p)
            per_part.append(len(objs))
            for o in objs:
                key = (o.metadata.namespace, o.metadata.name)
                if key in union:
                    dup_pods += 1
                union[key] = o.metadata.resource_version
        pods_total = len(union)
        inf = {(o.metadata.namespace, o.metadata.name):
               o.metadata.resource_version for o in pod_lister.list()}
        missing = [k for k in union if k not in inf]
        extra = [k for k in inf if k not in union]
        stale = [k for k, rv in union.items()
                 if k in inf and inf[k] != rv]
        informer_pods = len(inf)
        lost_pods = max(0, confirmed - pods_total)
        lost_watches = len(missing) + len(extra) + len(stale)
        unmoved_relists = sum(
            v for (kind, p), v in control.stream_relists.items())

        # recovered steady-state rate: mutations/s over the window
        # AFTER the last rebalance action landed (trailing idle
        # samples — the poll loop outliving the creators — trimmed so
        # a short run's tail can't dilute the recovered rate)
        def window_rate(frac: float) -> float:
            live = list(series)
            while len(live) > 2 and live[-1][1] <= live[-2][1]:
                live.pop()
            if len(live) < 3:
                return confirmed / elapsed if elapsed else 0.0
            start_idx = int(len(live) * (1.0 - frac))
            if rebalancer is not None and rebalancer.actions:
                acted_rel = max(a["at"] for a in rebalancer.actions) \
                    - t0
                for i, (t_rel, _v) in enumerate(live):
                    if t_rel >= acted_rel:
                        start_idx = i
                        break
            # a usable window needs real samples: when the action
            # landed near the end, widen back (conservative — the
            # pre-action throttled time only UNDERSTATES recovery)
            start_idx = min(start_idx,
                            len(live) - max(4, len(live) // 5))
            start_idx = max(0, start_idx)
            cut = live[start_idx]
            last = live[-1]
            dt = last[0] - cut[0]
            return (last[1] - cut[1]) / dt if dt > 0 else 0.0

        # the rebalancer drives THIS coordinator, so its action reports
        # are already in coordinator.reports — identity-dedupe
        migrations = list(coordinator.reports)
        if rebalancer is not None:
            for a in rebalancer.actions:
                rep = a.get("report")
                if rep and all(rep is not m for m in migrations):
                    migrations.append(rep)
        arm = {
            "pods": pods,
            "partitions": partitions,
            "hot_share": hot_share,
            "rebalance": rebalance,
            "confirmed": confirmed,
            "pods_per_sec": round(confirmed / elapsed, 1)
            if elapsed else 0.0,
            "recovered_rate": round(window_rate(0.35), 1),
            "elapsed_s": round(elapsed, 2),
            "server_pods_total": pods_total,
            "per_partition_pods": per_part,
            "lost_pods": lost_pods,
            "duplicated_pods": dup_pods,
            "informer_pods": informer_pods,
            "lost_watches": lost_watches,
            "unmoved_relists": unmoved_relists,
            "rv_regressions": len(control.rv_regressions),
            "epoch": control.topology_epoch,
            "migrations": migrations,
            "rebalancer_actions": [a["action"] for a in
                                   (rebalancer.actions
                                    if rebalancer else [])],
        }
        return arm
    finally:
        if rebalancer is not None:
            rebalancer.stop()
        if factory is not None:
            factory.stop()
        control._stop_watches()
        control._drop_conn()
        teardown()


def run_hotspot_row(
    pods: int = 24_000,
    partitions: int = 3,
    hot_share: float = 0.8,
    creator_clients: int = 3,
    wait_timeout: float = 600.0,
    rebalance_interval_s: float = 0.3,
    sustain_ticks: int = 2,
    cooldown_s: float = 1.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """The committed bench row: balanced / hotspot / rebalanced arms,
    recovery ratio + invariants, and the ``reshard[...]`` diag."""
    balanced = run_hotspot_arm(
        pods=pods, partitions=partitions, hot_share=0.0,
        rebalance=False, creator_clients=creator_clients,
        wait_timeout=wait_timeout, progress=progress)
    hotspot = run_hotspot_arm(
        pods=pods, partitions=partitions, hot_share=hot_share,
        rebalance=False, creator_clients=creator_clients,
        wait_timeout=wait_timeout, progress=progress)
    rebalanced = run_hotspot_arm(
        pods=pods, partitions=partitions, hot_share=hot_share,
        rebalance=True, creator_clients=creator_clients,
        wait_timeout=wait_timeout,
        rebalance_interval_s=rebalance_interval_s,
        sustain_ticks=sustain_ticks, cooldown_s=cooldown_s,
        progress=progress)

    balanced_rate = balanced["pods_per_sec"]
    recovery_ratio = (rebalanced["recovered_rate"] / balanced_rate) \
        if balanced_rate else 0.0
    hot_ratio = (hotspot["pods_per_sec"] / balanced_rate) \
        if balanced_rate else 0.0
    invariants = {
        "lost_pods": sum(a["lost_pods"] for a in
                         (balanced, hotspot, rebalanced)),
        "duplicated_pods": sum(a["duplicated_pods"] for a in
                               (balanced, hotspot, rebalanced)),
        "lost_watches": sum(a["lost_watches"] for a in
                            (balanced, hotspot, rebalanced)),
        "unmoved_relists": rebalanced["unmoved_relists"],
        "rv_regressions": sum(a["rv_regressions"] for a in
                              (balanced, hotspot, rebalanced)),
        "rebalancer_acted": bool(rebalanced["rebalancer_actions"]),
    }
    invariants_ok = (invariants["lost_pods"] == 0
                     and invariants["duplicated_pods"] == 0
                     and invariants["lost_watches"] == 0
                     and invariants["unmoved_relists"] == 0
                     and invariants["rv_regressions"] == 0
                     and invariants["rebalancer_acted"])
    frozen_ms = sum(m.get("frozen_ms", 0.0)
                    for m in rebalanced["migrations"])
    _reshard_diag(rebalanced, frozen_ms, invariants)
    return {
        "metric": (f"hotspot_recovery[{partitions}p, one namespace "
                   f"{hot_share:.0%} of {pods} writes, elastic "
                   f"control plane]"),
        "value": round(recovery_ratio, 3),
        "unit": "ratio",
        "balanced_pods_per_sec": balanced_rate,
        "hotspot_pods_per_sec": hotspot["pods_per_sec"],
        "hotspot_ratio_vs_balanced": round(hot_ratio, 3),
        "rebalanced_pods_per_sec": rebalanced["pods_per_sec"],
        "recovered_rate": rebalanced["recovered_rate"],
        "recovery_ratio": round(recovery_ratio, 3),
        "migrations": rebalanced["migrations"],
        "rebalancer_actions": rebalanced["rebalancer_actions"],
        "epoch": rebalanced["epoch"],
        "frozen_ms_total": round(frozen_ms, 2),
        "per_partition_pods": {
            "hotspot": hotspot["per_partition_pods"],
            "rebalanced": rebalanced["per_partition_pods"],
        },
        "invariants": invariants,
        "invariants_ok": invariants_ok,
        "lost_watches": invariants["lost_watches"],
    }


def _reshard_diag(rebalanced: Dict, frozen_ms: float,
                  invariants: Dict) -> None:
    import sys

    from kubernetes_tpu.harness import diagfmt

    seg = diagfmt.format_reshard({
        "moves": len(rebalanced["migrations"]),
        "frozen_ms": frozen_ms,
        "epoch": rebalanced["epoch"],
        "lost_watches": invariants["lost_watches"],
    })
    print(diagfmt.format_diag([seg]), file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# the tier-1 mini-cell: live 2→3 split under writes + informer + fleet


def run_reshard_mini_cell(
    nodes: int = 200,
    pods: int = 240,
    partitions_from: int = 2,
    write_batch: int = 6,
    settle_s: float = 1.2,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """CI-fast live split: ``partitions_from`` in-process apiservers
    (real HTTP, shared process — spawn cost without the spawn), a
    hollow-node fleet, an elastic client + SharedInformerFactory, and a
    writer running THROUGH a ``split_to`` migration. Asserted by the
    caller: informer ≡ server truth, zero lost, zero relists of
    unmoved slices, bounded freeze."""
    from kubernetes_tpu.apiserver.partition import PartitionTopology
    from kubernetes_tpu.apiserver.reshard import ReshardCoordinator
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.client import SharedInformerFactory
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.kubemark import HollowFleet

    servers = [APIServer(store=ClusterStore(),
                         partition=(i, partitions_from)).start()
               for i in range(partitions_from)]
    urls = [s.url for s in servers]
    topo = PartitionTopology.default(partitions_from, urls=urls)
    for s in servers:
        s.install_topology(topo)

    client = RestClusterClient(urls[0], partition_urls=urls,
                               watch_kinds=("Pod", "Node"))
    coordinator = ReshardCoordinator(client, freeze_eta=5.0,
                                     evict_grace_s=0.1)
    factory = None
    fleet = None
    new_server = None
    try:
        assert client.enable_topology(poll_interval=0.15)
        factory = SharedInformerFactory(client)
        pod_lister = factory.lister_for("Pod")
        node_lister = factory.lister_for("Node")
        fleet = HollowFleet(client, interval=30.0)
        fleet.register(nodes, cpu="16", chunk=256)
        fleet.start()
        factory.start()
        factory.wait_for_cache_sync()
        if progress:
            progress(f"mini-cell: {nodes} hollow nodes registered")

        namespaces = [f"mc-{i}" for i in range(8)]
        stop = threading.Event()
        errors: List[str] = []
        confirmed = [0]

        def writer() -> None:
            i = 0
            while not stop.is_set():
                batch = make_burst_pods(
                    write_batch, cpu_milli=POD_CPU_MILLI,
                    memory=POD_MEMORY, name_prefix="mc-",
                    uid_prefix="mcu-", offset=i,
                    namespaces=namespaces)
                try:
                    confirmed[0] += client.create_objects_bulk(
                        "Pod", batch)
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{type(e).__name__}: {e}")
                    return
                i += write_batch
                time.sleep(0.01)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.4)

        # the LIVE SPLIT: a third partition joins and takes its share
        new_server = APIServer(
            store=ClusterStore(),
            partition=(partitions_from, partitions_from + 1)).start()
        report = coordinator.split_to(new_server.url)
        if progress:
            progress(f"mini-cell: split report {report}")
        time.sleep(0.6)   # keep writing through the new layout
        stop.set()
        t.join(timeout=5.0)
        time.sleep(settle_s)   # quiesce: informer catches up

        all_servers = servers + [new_server]
        union: Dict[tuple, str] = {}
        duplicates = 0
        for s in all_servers:
            for p in s.store.list_pods():
                key = (p.namespace, p.metadata.name)
                if key in union:
                    duplicates += 1
                union[key] = p.metadata.resource_version
        node_union = {
            n.name for s in all_servers for n in s.store.list_nodes()}
        inf = {(o.metadata.namespace, o.metadata.name):
               o.metadata.resource_version for o in pod_lister.list()}
        missing = [k for k in union if k not in inf]
        extra = [k for k in inf if k not in union]
        stale = [k for k in union if k in inf and inf[k] != union[k]]
        moved_relists = sum(
            v for (kind, p), v in client.stream_relists.items())
        return {
            "errors": errors,
            "confirmed": confirmed[0],
            "server_pods": len(union),
            "duplicates": duplicates,
            "nodes": len(node_union),
            "informer_nodes": len(node_lister.list()),
            "informer_pods": len(inf),
            "missing": missing[:5],
            "extra": extra[:5],
            "stale": stale[:5],
            "lost_watches": len(missing) + len(extra) + len(stale),
            "unmoved_relists": moved_relists,
            "rv_regressions": list(client.rv_regressions),
            "epoch": client.topology_epoch,
            "moved_objects": report["moved_objects"],
            "frozen_ms": report["frozen_ms"],
            "handoff_fetches": client.handoff_fetches,
        }
    finally:
        if factory is not None:
            factory.stop()
        if fleet is not None:
            fleet.stop()
        client._stop_watches()
        client._drop_conn()
        for s in servers + ([new_server] if new_server else []):
            s.shutdown_server()
