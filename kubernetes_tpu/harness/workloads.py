"""Declarative benchmark workloads (reference
``test/integration/scheduler_perf/config/performance-config.yaml`` +
the op DSL of ``scheduler_perf_test.go:42-47``).

An op is a dict: ``{"opcode": "createNodes"|"createPods"|"barrier", ...}``.
``WORKLOADS`` carries the reference's 16 named test cases (SURVEY.md
section 6), parameterizable by node/pod counts like the
{500Nodes, 5000Nodes} variants.
"""

from __future__ import annotations

from typing import Dict, List


def _zone(i: int, zones: int = 10) -> str:
    return f"zone-{i % zones}"


def node_template(i: int, cpu: str = "32", memory: str = "64Gi",
                  zones: int = 10) -> dict:
    return {
        "metadata": {
            "name": f"node-{i}",
            "labels": {
                "kubernetes.io/hostname": f"node-{i}",
                "topology.kubernetes.io/zone": _zone(i, zones),
            },
        },
        "status": {
            "capacity": {"cpu": cpu, "memory": memory, "pods": "110"},
        },
    }


def basic_pod(i: int, cpu: str = "500m", memory: str = "500Mi",
              labels: Dict[str, str] = None, extra_spec: dict = None) -> dict:
    spec = {
        "containers": [
            {"name": "c", "image": "registry/fake:1",
             "resources": {"requests": {"cpu": cpu, "memory": memory}}}
        ],
    }
    if extra_spec:
        spec.update(extra_spec)
    return {
        "metadata": {"name": f"pod-{i}", "labels": dict(labels or {})},
        "spec": spec,
    }


def _spread(max_skew: int, key: str, action: str, labels: Dict[str, str]) -> dict:
    return {
        "topologySpreadConstraints": [
            {"maxSkew": max_skew, "topologyKey": key,
             "whenUnsatisfiable": action,
             "labelSelector": {"matchLabels": labels}}
        ]
    }


def _affinity(kind: str, key: str, values: List[str], topo: str,
              weight: int = 0) -> dict:
    term = {
        "labelSelector": {
            "matchExpressions": [{"key": key, "operator": "In", "values": values}]
        },
        "topologyKey": topo,
    }
    if weight:
        block = {"preferredDuringSchedulingIgnoredDuringExecution": [
            {"weight": weight, "podAffinityTerm": term}
        ]}
    else:
        block = {"requiredDuringSchedulingIgnoredDuringExecution": [term]}
    return {"affinity": {kind: block}}


def make_workload(name: str, nodes: int, init_pods: int, measure_pods: int) -> List[dict]:
    """Build the op list for a named workload at the given scale."""
    builder = WORKLOADS[name]
    return builder(nodes, init_pods, measure_pods)


def _pods_op(count: int, pod_fn, collect: bool = False, offset: int = 0,
             skip_wait: bool = False) -> dict:
    return {
        "opcode": "createPods",
        "count": count,
        "podTemplate": pod_fn,
        "collectMetrics": collect,
        "offset": offset,
        "skipWaitToCompletion": skip_wait,
    }


def _nodes_op(count: int, **kw) -> dict:
    return {"opcode": "createNodes", "count": count,
            "nodeTemplate": lambda i: node_template(i, **kw)}


def _barrier() -> dict:
    return {"opcode": "barrier"}


def scheduling_basic(nodes, init_pods, measure_pods):
    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i)),
        _barrier(),
        _pods_op(measure_pods, lambda i: basic_pod(i), collect=True,
                 offset=init_pods),
    ]


def scheduling_pod_anti_affinity(nodes, init_pods, measure_pods):
    def pod(i):
        p = basic_pod(i, labels={"color": f"blue-{i % 100}"})
        p["spec"].update(
            _affinity("podAntiAffinity", "color", [f"blue-{i % 100}"],
                      "kubernetes.io/hostname")
        )
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i)),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def scheduling_pod_affinity(nodes, init_pods, measure_pods):
    def init_pod(i):
        return basic_pod(i, labels={"group": f"g{i % 50}"})

    def pod(i):
        p = basic_pod(i, labels={"group": f"g{i % 50}"})
        p["spec"].update(
            _affinity("podAffinity", "group", [f"g{i % 50}"],
                      "topology.kubernetes.io/zone")
        )
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, init_pod),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def scheduling_preferred_pod_affinity(nodes, init_pods, measure_pods):
    def pod(i):
        p = basic_pod(i, labels={"group": f"g{i % 50}"})
        p["spec"].update(
            _affinity("podAffinity", "group", [f"g{i % 50}"],
                      "kubernetes.io/hostname", weight=10)
        )
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i, labels={"group": f"g{i % 50}"})),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def scheduling_preferred_anti_affinity(nodes, init_pods, measure_pods):
    def pod(i):
        p = basic_pod(i, labels={"color": f"c{i % 100}"})
        p["spec"].update(
            _affinity("podAntiAffinity", "color", [f"c{i % 100}"],
                      "kubernetes.io/hostname", weight=10)
        )
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i, labels={"color": f"c{i % 100}"})),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def scheduling_node_affinity(nodes, init_pods, measure_pods):
    def pod(i):
        p = basic_pod(i)
        p["spec"]["affinity"] = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {"matchExpressions": [
                            {"key": "topology.kubernetes.io/zone",
                             "operator": "In",
                             "values": [f"zone-{i % 10}"]}
                        ]}
                    ]
                }
            }
        }
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i)),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def topology_spreading(nodes, init_pods, measure_pods):
    def pod(i):
        p = basic_pod(i, labels={"app": "spread"})
        p["spec"].update(
            _spread(1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "spread"})
        )
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i)),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def preferred_topology_spreading(nodes, init_pods, measure_pods):
    def pod(i):
        p = basic_pod(i, labels={"app": "spread"})
        p["spec"].update(
            _spread(1, "topology.kubernetes.io/zone", "ScheduleAnyway",
                    {"app": "spread"})
        )
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i)),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def mixed_scheduling_base_pod(nodes, init_pods, measure_pods):
    """Interleaved init pods with every constraint family, then plain
    measured pods (the reference's MixedSchedulingBasePod)."""
    builders = [
        lambda i: basic_pod(i),
        lambda i: _with(basic_pod(i, labels={"color": f"x{i % 20}"}),
                        _affinity("podAffinity", "color", [f"x{i % 20}"],
                                  "topology.kubernetes.io/zone")),
        # hostname-keyed anti-affinity: with 20 groups over 10 zones a
        # zone key would make the 11th member of a group permanently
        # unschedulable and deadlock the init op's wait-for-scheduled
        lambda i: _with(basic_pod(i, labels={"color": f"y{i % 20}"}),
                        _affinity("podAntiAffinity", "color", [f"y{i % 20}"],
                                  "kubernetes.io/hostname")),
        lambda i: _with(basic_pod(i, labels={"app": "mix"}),
                        _spread(2, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": "mix"})),
    ]

    def init_pod(i):
        return builders[i % len(builders)](i)

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, init_pod),
        _barrier(),
        _pods_op(measure_pods, lambda i: basic_pod(i), collect=True,
                 offset=init_pods),
    ]


def _with(pod: dict, extra: dict) -> dict:
    pod["spec"].update(extra)
    return pod


def preemption(nodes, init_pods, measure_pods):
    return [
        _nodes_op(nodes, cpu="4", memory="8Gi"),
        _pods_op(init_pods, lambda i: _prio(basic_pod(i, cpu="3"), 1)),
        _barrier(),
        _pods_op(measure_pods, lambda i: _prio(basic_pod(i, cpu="3"), 100),
                 collect=True, offset=init_pods),
    ]


def _prio(pod: dict, priority: int) -> dict:
    pod["spec"]["priority"] = priority
    return pod


def unschedulable(nodes, init_pods, measure_pods):
    """Many unschedulable pods pending while measured pods schedule."""
    def impossible(i):
        p = basic_pod(i)
        p["spec"]["nodeSelector"] = {"no-such-label": "true"}
        return p

    return [
        _nodes_op(nodes),
        # the impossible pods stay pending for the whole run (the
        # reference config marks this op skipWaitToCompletion)
        _pods_op(init_pods, impossible, skip_wait=True),
        _pods_op(measure_pods, lambda i: basic_pod(i), collect=True,
                 offset=init_pods),
    ]


def gang_scheduling(nodes, init_pods, measure_pods, gang_size: int = 10):
    """Coscheduling gangs + spread + fit (BASELINE config #5; no in-tree
    reference equivalent — the out-of-tree coscheduling pattern)."""
    def pod(i):
        gang = i // gang_size
        p = basic_pod(i, labels={
            "app": "gang",
            "pod-group.scheduling.k8s.io/name": f"gang-{gang}",
            "pod-group.scheduling.k8s.io/min-available": str(gang_size),
        })
        p["spec"].update(
            _spread(5, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "gang"})
        )
        return p

    return [
        _nodes_op(nodes),
        _pods_op(init_pods, lambda i: basic_pod(i)),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


def scheduling_secrets(nodes, init_pods, measure_pods):
    # secrets don't affect scheduling decisions; workload matches the
    # reference shape (pods referencing secret volumes are expressible —
    # secret volumes are not PVC volumes)
    return scheduling_basic(nodes, init_pods, measure_pods)


def _pvc_pod(i: int, claim: str, cpu: str = "500m") -> dict:
    p = basic_pod(i, cpu=cpu)
    p["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": claim}}
    ]
    return p


def _volumes_setup(count: int, storage_class: str, binding_mode: str,
                   provisioner: str = "kubernetes.io/fake",
                   csi_driver: str = "", prebound: bool = True,
                   offset: int = 0):
    """Create a StorageClass plus a 1:1 PV/PVC pair per pod (the
    reference pre-binds them via StartFakePVController,
    test/integration/util/util.go:109). ``csi_driver`` marks the PVs as
    CSI-provisioned so NodeVolumeLimits counts them against CSINode
    attach limits."""
    def setup(store):
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            ObjectMeta, PersistentVolume, PersistentVolumeClaim,
            StorageClass,
        )

        store.add_storage_class(StorageClass(
            metadata=ObjectMeta(name=storage_class),
            provisioner=provisioner,
            volume_binding_mode=binding_mode,
        ))
        for i in range(offset, offset + count):
            claim = f"claim-{i}"
            store.add_pv(PersistentVolume(
                metadata=ObjectMeta(name=f"pv-{i}"),
                capacity={"storage": parse_quantity("1Gi")},
                storage_class_name=storage_class,
                claim_ref=f"default/{claim}" if prebound else None,
                phase="Bound" if prebound else "Available",
                csi_driver=csi_driver,
            ))
            store.add_pvc(PersistentVolumeClaim(
                metadata=ObjectMeta(name=claim, namespace="default"),
                storage_class_name=storage_class,
                requests={"storage": parse_quantity("1Gi")},
                volume_name=f"pv-{i}" if prebound else "",
                phase="Bound" if prebound else "Pending",
            ))
    return {"opcode": "setup", "fn": setup}


def _pv_workload(storage_class: str, provisioner: str, csi_driver: str = "",
                 extra_setup=None):
    """Shared shape of the three PV scheduling workloads (they differ
    only in storage class, provisioner, and CSI-specific setup)."""
    def build(nodes, init_pods, measure_pods):
        ops = [_nodes_op(nodes)]
        if extra_setup is not None:
            ops.append({"opcode": "setup", "fn": extra_setup(nodes)})
        ops += [
            _volumes_setup(measure_pods, storage_class, "Immediate",
                           provisioner=provisioner, csi_driver=csi_driver,
                           offset=init_pods),
            _pods_op(init_pods, lambda i: basic_pod(i)),
            _barrier(),
            _pods_op(measure_pods, lambda i: _pvc_pod(i, f"claim-{i}"),
                     collect=True, offset=init_pods),
        ]
        return ops
    return build


def _csi_nodes_setup(nodes):
    def setup(store):
        from kubernetes_tpu.api.types import CSINode, CSINodeDriver, ObjectMeta

        for i in range(nodes):
            store.add_csi_node(CSINode(
                metadata=ObjectMeta(name=f"node-{i}"),
                drivers=[CSINodeDriver(
                    name="csi.fake.driver", node_id=f"node-{i}",
                    allocatable_count=39,
                )],
            ))
    return setup


def scheduling_shared_pvs(nodes, init_pods, measure_pods):
    """Shared/unbound-claim family (VERDICT r3 weak #7): the volume
    shapes round 3 left entirely on the host serial path. Round 4
    tensorized two of them — this family measures both the tensorized
    rate AND the remaining genuine fallback, so neither can silently
    cliff. Three populations:

    - 45%: SHARED RWX claims on non-CSI PVs (ten pods per claim,
      pre-bound) — no CSI driver ⇒ no attach budget to double-count,
      so these now BATCH (static PV-affinity masks only);
    - 45%: UNBOUND WaitForFirstConsumer claims over an affinity-free
      Available PV pool (1:1) — no per-node constraint, so these BATCH
      with the sidecar popping a real PV per claim at commit time;
    - 10%: SHARED RWX claims on CSI PVs — one attachment consumed by
      many pods is exactly what the per-pod attach columns cannot
      express, so these stay on the SERIAL path (``is_host_only``,
      ops/encode.py) and keep the fallback's rate measured.
    """
    def setup_shared(store):
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            ObjectMeta, PersistentVolume, PersistentVolumeClaim,
            StorageClass,
        )

        store.add_storage_class(StorageClass(
            metadata=ObjectMeta(name="shared-sc"),
            provisioner="kubernetes.io/fake",
            volume_binding_mode="Immediate",
        ))

        def shared_pair(name_prefix, count, csi_driver=""):
            for i in range(count):
                store.add_pv(PersistentVolume(
                    metadata=ObjectMeta(name=f"{name_prefix}-pv-{i}"),
                    capacity={"storage": parse_quantity("10Gi")},
                    storage_class_name="shared-sc",
                    access_modes=["ReadWriteMany"],
                    claim_ref=f"default/{name_prefix}-claim-{i}",
                    phase="Bound",
                    csi_driver=csi_driver,
                ))
                store.add_pvc(PersistentVolumeClaim(
                    metadata=ObjectMeta(name=f"{name_prefix}-claim-{i}",
                                        namespace="default"),
                    storage_class_name="shared-sc",
                    requests={"storage": parse_quantity("1Gi")},
                    access_modes=["ReadWriteMany"],
                    volume_name=f"{name_prefix}-pv-{i}",
                    phase="Bound",
                ))
        shared_pair("shared", max(n_batch_shared // 10, 1))
        shared_pair("csishared", max(n_serial // 10, 1),
                    csi_driver="rwx.csi.example.com")

    n_serial = measure_pods // 10
    n_batch_shared = (measure_pods - n_serial) // 2
    n_wfc = measure_pods - n_serial - n_batch_shared
    n_claims = max(n_batch_shared // 10, 1)
    n_csi_claims = max(n_serial // 10, 1)

    def pod(j):
        # j is the global template index (offset already applied)
        k = j - init_pods
        if k < n_batch_shared:
            return _pvc_pod(j, f"shared-claim-{k % n_claims}")
        if k < n_batch_shared + n_wfc:
            return _pvc_pod(j, f"claim-{j}")
        return _pvc_pod(j, f"csishared-claim-{k % n_csi_claims}")

    return [
        _nodes_op(nodes),
        {"opcode": "setup", "fn": setup_shared},
        # Available (unclaimed) PV pool for the unbound population:
        # WaitForFirstConsumer, so binding happens at scheduling time
        # (Immediate-mode unbound claims are correctly unschedulable
        # until the PV controller binds them)
        _volumes_setup(n_wfc, "unbound-sc",
                       "WaitForFirstConsumer", prebound=False,
                       offset=init_pods + n_batch_shared),
        _pods_op(init_pods, lambda i: basic_pod(i)),
        _barrier(),
        _pods_op(measure_pods, pod, collect=True, offset=init_pods),
    ]


# SchedulingInTreePVs: pre-bound in-tree PV/PVC pairs.
scheduling_in_tree_pvs = _pv_workload("intree-sc", "kubernetes.io/fake")
# SchedulingMigratedInTreePVs: the same pairs served through the
# CSI-migration path (PVs carry the CSI driver name).
scheduling_migrated_in_tree_pvs = _pv_workload(
    "migrated-sc", "pd.csi.storage.gke.io",
    csi_driver="pd.csi.storage.gke.io",
)
# SchedulingCSIPVs: CSI volumes counted against CSINode attach limits.
scheduling_csi_pvs = _pv_workload(
    "csi-sc", "csi.fake.driver", csi_driver="csi.fake.driver",
    extra_setup=_csi_nodes_setup,
)


def preemption_pvs(nodes, init_pods, measure_pods):
    """Preemption where the preempting pods carry PVCs (PreemptionPVs):
    victims evicted AND volumes bound in the same flow."""
    return [
        _nodes_op(nodes, cpu="4", memory="8Gi"),
        _volumes_setup(measure_pods, "preempt-sc", "Immediate",
                       offset=init_pods),
        _pods_op(init_pods, lambda i: _prio(basic_pod(i, cpu="3"), 1)),
        _barrier(),
        _pods_op(measure_pods,
                 lambda i: _prio(_pvc_pod(i, f"claim-{i}", cpu="3"), 100),
                 collect=True, offset=init_pods),
    ]


WORKLOADS = {
    "SchedulingBasic": scheduling_basic,
    "SchedulingPodAntiAffinity": scheduling_pod_anti_affinity,
    "SchedulingSecrets": scheduling_secrets,
    "SchedulingPodAffinity": scheduling_pod_affinity,
    "SchedulingPreferredPodAffinity": scheduling_preferred_pod_affinity,
    "SchedulingPreferredPodAntiAffinity": scheduling_preferred_anti_affinity,
    "SchedulingNodeAffinity": scheduling_node_affinity,
    "TopologySpreading": topology_spreading,
    "PreferredTopologySpreading": preferred_topology_spreading,
    "MixedSchedulingBasePod": mixed_scheduling_base_pod,
    "Preemption": preemption,
    "Unschedulable": unschedulable,
    "GangScheduling": gang_scheduling,
    "SchedulingInTreePVs": scheduling_in_tree_pvs,
    "SchedulingSharedPVs": scheduling_shared_pvs,
    "SchedulingMigratedInTreePVs": scheduling_migrated_in_tree_pvs,
    "SchedulingCSIPVs": scheduling_csi_pvs,
    "PreemptionPVs": preemption_pvs,
}
