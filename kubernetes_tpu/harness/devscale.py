"""Devices×throughput scaling bench — the ``devscale`` row — plus the
legacy multi-chip scaling-shape bench, behind ONE virtual-device
bootstrap.

The sharded-by-default solve (``ops.session.default_backend`` mesh
tier) claims the hardware, not the host, is the ceiling; this harness
is its proof surface. Because a JAX process fixes its device count at
backend init, every arm runs in a SPAWNED child whose environment
forces the device count (``XLA_FLAGS=--xla_force_host_platform_
device_count=N``) before the interpreter touches JAX — the same
mechanism tests/conftest.py uses, now living in exactly one place
(``ensure_virtual_devices``; ``bench_sharded.py`` is a thin shim over
it, so the committed ``sharded_scaling.log`` workflow keeps working
without a second diverging copy of the bootstrap).

Each child runs the workload END-TO-END through the sidecar with the
DEFAULT backend selection (``KTPU_SOLVER=auto`` → the mesh tier
whenever >1 device is visible; the 1-device reference arm pins
``KTPU_SOLVER=xla``, the same planes scan the mesh distributes — a
1-device "auto" child would pick the native C++ solver where it
builds, and the row would measure backend choice, not sharding) and
reports:

- ``pods_per_second`` — end-to-end, Amdahl-bounded by the host-side
  encode/commit pipeline (reported for honesty, not the scaling
  claim);
- ``solve_pods_per_sec`` — measured pods over the device solve phase,
  the devices×throughput number the row's ``value`` carries;
- per-arm devprof telemetry — ``device_wait_share``, per-cycle
  h2d/d2h/donated bytes — so the donation A/B (``KTPU_SHARDED_DONATE``
  on vs off at one mesh width) shows transfer bytes and device-wait
  share strictly lower with donation on.

Run via ``python bench.py --config devscale`` (or directly:
``python -m kubernetes_tpu.harness.devscale``). Absolute CPU rates say
nothing about TPU rates; the SHAPE — solve throughput vs device count
at fixed problem size — is the evidence that node-axis sharding pays
before multi-chip hardware exists.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

# default scales: the 50k-node tier (the plane size PR 9's partitioned
# fabric already drives) — big enough that per-pod compute dominates
# the per-pod collective (latency-bound, shard-count-dependent), the
# regime real multi-chip clusters live in. On shared-silicon virtual
# devices the 1-device baseline is itself intra-op multithreaded, so
# measured efficiency UNDERSTATES what real ICI meshes get; the shape
# (solve throughput growing with mesh width), not the efficiency, is
# the claim a virtual-device row can make.
FULL_NODES, FULL_PODS, FULL_BATCH = 51_200, 8_192, 2_048
QUICK_NODES, QUICK_PODS, QUICK_BATCH = 1_024, 2_048, 1_024

_FLAG = "xla_force_host_platform_device_count"

# the package ships without an installer: children spawned with
# ``-m kubernetes_tpu.harness.devscale`` can only import it with the
# repo root on their path, wherever the PARENT was invoked from
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def child_env(devices: int) -> Dict[str, str]:
    """A child-process environment with the virtual-device bootstrap
    applied AND the repo root importable (PYTHONPATH) — the parent may
    have been launched from any cwd."""
    env = ensure_virtual_devices(devices, dict(os.environ))
    env["PYTHONPATH"] = _REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def ensure_virtual_devices(n: int,
                           env: Optional[Dict[str, str]] = None,
                           ) -> Dict[str, str]:
    """THE spawn-with-XLA_FLAGS bootstrap: force an ``n``-device CPU
    host platform. With ``env=None`` mutates ``os.environ`` — which
    only works BEFORE any JAX backend initializes in this interpreter
    (the bench_sharded.py / conftest.py pattern); pass a copied env to
    prepare a child process instead."""
    target = os.environ if env is None else env
    flags = target.get("XLA_FLAGS", "")
    if _FLAG in flags:
        flags = re.sub(rf"--{_FLAG}=\d+", f"--{_FLAG}={n}", flags)
    else:
        flags = (flags + f" --{_FLAG}={n}").strip()
    target["XLA_FLAGS"] = flags
    return target


def force_cpu_platform() -> None:
    """This environment's sitecustomize pins a TPU-tunnel PJRT plugin
    via JAX_PLATFORMS, so env vars are too late — the working override
    is jax.config AFTER import, BEFORE first backend use."""
    import jax

    jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# child side: one measured arm, end-to-end through the sidecar


def run_devscale_arm(workload: str, nodes: int, pods: int,
                     max_batch: int, donate: bool,
                     wait_timeout: float = 3600.0) -> dict:
    """One end-to-end arm on THIS interpreter's device count. The
    backend comes from the session's default tier (KTPU_SOLVER in the
    environment), so the arm measures the actual default path."""
    force_cpu_platform()
    import jax

    devices = len(jax.devices())
    from kubernetes_tpu.harness import make_workload
    from kubernetes_tpu.harness.perf import run_workload

    seg: dict = {}
    mesh_info: dict = {}

    def hook(sched, bs):
        series = sched.metrics.batch_solve_duration._series
        for key, (_counts, total, count) in series.items():
            seg[key[0]] = (total, count)
        if bs is not None:
            mi = bs.mesh_info()
            if mi:
                mesh_info.update(mi)

    ops = make_workload(workload, nodes=nodes, init_pods=0,
                        measure_pods=pods)
    t0 = time.time()
    # adaptive_chunk=False: every arm must solve the IDENTICAL batch
    # partition, or the latency tuner shrinks the slow arms' chunks and
    # the comparison measures the tuner, not the sharding
    r = run_workload(
        f"{workload}/devscale-{devices}dev"
        + ("" if donate else "-nodonate"),
        ops, use_batch=True, max_batch=max_batch,
        wait_timeout=wait_timeout, progress=log, result_hook=hook,
        adaptive_chunk=False,
    )
    _, dev_batches = seg.get("device", (0.0, 0))
    tel = r.telemetry or {}
    cycles = max(int(tel.get("cycles", 0)), 1)
    # solve time from the devprof dispatch+block split, NOT the session
    # "device" histogram segment: with lazy pipelined solves the
    # histogram measures dispatch only (the block lands cycles later in
    # the commit pipeline), while devprof attributes the measured block
    # wait back to the cycle that dispatched it — the same number on
    # every arm, whichever side of the pipeline the wait surfaces on
    dev_total = float(tel.get("dispatch_s", 0.0)) \
        + float(tel.get("block_s", 0.0))
    return {
        "devices": devices,
        "donated": bool(donate),
        "pods_per_second": round(r.pods_per_second, 1),
        "p99_latency_ms": round(r.metrics.get("Perc99", 0)),
        "device_solve_s": round(dev_total, 3),
        "solve_batches": dev_batches,
        "solve_pods_per_sec": round(pods / dev_total, 1)
        if dev_total > 0 else 0.0,
        "device_wait_share": tel.get("device_wait_share", 0.0),
        "h2d_bytes_per_cycle": int(tel.get("h2d_bytes", 0) / cycles),
        "d2h_bytes_per_cycle": int(tel.get("d2h_bytes", 0) / cycles),
        "donated_bytes_per_cycle": int(
            tel.get("donated_bytes", 0) / cycles),
        "telemetry": tel,
        "mesh": mesh_info
        or {"devices": devices, "shards": 1, "donated": False},
        "wall_s": round(time.time() - t0, 1),
    }


# ---------------------------------------------------------------------------
# parent side: spawn one child per arm, assemble the row


def _spawn_arm(devices: int, workload: str, nodes: int, pods: int,
               max_batch: int, donate: bool,
               timeout: float = 3600.0) -> dict:
    env = child_env(devices)
    # the sharded-by-default tier under test: auto → mesh whenever >1
    # device; the 1-device reference pins the planes scan the mesh
    # distributes (see module docstring)
    env["KTPU_SOLVER"] = "auto" if devices > 1 else "xla"
    env["KTPU_SHARDED_DONATE"] = "1" if donate else "0"
    cmd = [
        sys.executable, "-m", "kubernetes_tpu.harness.devscale",
        "--child", "--workload", workload, "--nodes", str(nodes),
        "--pods", str(pods), "--max-batch", str(max_batch),
    ]
    if not donate:
        cmd.append("--no-donate")
    proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                          text=True, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"devscale child (devices={devices}, donate={donate}) "
            f"exited {proc.returncode}")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError("devscale child produced no row JSON")


def _ab_view(arm: dict) -> dict:
    """The donation-A/B slice of one arm: exactly the fields the
    acceptance bar names, per cycle."""
    return {
        "device_wait_share": arm["device_wait_share"],
        "h2d_bytes_per_cycle": arm["h2d_bytes_per_cycle"],
        "d2h_bytes_per_cycle": arm["d2h_bytes_per_cycle"],
        "donated_bytes_per_cycle": arm["donated_bytes_per_cycle"],
        "solve_pods_per_sec": arm["solve_pods_per_sec"],
        "pods_per_second": arm["pods_per_second"],
    }


def run_devscale_row(nodes: int = FULL_NODES, pods: int = FULL_PODS,
                     max_batch: int = FULL_BATCH,
                     device_counts: Sequence[int] = (1, 2, 4, 8),
                     donation_ab_devices: int = 4,
                     workload: str = "SchedulingBasic",
                     timeout: float = 3600.0,
                     progress=log) -> dict:
    """The devices×throughput row: one spawned child per device count
    (donation on), plus one donation-off child at ``donation_ab_devices``
    for the before/after telemetry A/B. ``value`` is the solve
    throughput at the A/B mesh width — the number the scaling claim is
    about; end-to-end pods/s rides each arm for honesty."""
    arms: List[dict] = []
    for d in device_counts:
        progress(f"--- devscale: {d} device(s), donation on ---")
        arms.append(_spawn_arm(d, workload, nodes, pods, max_batch,
                               donate=True, timeout=timeout))
    base = next((a for a in arms if a["devices"] == 1), None)
    for a in arms:
        if base and a["device_solve_s"] > 0 \
                and base["device_solve_s"] > 0:
            a["solve_speedup_vs_1dev"] = round(
                base["device_solve_s"] / a["device_solve_s"], 2)
    ab = None
    if donation_ab_devices in [a["devices"] for a in arms]:
        progress(f"--- devscale: {donation_ab_devices} device(s), "
                 f"donation OFF (A/B arm) ---")
        off = _spawn_arm(donation_ab_devices, workload, nodes, pods,
                         max_batch, donate=False, timeout=timeout)
        on = next(a for a in arms
                  if a["devices"] == donation_ab_devices)
        ab = {
            "devices": donation_ab_devices,
            "on": _ab_view(on),
            "off": _ab_view(off),
            # the acceptance bar: per-cycle transfer bytes (BOTH
            # directions — solver_transfer_bytes_total counts h2d and
            # d2h) AND device wait share strictly lower with donation on
            "donation_pays": (
                on["h2d_bytes_per_cycle"] < off["h2d_bytes_per_cycle"]
                and on["d2h_bytes_per_cycle"]
                < off["d2h_bytes_per_cycle"]
                and on["device_wait_share"] < off["device_wait_share"]
            ),
        }
    anchor = next((a for a in arms if a["devices"] == 4), arms[-1])
    row = {
        "metric": f"solve_throughput_devscale[{workload} {nodes}nodes/"
                  f"{pods}pods]",
        "value": anchor["solve_pods_per_sec"],
        "unit": "pods/s",
        "devices": anchor["devices"],
        # this harness always forces shared-silicon virtual devices:
        # the 1-device baseline is itself intra-op multithreaded, so
        # efficiency understates real meshes — perf_report's 0.6
        # efficiency gate applies to real-hardware rows only (the
        # ≥1.5× speedup bar and the donation A/B apply everywhere)
        "virtual_devices": True,
        "arms": arms,
        "solve_speedup_vs_1dev": {
            str(a["devices"]): a.get("solve_speedup_vs_1dev", 1.0)
            for a in arms
        },
    }
    four = next((a for a in arms if a["devices"] == 4), None)
    if four is not None and "solve_speedup_vs_1dev" in four:
        row["scaling_efficiency_4dev"] = round(
            four["solve_speedup_vs_1dev"] / 4.0, 3)
    if ab is not None:
        row["donation_ab"] = ab
    return row


# ---------------------------------------------------------------------------
# REST row on the sharded default: the deployable-fabric A/B


def run_rest_arm(nodes: int, pods: int, qps: Optional[float],
                 max_batch: int, wait_timeout: float = 1800.0) -> dict:
    """One REST-fabric arm on THIS interpreter's device count: the
    headline workload with every byte over HTTP (apiserver child, WAL,
    watch-fed scheduler), the scheduler's solve backend coming from
    the DEFAULT tier — so a multi-device interpreter runs the REST row
    on the sharded-by-default solve."""
    force_cpu_platform()
    import jax

    devices = len(jax.devices())
    from kubernetes_tpu.harness.rest_perf import run_workload_rest

    mesh_info: dict = {}

    def hook(sched, bs):
        if bs is not None:
            mi = bs.mesh_info()
            if mi:
                mesh_info.update(mi)

    t0 = time.time()
    r = run_workload_rest(
        "SchedulingBasic", nodes=nodes, measure_pods=pods,
        max_batch=max_batch, qps=qps, wait_timeout=wait_timeout,
        progress=log, result_hook=hook,
    )
    tel = r.telemetry or {}
    return {
        "devices": devices,
        "pods_per_second": round(r.pods_per_second, 1),
        "p99_latency_ms": round(r.metrics.get("Perc99", 0)),
        "server_pods_bound": r.metrics.get("server_pods_bound"),
        "device_wait_share": tel.get("device_wait_share", 0.0),
        "solve_s": round(float(tel.get("dispatch_s", 0.0))
                         + float(tel.get("block_s", 0.0)), 3),
        "mesh": mesh_info
        or {"devices": devices, "shards": 1, "donated": False},
        "wall_s": round(time.time() - t0, 1),
    }


def run_rest_sharded_ab(nodes: int, pods: int,
                        qps: Optional[float] = 5000.0,
                        max_batch: int = 2048, devices: int = 4,
                        timeout: float = 3600.0,
                        progress=log) -> dict:
    """The REST row A/B'd over the sharded default: one child with the
    mesh tier (``devices`` virtual devices, KTPU_SOLVER=auto), one on
    the single-device planes scan — the deployable-system view of the
    sharded-by-default solve. On real multi-chip hardware the sharded
    arm is the one that makes the hardware, not the fabric, the
    ceiling; on shared-silicon virtual devices the arm documents the
    PATH (mesh solve under the full REST pipeline), not a CPU win."""

    def spawn(dev_count: int) -> dict:
        env = child_env(dev_count)
        env["KTPU_SOLVER"] = "auto" if dev_count > 1 else "xla"
        env["KTPU_SHARDED_DONATE"] = "1"
        cmd = [
            sys.executable, "-m", "kubernetes_tpu.harness.devscale",
            "--child-rest", "--nodes", str(nodes), "--pods", str(pods),
            "--max-batch", str(max_batch),
            "--qps", str(qps if qps else 0),
        ]
        proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                              text=True, timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"rest-ab child (devices={dev_count}) exited "
                f"{proc.returncode}")
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError("rest-ab child produced no row JSON")

    progress(f"--- rest-ab: sharded default, {devices} device(s) ---")
    sharded = spawn(devices)
    progress("--- rest-ab: single-device reference ---")
    single = spawn(1)
    return {
        "sharded": sharded,
        "single_device": single,
        "sharded_vs_single": round(
            sharded["pods_per_second"]
            / max(single["pods_per_second"], 1e-9), 3),
    }


# ---------------------------------------------------------------------------
# legacy multi-chip scaling-shape bench (folded in from bench_sharded.py
# — the committed sharded_scaling.log workflow)


def _measure_sharded_cpu(name: str, nodes: int, pods: int, devices: int,
                         init_pods: int = 0) -> dict:
    """One end-to-end run; returns the JSON row. devices=1 uses the
    single-device planes scan, >1 the mesh-sharded backend."""
    from kubernetes_tpu.harness import make_workload, run_workload

    if devices == 1:
        def backend_factory():
            from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend

            return XlaPlanesBackend()
    else:
        def backend_factory():
            from kubernetes_tpu.parallel import ShardedBackend, make_mesh

            return ShardedBackend(make_mesh(devices, batch_axis=1))

    seg = {}
    mem = {}

    def _shard_bytes(x) -> int:
        """Bytes ONE device holds for array x (sharded arrays report a
        single shard; replicated/host arrays their full size)."""
        try:
            return x.addressable_shards[0].data.nbytes
        except Exception:  # noqa: BLE001 — numpy / non-jax fields
            return int(getattr(x, "nbytes", 0))

    def hook(sched, bs):
        series = sched.metrics.batch_solve_duration._series
        for key, (_counts, total, count) in series.items():
            seg[key[0]] = (total, count)
        # per-device footprint of the resident mirror (static planes +
        # carried state): the multi-chip memory story — per-device bytes
        # shrink ~1/N with the node axis sharded, so clusters larger
        # than one chip's HBM fit the mesh
        import dataclasses

        total_b = 0
        for obj in (bs.session._static, bs.session._state):
            if obj is None:
                continue
            if dataclasses.is_dataclass(obj):
                for f in dataclasses.fields(obj):
                    v = getattr(obj, f.name)
                    if hasattr(v, "nbytes") or hasattr(
                            v, "addressable_shards"):
                        total_b += _shard_bytes(v)
            elif isinstance(obj, (tuple, list)):
                for v in obj:
                    total_b += _shard_bytes(v)
        mem["per_device_bytes"] = total_b

    ops = make_workload(name, nodes=nodes, init_pods=init_pods,
                        measure_pods=pods)
    t0 = time.time()
    # adaptive_chunk=False: every mesh size must solve the IDENTICAL
    # batch partition (the latency tuner would shrink slow
    # configurations' chunks and inflate their batch counts — round-3's
    # 13-vs-29 artifact measured the tuner, not the sharding)
    r = run_workload(
        f"{name}/sharded-{devices}dev", ops, use_batch=True,
        max_batch=4096, wait_timeout=3600, progress=log,
        backend_factory=backend_factory, result_hook=hook,
        adaptive_chunk=False,
    )
    dev_total, dev_batches = seg.get("device", (0.0, 0))
    return {
        "metric": f"sharded_cpu[{name} {nodes}nodes/{pods}pods]",
        "devices": devices,
        "pods_per_second": round(r.pods_per_second, 1),
        "device_solve_s": round(dev_total, 3),
        "solve_batches": dev_batches,
        "mirror_bytes_per_device": mem.get("per_device_bytes", 0),
        "wall_s": round(time.time() - t0, 1),
    }


def _breakdown(n_nodes: int, batch_pods: int, device_counts) -> list:
    """Per-batch compute-vs-collective split on one representative
    solve batch. The ablated build (``collectives=False``) replaces
    every cross-shard op with a local stand-in of identical arithmetic
    shape, so full-minus-ablated wall time isolates pure collective
    cost — the quantity shared-silicon virtual devices inflate (every
    shard's collective work serializes onto the same cores) and real
    ICI does not."""
    import jax

    from kubernetes_tpu.ops import BatchEncoder
    from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend
    from kubernetes_tpu.ops.solver import SolverParams, pack_podin
    from kubernetes_tpu.parallel.sharded import (
        _build_solve,
        _prepare_sharded,
        make_mesh,
    )
    from kubernetes_tpu.scheduler.snapshot import new_snapshot
    from kubernetes_tpu.testing import MakeNode, MakePod

    nodes = [
        MakeNode().name(f"n{i}")
        .capacity({"cpu": "32", "memory": "64Gi", "pods": "110"}).obj()
        for i in range(n_nodes)
    ]
    pods = [
        MakePod().name(f"p{i}").uid(f"u{i}")
        .req({"cpu": "100m", "memory": "200Mi"}).obj()
        for i in range(batch_pods)
    ]
    snap = new_snapshot([], nodes)
    cluster, batch = BatchEncoder(snap, pad_nodes=128).encode(
        pods, pad_pods=batch_pods
    )
    params = SolverParams()
    ints, floats = pack_podin(batch)

    def timed(fn, reps: int = 3) -> float:
        fn()  # warm (compile)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            best = min(best, time.perf_counter() - t0)
        return best

    rows = []
    # single-device reference: the same planes scan the sharded build
    # distributes
    be = XlaPlanesBackend()
    static1, state1 = be.prepare(cluster, batch)
    base_s = timed(
        lambda: be.solve(params, static1, state1, ints, floats)[0]
    )
    rows.append({
        "metric": f"sharded_breakdown[{n_nodes}nodes/{batch_pods}pod-batch]",
        "devices": 1, "batch_solve_s": round(base_s, 3),
        "compute_s": round(base_s, 3), "collective_s": 0.0,
        "collective_frac": 0.0,
    })
    # 1-shard control: the SAME shard_map build on a 1-device mesh —
    # collectives are no-ops, so (control - planes-scan baseline)
    # isolates the shard_map machinery's constant overhead from
    # anything that scales with shard count
    for d in [1] + list(device_counts):
        mesh = make_mesh(d, batch_axis=1)
        sstatic, sstate = _prepare_sharded(cluster, batch, mesh)
        args = (sstatic.sc_meta, sstatic.ints, sstatic.f32s,
                sstate.planes, sstate.totals, ints, floats, ints,
                sstatic.has_dom)
        times = {}
        for collectives in (True, False):
            run = _build_solve(
                mesh, params, sstatic.r, sstatic.sc, sstatic.t,
                sstatic.u, sstatic.v, with_counts=False,
                any_hard=sstatic.any_hard, collectives=collectives,
            )
            with mesh:
                times[collectives] = timed(lambda: run(*args)[0])
        coll = max(times[True] - times[False], 0.0)
        rows.append({
            "metric":
                f"sharded_breakdown[{n_nodes}nodes/{batch_pods}pod-batch]"
                + ("(1-shard shard_map control)" if d == 1 else ""),
            "devices": d,
            "batch_solve_s": round(times[True], 3),
            "compute_s": round(times[False], 3),
            "collective_s": round(coll, 3),
            "collective_frac": round(coll / max(times[True], 1e-9), 3),
        })
    return rows


def run_sharded_cpu(quick: bool = False,
                    breakdown_only: bool = False) -> None:
    """The legacy scaling-shape flow (sharded_scaling.log): end-to-end
    rows per mesh size, the preemption family, and the per-batch
    compute/collective breakdown. Must own the interpreter's JAX
    platform — call ``ensure_virtual_devices(8)`` before any backend
    initializes (the bench_sharded.py shim does)."""
    force_cpu_platform()
    import jax

    n_dev = len(jax.devices())
    if n_dev < 8:
        log(f"WARNING: only {n_dev} CPU devices (wanted 8); "
            "XLA_FLAGS was set too late for this interpreter — run "
            "bench_sharded.py (or -m kubernetes_tpu.harness.devscale "
            "--sharded-cpu) directly")
    name = "SchedulingBasic"
    nodes, pods = (512, 4096) if quick else (5000, 30000)
    rows = []
    for devices in (1, 2, 4, 8):
        if devices > n_dev or breakdown_only:
            continue
        log(f"--- {devices} device(s) ---")
        rows.append(_measure_sharded_cpu(name, nodes, pods, devices))
    # preemption-heavy scaling row (VERDICT r4 next #4): the mass-
    # decline -> vectorized screen -> victim-planner flow on the mesh
    # path; fillers exactly fill the cluster so every measured pod
    # preempts
    p_nodes, p_pods = (256, 256) if quick else (1000, 1000)
    for devices in (1, 8):
        if devices > n_dev or breakdown_only:
            continue
        log(f"--- Preemption, {devices} device(s) ---")
        row = _measure_sharded_cpu("Preemption", p_nodes, p_pods,
                                   devices, init_pods=p_nodes)
        print(json.dumps(row), flush=True)
    base = next((r for r in rows if r["devices"] == 1), None)
    for r in rows:
        if base and r["device_solve_s"] > 0:
            r["solve_speedup_vs_1dev"] = round(
                base["device_solve_s"] / r["device_solve_s"], 2
            )
        print(json.dumps(r), flush=True)
    log("--- per-batch compute/collective breakdown ---")
    bd_nodes, bd_pods = (512, 1024) if quick else (5000, 4096)
    for row in _breakdown(bd_nodes, bd_pods,
                          [d for d in (2, 4, 8) if d <= n_dev]):
        print(json.dumps(row), flush=True)


# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true",
                    help="run ONE spawned arm on this interpreter's "
                         "forced device count")
    ap.add_argument("--child-rest", action="store_true",
                    help="run ONE spawned REST-fabric arm")
    ap.add_argument("--rest-ab", action="store_true",
                    help="REST row A/B: sharded default vs "
                         "single-device")
    ap.add_argument("--qps", type=float, default=5000.0)
    ap.add_argument("--sharded-cpu", action="store_true",
                    help="legacy scaling-shape flow "
                         "(sharded_scaling.log)")
    ap.add_argument("--workload", default="SchedulingBasic")
    ap.add_argument("--nodes", type=int, default=FULL_NODES)
    ap.add_argument("--pods", type=int, default=FULL_PODS)
    ap.add_argument("--max-batch", type=int, default=FULL_BATCH)
    ap.add_argument("--no-donate", action="store_true")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--breakdown-only", action="store_true")
    args = ap.parse_args(argv)

    if args.sharded_cpu:
        # the bootstrap must land before ANY jax import resolves a
        # backend — module import above is jax-free, and
        # run_sharded_cpu only imports jax inside, so this is in time
        # whether we were spawned by bench.py or invoked directly
        ensure_virtual_devices(8)
        run_sharded_cpu(quick=args.quick,
                        breakdown_only=args.breakdown_only)
        return
    if args.child:
        os.environ["KTPU_SHARDED_DONATE"] = \
            "0" if args.no_donate else "1"
        os.environ.setdefault("KTPU_SOLVER", "auto")
        row = run_devscale_arm(args.workload, args.nodes, args.pods,
                               args.max_batch,
                               donate=not args.no_donate)
        print(json.dumps(row), flush=True)
        return
    if args.child_rest:
        os.environ.setdefault("KTPU_SOLVER", "auto")
        row = run_rest_arm(args.nodes, args.pods,
                           qps=args.qps or None,
                           max_batch=args.max_batch)
        print(json.dumps(row), flush=True)
        return
    if args.rest_ab:
        nodes, pods = (1024, 4096) if args.quick else (5000, 30000)
        ab = run_rest_sharded_ab(nodes, pods, qps=args.qps or None,
                                 max_batch=args.max_batch)
        print(json.dumps({
            "metric": f"rest_sharded_ab[SchedulingBasic {nodes}nodes/"
                      f"{pods}pods]", **ab}), flush=True)
        return
    if args.quick:
        row = run_devscale_row(
            nodes=QUICK_NODES, pods=QUICK_PODS, max_batch=QUICK_BATCH,
            device_counts=(1, 2), donation_ab_devices=2)
    else:
        row = run_devscale_row()
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
