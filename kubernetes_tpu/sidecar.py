"""The TPU batch scheduler — the north-star component.

Recasts the per-pod serial loop as batched constraint satisfaction on
device (BASELINE.json north_star): drain the pending queue, ship the
snapshot + pod batch to the JAX solver (``kubernetes_tpu.ops``), evaluate
all predicates/scores as dense tensors, commit the returned assignments
through the framework's assume → Reserve → Permit → Bind pipeline so every
host-side contract (cache assume/TTL, volume reservations, gang permits,
events, metrics) is preserved.

The solve loop is a STREAMING PIPELINE (double-buffered, Pathways-style
host/device overlap): per pump cycle the host drains batch N+1 under a
non-blocking queue hint, encodes its delta columns against the live
snapshot, and dispatches its solve — jax dispatch is async, so the
dispatch chains onto batch N's in-flight state carry with no host
sync — then commits batch N−1 while the device crunches, with remote
clients' bulk binds flying on the binding pool (batch N−2 may still be
on the wire). Every correctness guard runs in its original stage:
stale-node probes and ``commit_fits`` at commit time, drift re-encode
via ``mirror_current``/``note_drift``, the mutation-ledger arithmetic
per cycle. ``KTPU_PIPELINE=off`` is the kill-switch: the exact
serialized barrier loop (drain → encode → solve → commit per call),
held bit-identical to the pipeline by the differential guard in
tests/test_pipeline.py. ``devprof`` measures what the overlap wins as
``overlap_share`` (the ``pipeline[...]`` diag segment).

Fallback contract (mirrors how extenders are ``IsIgnorable``,
``core/extender.go:154``; SURVEY.md section 5): any pod the tensor model
can't express — unbound/shared PVC volumes, inline cloud-disk volumes,
host ports, foreign scheduler profiles — and any pod the device marks
unschedulable goes through the UNMODIFIED serial path
(``schedule_pod_serial``), which also supplies preemption. Bound-PVC
pods ride the batch path since round 3 (PV affinity/zone as static
masks, CSI attach limits as resource columns — VERDICT r2 #1). Disabling
the ``TPUBatchScheduler`` feature gate removes the batch path entirely.

Enable with::

    sched = Scheduler.create(store, feature_gates=FeatureGates(
        {"TPUBatchScheduler": True}))
    attach_batch_scheduler(sched)
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional

_logger = logging.getLogger(__name__)

from kubernetes_tpu.ops.encode import is_host_only
from kubernetes_tpu.ops.session import SolverSession
from kubernetes_tpu.ops.solver import SolverParams
from kubernetes_tpu.scheduler.core import ScheduleResult
from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.scheduler import Scheduler, commit_target_stale
from kubernetes_tpu.scheduler.types import PodInfo, QueuedPodInfo


class _CommitVolumeBinder:
    """Commit-time PV assignment for batched pods carrying
    node-independent WaitForFirstConsumer claims — the batch path's
    Reserve/PreBind moment (reference ``volume_binding.go`` PreBind →
    BindPodVolumes). Such claims impose no per-node constraint
    (``wfc_class_batchable``), so the solve ignores them and the
    actual PV pops from the class's free pool here, while the store
    lock still serializes against concurrent serial-path binders.
    Lazily snapshots each pool once per commit batch."""

    def __init__(self, client):
        self.client = client
        self._pools: Dict[str, list] = {}
        self.bound = 0

    def _pool(self, sc_name: str) -> list:
        pool = self._pools.get(sc_name)
        if pool is None:
            # node_affinity filter: the drain-time verdict saw an
            # affinity-free pool, but a zonal PV may have become
            # Available since — binding it here would hand a pod a
            # volume its (already chosen) node cannot access
            pool = [
                pv for pv in self.client.list_pvs()
                if pv.phase == "Available" and pv.claim_ref is None
                and pv.storage_class_name == sc_name
                and pv.node_affinity is None
            ]
            # ascending capacity → each claim takes the smallest
            # adequate PV (the reference's smallestPVForClaim ordering)
            def cap_key(pv):
                cap = pv.capacity.get("storage")
                return (cap is None, 0 if cap is None else cap.value())

            pool.sort(key=cap_key)
            self._pools[sc_name] = pool
        return pool

    def finalize(self, pod) -> bool:
        """Bind every still-unbound claim of the pod. False = a pool
        ran dry with no provisioner — the assignment is void and the
        pod must take the serial path for its real status. A partial
        failure unwinds the pod's earlier binds (the serial path's
        Unreserve contract): a pod that ends up pending must not keep
        PVs the next batch needs."""
        done: List[tuple] = []  # (pv name, pvc name) bound for THIS pod
        for v in pod.spec.volumes:
            if not v.persistent_volume_claim:
                continue
            pvc = self.client.get_pvc(pod.namespace,
                                      v.persistent_volume_claim)
            if pvc is None or pvc.volume_name:
                continue      # bound claims were expressible statically
            sc_name = pvc.storage_class_name or ""
            pool = self._pool(sc_name)
            request = pvc.requests.get("storage")
            chosen = None
            for i, pv in enumerate(pool):
                if pvc.access_modes and not \
                        set(pvc.access_modes) <= set(pv.access_modes):
                    continue
                cap = pv.capacity.get("storage")
                if request is not None and (cap is None or cap < request):
                    continue
                chosen = i
                break
            if chosen is not None:
                pv = pool.pop(chosen)
                if not self.client.bind_pv(pv.name, pod.namespace,
                                           pvc.name):
                    self._rollback(pod, done)   # raced away mid-commit
                    return False
                done.append((pv.name, pvc.name))
                self.bound += 1
                continue
            sc = self.client.get_storage_class(sc_name) if sc_name \
                else None
            if sc is None or not sc.provisioner:
                self._rollback(pod, done)
                return False
            # dynamic provisioning satisfies the claim on any node
        return True

    def _rollback(self, pod, done: List[tuple]) -> None:
        for pv_name, pvc_name in done:
            try:
                self.client.unbind_pv(pv_name, pod.namespace, pvc_name)
            except Exception:  # noqa: BLE001 — unwind must not mask
                _logger.exception("PV bind rollback failed: %s", pv_name)
        self.bound -= len(done)


class TPUBatchScheduler:
    # up to this many device-declined pods per batch take the serial
    # path (exact statuses/messages); above it, mass-decline fast path
    DECLINED_SERIAL_LIMIT = 32
    # p99 schedule-latency budget: a pod's latency is roughly one batch
    # cycle (solve + commit), so the drain/pad size adapts to keep each
    # cycle under this (BASELINE.json's p99 target is 2s; budgeting
    # below it leaves headroom for tunnel variance)
    LATENCY_BUDGET_S = 1.5
    MIN_CHUNK = 512

    def __init__(
        self,
        scheduler: Scheduler,
        max_batch: int = 4096,
        params: SolverParams = SolverParams(),
        validate: bool = False,
        backend=None,
        adaptive_chunk: bool = True,
        pipeline: Optional[bool] = None,
    ):
        self.sched = scheduler
        self.max_batch = max_batch
        # streaming pipeline kill-switch: ``KTPU_PIPELINE=off`` (or
        # pipeline=False) runs the serialized barrier loop — drain →
        # encode → solve (eager) → commit in ONE call, nothing carried
        # across cycles. The differential guard
        # (tests/test_pipeline.py) asserts a bit-identical bound set
        # between the two arms over identical seeded event sequences.
        if pipeline is None:
            pipeline = os.environ.get(
                "KTPU_PIPELINE", "").lower() not in ("off", "0", "false")
        self.pipeline_enabled = bool(pipeline)
        # max batches simultaneously in flight across the stages
        # (solve N dispatched + commit N−1 pending + N−2's bulk binds
        # on the binding pool) — the ``pipeline[depth=...]`` diag
        self.pipeline_depth_max = 0
        # False pins the drain/pad size at max_batch (no latency-budget
        # tuning): the multi-chip scaling bench needs every mesh size to
        # solve the IDENTICAL batch partition, or slower configurations
        # shrink their chunks and the comparison measures the tuner, not
        # the sharding
        self.adaptive_chunk = adaptive_chunk
        self.params = params
        # differential-debug mode: re-check every device assignment with
        # the host filter chain before committing
        self.validate = validate
        # device-resident state mirror, carried across batches.
        # ``backend`` overrides the platform default (e.g. the
        # multi-chip ShardedBackend over a device mesh).
        self.session = SolverSession(scheduler, params=params,
                                     max_batch=max_batch, backend=backend)
        # one solved-but-uncommitted batch (pipelining: the host commits
        # batch k while the device solves batch k+1)
        self._pending: Optional[dict] = None
        # latency-budget chunking: drain/pad size, halved (power-of-2
        # buckets — each bucket is one compiled executable) whenever a
        # batch cycle overruns the budget. Wide-term workloads that
        # solve slowly get small low-latency batches; fast ones keep
        # the full pipeline width.
        self._chunk = max_batch
        # pad sizes whose executables are known-compiled. A tuner shrink
        # to an UNWARMED bucket must never compile inside a measured
        # cycle: one slow batch (tunnel stall) would halve the chunk,
        # the new shape's compile would make the NEXT batch slow too,
        # and the cascade lands thousands of pods in 20-50s e2e buckets
        # (VERDICT r4 weak #1, the driver run-1 collapse). Shrinks to
        # unwarmed buckets are pre-warmed with synthetic solves between
        # cycles instead — and the convention is no longer trusted on
        # faith: devprof's compile listener counts any compile that
        # still lands inside a measured cycle
        # (solver_unexpected_compiles_total + flight-recorder dump).
        self._warmed_pads: set = set()
        self._need_warm_pad: Optional[int] = None
        self._warm_samples: List = []
        # XLA compile events MEASURED inside pre-warm solves (devprof
        # listener; legacy builds fall back to one-per-warm) — not the
        # old "assume every warm call compiled" bookkeeping
        self.pad_warms = 0
        self.max_cycle_s = 0.0
        # cache mutations the CURRENT cycle's commits performed
        # (accumulated from commit_assignments_bulk's ledger): the
        # session's validity arithmetic must count every sanctioned
        # mutation — assumes of gang pods parked at Permit included —
        # not just committed pods, or every gang batch reads as drift
        # and rebuilds the session (VERDICT r5 weak #4: state_only
        # rebuild per batch, encode at 6.8x the headline's cost)
        self._cycle_mutations = 0

    # ------------------------------------------------------------------
    def _drain(self, pop_timeout: Optional[float]):
        """Pop up to max_batch pods (bulk, one lock). Each pod's
        scheduling cycle is captured AT POP TIME (serial semantics: the
        moveRequestCycle race rule compares against the cycle the pod was
        popped in, scheduling_queue.go:317) — pop_batch consumes one
        cycle per pod, so cycles are reconstructed from the final value."""
        items, first_cycle = self.sched.queue.pop_batch(
            self._chunk, timeout=pop_timeout
        )
        return [(qpi, first_cycle + i) for i, qpi in enumerate(items)]

    def _tune_chunk(self, padded_pods: int, cycle_seconds: float) -> None:
        """Latency-budget chunk sizing, called after each committed
        batch: per-pod cost × chunk must stay under the p99 budget.
        Cost is divided by the PADDED batch size — device latency scales
        with the compiled scan length, so a sparsely-filled drain must
        not read as slow and collapse the chunk. Movement is one
        power-of-2 bucket per batch in either direction: each bucket is
        its own compiled executable, and a single outlier cycle (e.g.
        one absorbing a compile) must not trigger a cascade of unwarmed
        shapes mid-run."""
        if not self.adaptive_chunk:
            return
        if padded_pods <= 0 or cycle_seconds <= 0:
            return
        per_pod = cycle_seconds / padded_pods
        target = int(0.7 * self.LATENCY_BUDGET_S / max(per_pod, 1e-9))
        if target < self._chunk and self._chunk > self.MIN_CHUNK:
            new = self._chunk // 2
        elif target >= 2 * self._chunk and self._chunk < self.max_batch:
            new = self._chunk * 2
        else:
            return
        # MIN_CHUNK floors the bucket — but never above max_batch
        # (tests and small deployments run with tiny max_batch)
        self._chunk = min(self.max_batch, max(self.MIN_CHUNK, new))
        if self._chunk not in self._warmed_pads:
            # compile between cycles, not inside a measured one
            self._need_warm_pad = self._chunk

    def run_batch(self, pop_timeout: Optional[float] = 0.2) -> int:
        """One pump cycle. Default (``KTPU_PIPELINE`` unset): the
        STREAMING pipeline — drain batch N+1 under a non-blocking
        hint, encode its delta columns and dispatch its solve (jax
        dispatch is async, chaining onto batch N's in-flight state
        carry), then commit batch N−1 while the device crunches, its
        bulk binds flying on the binding pool for remote clients. A
        solved batch is held at most one cycle and commits immediately
        when the queue is empty, so single-shot callers see their pods
        bound in the same call. With ``KTPU_PIPELINE=off``: the
        serialized barrier loop (one batch per call, solve blocks,
        commit follows — the differential guard's reference arm).
        Returns the number of pods worked on this cycle."""
        if not self.pipeline_enabled:
            return self._run_batch_serialized(pop_timeout)
        return self._run_batch_pipelined(pop_timeout)

    # -- shared stages --------------------------------------------------
    def _degraded_pause(self, pop_timeout: Optional[float]) -> None:
        # circuit open: the batch path pauses exactly like the
        # serial loop — solved-but-uncommitted work stays pending
        # and commits on the first cycle after recovery. Always
        # sleep: flush() drives this with pop_timeout=0.0 in a
        # while-_pending loop, which must not become a busy spin.
        time.sleep(min(pop_timeout, 0.05) if pop_timeout else 0.01)

    def _service_warm_pad(self) -> None:
        if self._need_warm_pad is None:
            return
        # session.warm_pad discards its outputs, so the resident
        # state — and any pipelined batch's lazy handle — survive;
        # this runs on the very next cycle after a shrink, even
        # under sustained load where something is always in flight
        pad = self._need_warm_pad
        self._need_warm_pad = None
        if pad not in self._warmed_pads and self._warm_samples:
            warmed = self.session.warm_pad(self._warm_samples, pad)
            if warmed is not None:
                # the bucket is live either way; pad_warms counts
                # the compiles devprof MEASURED (0 = executable was
                # already cached and the warm cost ~nothing)
                self._warmed_pads.add(pad)
                self.pad_warms += warmed

    def _partition(self, qpis: List[tuple]):
        """Batchable vs serial-fallback split (one wfc-class scan per
        drain, not one per pod) — identical in both pipeline arms."""
        sched = self.sched
        batchable: List[tuple] = []
        serial: List[QueuedPodInfo] = []
        host_only_cache: dict = {}
        for qpi, cycle in qpis:
            pod = qpi.pod
            fwk = sched.profiles.get(pod.spec.scheduler_name)
            if fwk is None:
                continue
            if sched.skip_pod_schedule(fwk, pod):
                continue
            if fwk.profile_name != "default-scheduler" or \
                    self._needs_serial(pod, host_only_cache):
                serial.append(qpi)
            else:
                batchable.append((qpi, cycle))
        return batchable, serial

    def _select_pad(self, n_batch: int) -> int:
        """Right-size the pad: a partial drain (creator still
        streaming, queue trickle) pays the device scan of its
        SMALLEST already-compiled pow-2 bucket, not the full
        chunk — device latency scales with the padded size, and
        only warmed buckets are eligible so this never compiles
        inside a measured cycle."""
        pad = self._chunk
        for b in sorted(self._warmed_pads):
            if n_batch <= b < pad:
                return b
        return pad

    # -- the pipelined loop ---------------------------------------------
    def _trace_cycle(self, start: float, processed: int,
                     committed: int) -> None:
        """Batch-level ``queue.cycle`` span covering one drain → solve →
        commit pass. Carries no pod trace, so critical-path attribution
        overlays it at the LOWEST priority: it soaks up the per-pod
        assembly gaps no specific span covers (pop → solve dispatch,
        solve handle → pending stamp, guard re-probes between commit
        chunks) without ever masking encode/solve/commit/bind time.
        Idle passes (nothing drained, nothing committed) stay silent."""
        if processed == 0 and committed == 0:
            return
        try:
            from kubernetes_tpu.observability import get_tracer

            tracer = get_tracer()
            if tracer.enabled:
                tracer.record("queue.cycle", start, time.monotonic(),
                              pods=processed, committed=committed)
        except Exception:   # noqa: BLE001 — tracing must not break cycles
            pass

    def _run_batch_pipelined(self, pop_timeout: Optional[float]) -> int:
        sched = self.sched
        if sched.is_degraded():
            self._degraded_pause(pop_timeout)
            return 0
        t_cycle = time.monotonic()
        prev = self._pending
        self._pending = None
        self._service_warm_pad()

        # a pending batch solved against a mirror that has since
        # diverged (external events, failed commits) is suspect: its
        # assignments are discarded and its pods RE-SOLVED this cycle
        # (the solve below rebuilds from a fresh snapshot), keeping
        # them on the batch path instead of serializing up to
        # max_batch pods. Carried-over pods go back through the SAME
        # partition as freshly drained ones, against the live store
        # object — the divergence that discarded the batch may be the
        # pod itself being deleted or updated (e.g. gaining a PVC)
        # while its batch was in flight.
        if prev is not None and not self.session.mirror_current():
            qpis = []
            for qpi, cycle in prev["batchable"]:
                pod = qpi.pod
                live = sched.client.get_pod(pod.namespace, pod.name)
                if live is None or live.uid != pod.uid:
                    continue  # deleted (and maybe recreated) in flight
                if live is not pod:
                    qpi.pod_info = PodInfo.of(live)
                qpis.append((qpi, cycle))
            prev = None
        else:
            # drain stage, hint-gated: the non-blocking peek decides
            # whether a drain is worth attempting at all — with batch
            # N−1's commit pending and nothing queued, skip the pop
            # (and its condition wait) entirely so stage overlap never
            # parks on an empty queue; with work queued, drain without
            # waiting. Only a fully idle pipeline blocks for
            # pop_timeout (the pump loops' idle-wait contract).
            hint_n, _hint_prio = sched.queue.pending_hint()
            if prev is not None and hint_n == 0:
                qpis = []
            else:
                qpis = self._drain(
                    0.0 if (prev is not None or hint_n) else pop_timeout)
        processed = len(qpis)

        batchable, serial = self._partition(qpis)

        committed = 0
        self._cycle_mutations = 0
        seq_anchor = sched.cache.mutation_seq
        if batchable:
            # pad sized from the PARTITIONED batchable count — the raw
            # hint overstates it whenever serial-fallback pods rode the
            # drain, and an overstated bucket is a larger device scan
            pad = self._select_pad(len(batchable))
            # correlate this batch's solver phase spans with its pods'
            # scheduling cycles (the flight recorder's cycle id)
            self.session.trace_cycle = batchable[0][1]
            try:
                res = self.session.solve(
                    [q.pod for q, _ in batchable], lazy=True,
                    incremental_only=prev is not None,
                    pad_to=pad,
                )
                if res is None:
                    # this solve needs a full rebuild, whose snapshot
                    # must include the in-flight batch: commit it first
                    # and settle the mutation accounting BEFORE the
                    # rebuild re-anchors the mirror (no overlap this
                    # cycle — rebuilds are rare)
                    committed += self._commit_pending_safe(prev, serial)
                    self.session.note_committed(self._cycle_mutations,
                                                seq_anchor)
                    self._cycle_mutations = 0
                    processed += len(prev["batchable"])
                    prev = None
                    seq_anchor = sched.cache.mutation_seq
                    res = self.session.solve(
                        [q.pod for q, _ in batchable], lazy=True,
                        pad_to=pad,
                    )
                handle, cluster, _ = res
                # this pad's executable is live now, and these pods are
                # shape-representative for future pre-warms
                self._warmed_pads.add(pad)
                self._warm_samples = [q.pod for q, _ in batchable[:8]]
                self._pending = {
                    "batchable": batchable,
                    "handle": handle,
                    "materializer": self.session.last_materializer,
                    "cluster": cluster,
                    "profiles": self.session.last_profile_idx,
                    "inexpressible": self.session.last_inexpressible,
                    # static masks for THIS batch's profiles — the
                    # session's live fields may describe a newer batch
                    # by the time this one commits
                    "masks": self.session.static_masks_host,
                    "start": time.monotonic(),
                    "pad": pad,
                }
                # pipeline depth at this instant: solve N in flight,
                # batch N−1 solved-but-uncommitted, batch N−2's bulk
                # binds still on the binding pool
                depth = 1 + (1 if prev is not None else 0) + (
                    1 if getattr(sched, "_inflight_bindings", 0) else 0)
                if depth > self.pipeline_depth_max:
                    self.pipeline_depth_max = depth
            except Exception:  # noqa: BLE001 — popped pods must not be lost
                _logger.exception(
                    "batch solve failed; %d pods fall back to the serial path",
                    len(batchable),
                )
                self.session.invalidate()
                serial.extend(q for q, _ in batchable)

        # commit the previous cycle's batch while the device solves
        # (every guard — stale-node probes, commit_fits, drift
        # re-encode — runs inside _commit_pending, stage-unchanged)
        if prev is not None:
            committed += self._commit_pending_safe(prev, serial)
            processed += len(prev["batchable"])

        # nothing else queued: no overlap to win — commit the fresh
        # solve in the same call (also the single-shot caller contract)
        if self._pending is not None and sched.queue.num_active() == 0:
            pending = self._pending
            self._pending = None
            committed += self._commit_pending_safe(pending, serial)

        self._run_serial(serial)
        # session validity: every cache mutation since the anchor must
        # be one this cycle's commits performed (assumes — including
        # gang pods parked at Permit — plus sync rejection forgets,
        # commit_assignments_bulk's ledger). Serial binds, async-bind
        # failures, or external events show up as extra mutations; with
        # the device mirror attached they land in the delta journal and
        # the next solve scatters them into the resident planes, without
        # it they invalidate the session for a full rebuild.
        self.session.note_committed(self._cycle_mutations, seq_anchor)
        self._trace_cycle(t_cycle, processed, committed)
        return processed

    # -- the serialized (kill-switch) loop ------------------------------
    def _run_batch_serialized(self, pop_timeout: Optional[float]) -> int:
        """The ``KTPU_PIPELINE=off`` barrier loop: drain → encode →
        solve (eager — the materializer blocks inside the solve) →
        commit, one batch per call, nothing carried across cycles.
        Every guard runs exactly as in the pipelined arm (same
        ``_commit_pending``); only the overlap is gone. This is the
        differential guard's reference arm and the operational
        kill-switch if the pipeline ever misbehaves in production."""
        sched = self.sched
        if sched.is_degraded():
            self._degraded_pause(pop_timeout)
            return 0
        t_cycle = time.monotonic()
        self._service_warm_pad()
        qpis = self._drain(pop_timeout)
        processed = len(qpis)
        batchable, serial = self._partition(qpis)
        committed = 0
        self._cycle_mutations = 0
        seq_anchor = sched.cache.mutation_seq
        if batchable:
            pad = self._select_pad(len(batchable))
            self.session.trace_cycle = batchable[0][1]
            start = time.monotonic()
            try:
                res = self.session.solve(
                    [q.pod for q, _ in batchable], lazy=False,
                    pad_to=pad,
                )
                handle, cluster, _ = res
                self._warmed_pads.add(pad)
                self._warm_samples = [q.pod for q, _ in batchable[:8]]
                committed += self._commit_pending_safe({
                    "batchable": batchable,
                    "handle": handle,
                    "materializer": None,   # already materialized
                    "cluster": cluster,
                    "profiles": self.session.last_profile_idx,
                    "inexpressible": self.session.last_inexpressible,
                    "masks": self.session.static_masks_host,
                    "start": start,
                    "pad": pad,
                }, serial)
            except Exception:  # noqa: BLE001 — popped pods must not be lost
                _logger.exception(
                    "batch solve failed; %d pods fall back to the serial path",
                    len(batchable),
                )
                self.session.invalidate()
                serial.extend(q for q, _ in batchable)
        self._run_serial(serial)
        self.session.note_committed(self._cycle_mutations, seq_anchor)
        self._trace_cycle(t_cycle, processed, committed)
        return processed

    def pipeline_info(self, telemetry: Optional[Dict] = None
                      ) -> Optional[Dict]:
        """The ``pipeline[...]`` diag segment's payload: max observed
        stage depth plus (when a devprof summary is supplied) the
        overlap share and how many cycles actually overlapped. None
        when the pipeline is off OR never dispatched a batch (a
        serial-only or empty row) — those rows print nothing, the
        quiet-row convention the other diag segments follow."""
        if not self.pipeline_enabled or self.pipeline_depth_max == 0:
            return None
        info: Dict = {"depth": self.pipeline_depth_max}
        if telemetry:
            info["overlap"] = float(telemetry.get("overlap_share", 0.0))
            info["cycles"] = int(telemetry.get("overlapped_cycles", 0))
        return info

    def mirror_info(self, telemetry: Optional[Dict] = None
                    ) -> Optional[Dict]:
        """The ``mirror[...]`` diag segment's payload: delta-journal
        events scattered into the device-resident planes, the bytes
        those index/value triples cost on the link, how often the
        mirror had to fall back to a full reseed, and (when a devprof
        summary is supplied) the surviving encode share. None when the
        mirror is off (``KTPU_MIRROR=off`` or a backend without scatter
        hooks) — quiet-row convention, same as ``pipeline_info``."""
        mirror = getattr(self.session, "_mirror", None)
        if mirror is None:
            return None
        info = mirror.info()
        if telemetry and "encode_share" in telemetry:
            info["encode_share"] = float(telemetry["encode_share"])
        return info

    def flush(self, timeout: float = 60.0) -> int:
        """Commit any held solved-but-uncommitted batch (the pipelining
        tail): a run that stops pumping mid-stream must not strand popped
        pods in ``_pending``. Returns the number of pods processed.
        Bounded by ``timeout``: in degraded mode the commit is paused,
        and a shutdown-path flush must not wait forever on a server
        that may never come back."""
        total = 0
        deadline = time.monotonic() + timeout
        while self._pending is not None and time.monotonic() < deadline:
            total += self.run_batch(pop_timeout=0.0)
        return total

    def _run_serial(self, serial: List[QueuedPodInfo]) -> None:
        sched = self.sched
        seen = set()
        for qpi in serial:
            if qpi.pod.full_name() in seen:
                continue  # appended both pre- and post-solve-failure
            seen.add(qpi.pod.full_name())
            fwk = sched.profiles[qpi.pod.spec.scheduler_name]
            # a partial batch commit may already have assumed some of these
            if sched.skip_pod_schedule(fwk, qpi.pod):
                continue
            sched.schedule_pod_serial(fwk, qpi)

    def warmup(self, sample_pods: Optional[List] = None) -> float:
        """Compile (or cache-load) the solver for this cluster's shapes by
        solving a representative batch. Returns seconds spent. Call after
        nodes exist and before the measured phase — the analog of the
        reference excluding informer warm-up from scheduler_perf's
        measured window.

        The compiled XLA signature depends on the batch's constraint and
        resource dims (spread constraints, affinity terms, topology value
        space, extended resources), so pass ``sample_pods`` drawn from the
        actual workload (e.g. one pod per template); constraints are
        deduped during encoding, so one representative pod per template
        yields the same shapes as the full batch. Without samples, only
        the constraint-free shape is warmed."""
        t0 = time.monotonic()
        sched = self.sched
        try:
            sched.algorithm.update_snapshot()
            if not sched.algorithm.snapshot.list():
                return 0.0
            pods = list(sample_pods) if sample_pods else []
            if not pods:
                from kubernetes_tpu.api.resource import parse_quantity
                from kubernetes_tpu.api.types import (
                    Container, ObjectMeta, Pod, PodSpec, ResourceRequirements,
                )

                pods = [Pod(
                    metadata=ObjectMeta(name="__warmup__", namespace="default"),
                    spec=PodSpec(containers=[Container(
                        name="c",
                        resources=ResourceRequirements(
                            requests={"cpu": parse_quantity("1m")}),
                    )]),
                )]
            # drive the session itself so the ACTIVE backend (pallas
            # kernel or xla scan) compiles for the exact steady-state
            # shapes; then invalidate — warmup pods were solved into the
            # device mirror but never committed on the host
            self.session.solve(pods, warming=True)
            # timed second solve (now cache-hot) estimates the per-pod
            # device rate so the latency-budget chunk is chosen — and
            # its executable compiled — BEFORE the measured phase
            t1 = time.monotonic()
            self.session.solve(pods, warming=True)
            est = time.monotonic() - t1
            # cost scales with the padded size; step until the bucket is
            # stable (runtime tuning moves one bucket per batch, but
            # warmup is free to settle immediately)
            per_pod = est / self.max_batch
            self._warmed_pads.add(self.max_batch)
            self._warm_samples = list(pods)
            prev = None
            while prev != self._chunk:
                prev = self._chunk
                self._tune_chunk(self._chunk, per_pod * self._chunk)
            self._need_warm_pad = None   # warmed HERE, not mid-run
            if self._chunk != self.max_batch:
                self.session.solve(pods, warming=True, pad_to=self._chunk)
                self._warmed_pads.add(self._chunk)
            # one shrink bucket below the settled chunk compiles for
            # free inside the un-measured warmup window, so the tuner's
            # FIRST mid-run shrink (a tunnel stall reacting) never waits
            # on a compile at all
            half = max(self.MIN_CHUNK, self._chunk // 2)
            if half < self._chunk:
                self.session.solve(pods, warming=True, pad_to=half)
                self._warmed_pads.add(half)
            self.session.invalidate()
        except Exception:
            _logger.exception("solver warmup failed (continuing cold)")
        return time.monotonic() - t0

    def mesh_info(self) -> Optional[Dict]:
        """Sharded-solve topology of the session's ACTIVE backend, or
        None off the mesh tier: mesh width, node-axis shard count, and
        whether the solve donates its state buffers. Feeds the bench
        ``diag:`` line's ``mesh[...]`` segment (harness/diagfmt.py) and
        the devscale row's per-arm provenance."""
        be = self.session._active
        mesh = getattr(be, "mesh", None)
        if mesh is None:
            return None
        try:
            shards = int(dict(mesh.shape).get("nodes", mesh.size))
        except Exception:  # noqa: BLE001 — diagnostics only
            shards = int(getattr(mesh, "size", 1))
        return {
            "devices": int(mesh.size),
            "shards": shards,
            "donated": bool(getattr(be, "donate", False)),
        }

    def _needs_serial(self, pod, cache=None) -> bool:
        if is_host_only(pod, self.sched.client, cache):
            return True
        return any(
            ext.is_interested(pod) for ext in self.sched.algorithm.extenders
        )

    def _commit_pending_safe(self, pending: dict,
                             serial: List[QueuedPodInfo]) -> int:
        """_commit_pending, but a failure (e.g. an async device error
        surfacing at materialization) must not lose popped pods: they
        fall back to the serial path (already-assumed ones are skipped
        there by skip_pod_schedule)."""
        try:
            return self._commit_pending(pending, serial)
        except Exception:  # noqa: BLE001
            _logger.exception(
                "batch commit failed; %d pods fall back to the serial path",
                len(pending["batchable"]),
            )
            self.session.invalidate()
            serial.extend(q for q, _ in pending["batchable"])
            return 0

    # ------------------------------------------------------------------
    def _commit_pending(self, pending: dict,
                        serial: List[QueuedPodInfo]) -> int:
        """Materialize and commit one solved batch. Returns the number
        of successfully committed pods; declined/rejected pods are
        appended to ``serial`` or failed directly (mass decline)."""
        sched = self.sched
        fwk = sched.profiles["default-scheduler"]
        batchable = pending["batchable"]
        cluster = pending["cluster"]
        start = pending["start"]
        mat = pending["materializer"] or (lambda h: h)
        assignments = mat(pending["handle"])

        t0 = time.monotonic()
        committed = 0
        declined: List[tuple] = []  # (batch index, qpi, cycle)
        commits: List[tuple] = []   # (qpi, result, cycle, start)
        vol_binder = _CommitVolumeBinder(sched.client)
        # stale-node guard (chaos_nodes): ONE cache probe for every
        # distinct target in this batch. The solve ran against an
        # encoding that may predate node churn; assignments whose node
        # has since died / been cordoned / gone unreachable route to
        # the serial path for a fresh verdict, and the session is told
        # the node planes drifted so the next solve re-encodes instead
        # of spinning mass declines against ghost columns.
        stale_flags = sched.cache.commit_target_flags(
            {cluster.node_names[int(a)] for a in assignments if a >= 0}
        )
        # multi-replica capacity guard (replicas sharing all nodes):
        # ONE cumulative cache probe for the whole batch — targets
        # whose remaining capacity a sibling replica consumed since
        # this solve route to the serial path, which re-places them
        # against the post-conflict cache instead of burning a backoff
        # round on a bind the guard would refuse anyway.
        cap_verdicts = None
        if sched.commit_capacity_guard:
            cap_verdicts = sched.cache.commit_fits([
                (qpi.pod,
                 cluster.node_names[int(a)] if a >= 0 else "")
                for (qpi, _), a in zip(batchable, assignments)
            ])
        stale_routed = 0
        capacity_routed = 0
        for bi, ((qpi, cycle), assignment) in enumerate(
            zip(batchable, assignments)
        ):
            if assignment < 0:
                declined.append((bi, qpi, cycle))
                continue
            node_name = cluster.node_names[assignment]
            flag = stale_flags.get(node_name, False)
            if flag is not False and \
                    commit_target_stale(qpi.pod, flag) is not None:
                stale_routed += 1
                serial.append(qpi)
                continue
            if cap_verdicts is not None and cap_verdicts[bi] is not None:
                capacity_routed += 1
                serial.append(qpi)
                continue
            if self.validate and not self._host_validates(fwk, qpi, node_name):
                # the device state counts this pod but the host refused it
                self.session.invalidate()
                serial.append(qpi)
                continue
            if not vol_binder.finalize(qpi.pod):
                # batched WFC claim whose pool ran dry with no
                # provisioner: the device's assignment is void — the
                # serial path will produce the proper unschedulable
                # status (and the mirror no longer matches)
                self.session.invalidate()
                serial.append(qpi)
                continue
            result = ScheduleResult(
                suggested_host=node_name,
                evaluated_nodes=cluster.num_real_nodes,
                feasible_nodes=1,
            )
            commits.append((qpi, result, cycle, start))
        if stale_routed:
            from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

            fabric_metrics().stale_binds_rejected_total.inc(
                "batch", amount=stale_routed)
            # the device counted these pods onto nodes that are gone:
            # static planes drifted, force a full re-encode
            self.session.note_drift()
        if capacity_routed:
            from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

            fabric_metrics().stale_binds_rejected_total.inc(
                "capacity", amount=capacity_routed)
            # sibling commits drifted the state planes (not the node
            # set): the mirror no longer matches the cluster
            self.session.invalidate()
        if commits:
            committed, failed = sched.commit_assignments_bulk(fwk, commits)
            self._cycle_mutations += sched.last_bulk_commit_mutations
            if failed:
                # committed on device, rejected on host: mirrors diverged
                self.session.invalidate()
        # Declined pods: with a FEW, re-run the serial path for its exact
        # per-plugin statuses and event messages. Under MASS decline
        # (e.g. thousands of impossible pods) the serial re-run costs
        # ~O(nodes) per pod for information the device already computed,
        # so fail directly with statuses synthesized from the static
        # masks — preemption still runs via PostFilter and correctly
        # prunes static-infeasible nodes.
        if len(declined) <= self.DECLINED_SERIAL_LIMIT:
            serial.extend(qpi for _, qpi, _ in declined)
        else:
            # statuses depend only on the pod's static profile: share one
            # (read-only) map per profile instead of building a
            # nodes-sized dict per declined pod
            statuses_by_profile: dict = {}
            inexpressible = pending["inexpressible"]
            # ONE vectorized preemption screen for the whole declined
            # set: each pod gets ranked candidate hints so its PostFilter
            # dry-runs a handful of nodes instead of the sampled ~10%
            # (per-pod dry-run over hundreds of candidates is what
            # collapses mass-preemption throughput)
            screen = None
            planner = None
            screen_masks: dict = {}
            if fwk.has_post_filter_plugins() and any(
                q.pod.priority() > 0 for _, q, _ in declined
            ):
                from kubernetes_tpu.scheduler.preemption_screen import (
                    build_screen,
                    build_victim_planner,
                )

                sched.algorithm.update_snapshot()
                try:
                    screen = build_screen(sched.algorithm.snapshot)
                    planner = build_victim_planner(
                        sched.algorithm.snapshot,
                        pdbs=sched.client.list_pdbs(),
                    )
                except Exception:  # noqa: BLE001 — hints are advisory
                    _logger.exception("preemption screen build failed")

            def screen_mask(bi: int):
                """This batch's static mask for pod ``bi``, re-ordered to
                the screen's node order (encoder vs snapshot order can
                differ); cached per profile."""
                profiles, masks = pending["profiles"], pending["masks"]
                if profiles is None or masks is None or \
                        bi >= len(profiles):
                    return None
                ui = int(profiles[bi])
                if ui in screen_masks:
                    return screen_masks[ui]
                if ui >= len(masks):
                    screen_masks[ui] = None
                    return None
                by_name = dict(zip(cluster.node_names, masks[ui]))
                import numpy as _np

                aligned = _np.array(
                    [bool(by_name.get(nm, False))
                     for nm in screen.node_names], dtype=bool,
                )
                screen_masks[ui] = aligned
                return aligned
            # batch preemption planning (VERDICT r2 #3): group the
            # declined preemptors by shape — mass declines are runs of
            # identical (priority, requests, static profile) pods — and
            # let the planner propose ONE (node, minimal victim set)
            # per pod from its per-(node, priority) sorted prefix sums
            # with live capacity accounting. Planned pods skip the
            # per-pod PostFilter dry-run entirely; the real filter
            # chain still validates every plan post-eviction.
            from kubernetes_tpu.scheduler.framework.plugins.default_preemption import (  # noqa: E501
                pod_eligible_to_preempt_others,
            )
            from kubernetes_tpu.scheduler.types import (
                compute_pod_resource_request,
            )

            groups: dict = {}   # shape key -> [(bi, qpi, cycle)]
            rest: List[tuple] = []
            for bi, qpi, cycle in declined:
                # an inexpressible pod's -1 is NOT a device verdict (the
                # tensor model simply can't express it) — it keeps the
                # documented serial-fallback contract even here
                if inexpressible is not None and bi < len(inexpressible) \
                        and inexpressible[bi]:
                    serial.append(qpi)
                    continue
                if planner is not None and qpi.pod.priority() > 0 and \
                        pod_eligible_to_preempt_others(
                            qpi.pod, sched.algorithm.snapshot):
                    req = compute_pod_resource_request(qpi.pod)
                    profiles = pending["profiles"]
                    ui = int(profiles[bi]) if profiles is not None and \
                        bi < len(profiles) else -1
                    key = (qpi.pod.priority(), req.milli_cpu,
                           req.memory, ui)
                    groups.setdefault(key, []).append((bi, qpi, cycle))
                else:
                    rest.append((bi, qpi, cycle))
            plans: List[tuple] = []  # (qpi, cycle, node_name, victims)
            for key, members in groups.items():
                got = []
                try:
                    got = planner.plan_group(
                        members[0][1].pod, len(members),
                        static_mask=screen_mask(members[0][0]),
                    )
                except Exception:  # noqa: BLE001 — advisory
                    _logger.exception("victim planning failed")
                for (bi, qpi, cycle), (node_name, victims) in zip(
                        members, got):
                    plans.append((qpi, cycle, node_name, victims))
                rest.extend(members[len(got):])
            # mass decline writes one PodScheduled=False condition per
            # pod: coalesce the whole sweep into one bulk /statuses
            # request (rate-equivalent — the bulk verb charges per
            # item) instead of thousands of serialized PUT round trips
            with sched.client.batched_status_writes():
                for bi, qpi, cycle in rest:
                    hints = None
                    if screen is not None and qpi.pod.priority() > 0:
                        # rotate by position in the declined set:
                        # uniform batches spread over distinct
                        # candidate nodes
                        hints = screen.candidates_for(
                            qpi.pod, static_mask=screen_mask(bi),
                            rotation=bi,
                        )
                    if not self._fail_declined(fwk, qpi, cycle, cluster,
                                               bi, pending["profiles"],
                                               pending["masks"],
                                               statuses_by_profile,
                                               candidate_hints=hints):
                        serial.append(qpi)
            if plans:
                committed += self._execute_preemption_plans(
                    fwk, plans, pending["start"], serial
                )
        now = time.monotonic()
        sched.metrics.batch_solve_duration.observe(now - t0, "commit")
        try:
            from kubernetes_tpu.observability import get_tracer

            tracer = get_tracer()
            if tracer.enabled:
                tracer.record("solve.commit", t0, now,
                              pods=len(batchable), committed=committed,
                              cycle=batchable[0][1] if batchable else -1,
                              pad=pending.get("pad", self.max_batch))
        except Exception:   # noqa: BLE001 — tracing must not break commits
            pass
        self.max_cycle_s = max(self.max_cycle_s, now - start)
        self._tune_chunk(pending.get("pad", self.max_batch), now - start)
        return committed

    def _execute_preemption_plans(self, fwk, plans, start,
                                  serial: List[QueuedPodInfo]) -> int:
        """Execute a batch of (preemptor, node, victims) plans: evict
        all victims in bulk, refresh the snapshot once, then validate
        each preemptor on its planned node with the REAL filter chain
        (against clones carrying the batch's earlier placements — the
        assume semantics without touching the cache) and commit the
        validated set in bulk. A failed validation routes that pod to
        the serial path; its victims are already gone, which the serial
        PostFilter treats as ordinary freed capacity.

        Semantics vs the reference: victims get the same Preempted
        events and waiting-pod rejection (``default_preemption.go:698``
        PrepareCandidate); the preemptor binds in THIS cycle instead of
        being requeued with ``nominatedNodeName`` — outcome-equivalent
        (PreferNominatedNode would pick the same node next cycle,
        ``generic_scheduler.go:250``) minus one full solve round trip,
        which is what makes mass preemption fast."""
        sched = self.sched
        recorder = getattr(fwk, "event_recorder", None)
        doomed: List[tuple] = []
        for qpi, _cycle, node_name, victims in plans:
            for victim in victims:
                if fwk.reject_waiting_pod(victim.uid):
                    continue
                doomed.append((victim.namespace, victim.name))
                if recorder is not None:
                    recorder.event(
                        victim, "Normal", "Preempted",
                        f"Preempted by {qpi.pod.namespace}/"
                        f"{qpi.pod.metadata.name} on node {node_name}",
                    )
        if doomed:
            sched.client.delete_pods(doomed)
        sched.algorithm.update_snapshot()
        snapshot = sched.algorithm.snapshot
        clones: dict = {}
        commits: List[tuple] = []
        from kubernetes_tpu.scheduler.framework import interface as fw_iface

        for qpi, cycle, node_name, _victims in plans:
            ni = clones.get(node_name)
            if ni is None:
                base = snapshot.get(node_name)
                if base is None or base.node is None:
                    serial.append(qpi)
                    continue
                ni = base.clone()
                clones[node_name] = ni
            state = CycleState()
            status = fwk.run_pre_filter_plugins(state, qpi.pod)
            ok = fw_iface.Status.is_ok(status)
            if ok:
                ok = fw_iface.Status.is_ok(
                    fwk.run_filter_plugins_with_nominated_pods(
                        state, qpi.pod, ni
                    )
                )
            if not ok:
                # resource model said yes, full filters said no
                # (topology/affinity effect): exact fallback
                serial.append(qpi)
                continue
            ni.add_pod(qpi.pod)
            result = ScheduleResult(
                suggested_host=node_name,
                evaluated_nodes=len(snapshot.list()),
                feasible_nodes=1,
            )
            commits.append((qpi, result, cycle, start))
        committed = 0
        if commits:
            committed, failed = sched.commit_assignments_bulk(fwk, commits)
            self._cycle_mutations += sched.last_bulk_commit_mutations
            if failed:
                self.session.invalidate()
        # stale-nomination cleanup (default_preemption.go:277-282 via
        # _prepare_candidate): lower-priority pods nominated on a node a
        # batch preemptor just took must lose the nomination, or their
        # phantom reservation keeps filtering other pods off the node
        nominator = getattr(fwk, "pod_nominator", None)
        if nominator is not None:
            max_prio_by_node: dict = {}
            for qpi, _cycle, node_name, _victims in plans:
                prio = qpi.pod.priority()
                cur = max_prio_by_node.get(node_name)
                if cur is None or prio > cur:
                    max_prio_by_node[node_name] = prio
            for node_name, prio in max_prio_by_node.items():
                for pi in list(
                    nominator.nominated_pods_for_node(node_name)
                ):
                    if pi.pod.priority() < prio:
                        nominator.delete_nominated_pod_if_exists(pi.pod)
                        sched.client.clear_nominated_node_name(
                            pi.pod.namespace, pi.pod.name
                        )
        # victim deletions mutated the cache outside the commit
        # accounting: the mirror rebuilds next batch regardless
        return committed

    # shared (read-only) status instances for synthesized fit errors
    _STATUS_STATIC = None
    _STATUS_DYNAMIC = None

    def _fail_declined(self, fwk, qpi: QueuedPodInfo, cycle: int,
                       cluster, batch_index: int, profiles, masks,
                       statuses_by_profile: dict,
                       candidate_hints=None) -> bool:
        """Mark a device-declined pod unschedulable without the serial
        re-run. Returns False when the static context is unavailable
        (caller then uses the serial path). ``profiles`` is the solved
        batch's per-pod profile index array, captured at solve time (the
        session's live fields may already describe a NEWER batch)."""
        from kubernetes_tpu.scheduler.framework import interface as fw_iface

        if profiles is None or batch_index >= len(profiles):
            return False
        ui = int(profiles[batch_index])
        cached = statuses_by_profile.get(ui)
        if cached is None:
            if masks is None or ui >= len(masks):
                return False
            mask = masks[ui][: cluster.num_real_nodes]
            cls = TPUBatchScheduler
            if cls._STATUS_STATIC is None:
                cls._STATUS_STATIC = fw_iface.Status(
                    fw_iface.UNSCHEDULABLE_AND_UNRESOLVABLE,
                    "node(s) didn't satisfy the pod's node-static predicates",
                )
                cls._STATUS_DYNAMIC = fw_iface.Status(
                    fw_iface.UNSCHEDULABLE,
                    "node(s) had insufficient resources or violated "
                    "topology/affinity constraints",
                )
            statuses = {
                name: (cls._STATUS_DYNAMIC if ok else cls._STATUS_STATIC)
                for name, ok in zip(cluster.node_names, mask)
            }
            # the failure message and "preemption could never help" are
            # profile-wide facts: compute them once, not per pod
            # (message aggregation is O(nodes); the PostFilter's
            # candidate prefilter is another O(nodes) scan)
            probe = fw_iface.FitError(
                num_all_nodes=cluster.num_real_nodes,
                filtered_nodes_statuses=statuses,
            )
            cached = (statuses, str(probe), not bool(mask.any()))
            statuses_by_profile[ui] = cached
        statuses, message, all_static = cached
        fit_err = fw_iface.FitError(
            pod=qpi.pod,
            num_all_nodes=cluster.num_real_nodes,
            filtered_nodes_statuses=statuses,
            message=message,
        )
        self.sched.fail_unschedulable(
            fwk, qpi, fit_err, cycle, candidate_hints=candidate_hints,
            # every node failed a NODE-STATIC predicate: preemption can
            # never help (nodesWherePreemptionMightHelp would be empty),
            # so skip the per-pod PostFilter scan entirely
            run_post_filter=not all_static,
        )
        return True

    def _host_validates(self, fwk, qpi: QueuedPodInfo, node_name: str) -> bool:
        from kubernetes_tpu.scheduler.framework import interface as fw_iface

        # the session only refreshes the snapshot on rebuild; validation
        # must see the live cache INCLUDING this batch's earlier commits
        # (incremental update: O(changed nodes) per call)
        self.sched.algorithm.update_snapshot()
        state = CycleState()
        status = fwk.run_pre_filter_plugins(state, qpi.pod)
        if not fw_iface.Status.is_ok(status):
            return False
        ni = self.sched.algorithm.snapshot.get(node_name)
        if ni is None:
            return False
        return fw_iface.Status.is_ok(
            fwk.run_filter_plugins_with_nominated_pods(state, qpi.pod, ni)
        )


def attach_batch_scheduler(
    sched: Scheduler,
    max_batch: int = 4096,
    params: SolverParams = SolverParams(),
    validate: bool = False,
    backend=None,
    adaptive_chunk: bool = True,
    pipeline: Optional[bool] = None,
) -> Optional[TPUBatchScheduler]:
    """Install the batch path iff the TPUBatchScheduler gate is enabled
    (the --feature-gates=TPUBatchScheduler wiring). ``pipeline``
    overrides the ``KTPU_PIPELINE`` kill-switch (None = read the env;
    False = the serialized barrier loop)."""
    if not sched.feature_gates.enabled("TPUBatchScheduler"):
        return None
    bs = TPUBatchScheduler(sched, max_batch=max_batch, params=params,
                           validate=validate, backend=backend,
                           adaptive_chunk=adaptive_chunk,
                           pipeline=pipeline)
    sched.batch_scheduler = bs
    try:
        # the schedule-latency SLO reads the e2e histogram from THIS
        # scheduler's registry — point the SLO engine at it so every
        # batch-path consumer (bench, chaos, qos harnesses) gets live
        # evaluation without per-harness wiring
        from kubernetes_tpu.observability.slo import get_slo_engine

        get_slo_engine().add_registry(sched.metrics.registry)
    except Exception:  # noqa: BLE001 — SLO wiring must never block attach
        pass
    return bs
