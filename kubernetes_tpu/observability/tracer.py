"""Lock-cheap span recorder + bounded flight recorder with Perfetto export.

Design constraints (the headline bench schedules ~10k pods/s through the
hot paths this module instruments):

- **Recording is allocation-light and lock-free.** A finished span is one
  tuple appended to a ``collections.deque(maxlen=...)`` — append is
  GIL-atomic, so the hot paths never contend on a tracer lock. The only
  lock taken per span is the phase histogram's (one ``Histogram.observe``),
  and per-pod spans are head-sampled so steady-state volume is low.
- **Head-based sampling is deterministic.** A pod is in or out of the
  sampled set by ``crc32(seed:uid)`` — every component (REST ingest,
  queue, commit) makes the same decision for the same pod with no shared
  state, which is what stitches a sampled pod's causal trace across
  components. Cycle-level spans (one encode/device/commit span per batch
  cycle) are always recorded; they are the latency-breakdown backbone and
  cost a few spans per second.
- **The flight recorder is bounded twice**: by event count (the deque's
  ``maxlen``) and by time (dumps keep only the trailing ``retain_s``
  window), so it survives crashes with a predictable memory ceiling and
  a postmortem-relevant payload.

Span times are monotonic; the dump carries the wall-clock anchor so
offline tooling can reconstruct absolute times. Export is Chrome
``trace_event`` JSON (the ``{"traceEvents": [...]}`` shape), which loads
directly in https://ui.perfetto.dev and ``chrome://tracing``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

# Fleet trace propagation (PR 17): every REST request may carry this
# header so a pod sampled at the ingesting client is sampled in every
# process it touches. Format: ``<trace>;<parent_span_id>;<0|1>`` —
# trace id (pod uid where one exists), the sender's span id (kept as a
# span ATTRIBUTE by the receiver, since span-id counters are
# per-process and collide across the fleet), and the explicit sampling
# decision (crc32 head sampling re-derived per-process agrees for pod
# uids, but bulk verbs and control-plane calls need the bit).
TRACE_HEADER = "X-Ktpu-Trace"


class TraceContext(NamedTuple):
    """A parsed ``X-Ktpu-Trace`` header: the wire form of one hop of a
    fleet trace."""

    trace: str
    parent: int
    sampled: bool

    def header_value(self) -> str:
        return format_trace_header(self.trace, self.parent, self.sampled)


def format_trace_header(trace: str, parent: int = 0,
                        sampled: bool = True) -> str:
    """Serialize a trace context for the ``X-Ktpu-Trace`` header.
    Semicolons in the trace id would corrupt the frame; uids never
    contain them, but defend anyway."""
    return (f"{str(trace).replace(';', '_')};{int(parent)};"
            f"{1 if sampled else 0}")


def parse_trace_header(value: Optional[str]) -> Optional[TraceContext]:
    """Parse an ``X-Ktpu-Trace`` header value; returns None on any
    malformed input (propagation is best-effort by contract — a bad
    header must never fail the request that carried it)."""
    if not value:
        return None
    try:
        trace, parent, sampled = value.split(";", 2)
        if not trace or sampled.strip() not in ("0", "1"):
            return None
        return TraceContext(trace, int(parent), sampled.strip() == "1")
    except (ValueError, AttributeError):
        return None


# Thread-local inbound request context: rest.py sets it for the
# duration of a request handler so commit-time machinery deeper in the
# stack (store watch dispatch stamping origin context onto events) can
# read the propagated context without threading a parameter through
# every store verb. Request handlers run one request per thread, so a
# plain thread-local is exact.
_request_ctx = threading.local()


def set_request_context(ctx: Optional[TraceContext]) -> None:
    _request_ctx.ctx = ctx


def current_request_context() -> Optional[TraceContext]:
    return getattr(_request_ctx, "ctx", None)

# record layout (tuples, not objects: ~3x cheaper to build and they
# never need mutation once finished)
# (name, ph, t_end_mono, dur_s, trace, span_id, parent_id, tid, attrs)
_PH_SPAN = "X"
_PH_INSTANT = "i"

DEFAULT_MAX_EVENTS = 65536
DEFAULT_RETAIN_S = 60.0
DEFAULT_SAMPLE_RATE = 1.0 / 64.0

_SAMPLE_DENOM = float(1 << 32)


class Span:
    """An in-flight span handle (finished spans live as tuples in the
    ring). Use via ``Tracer.span(...)`` as a context manager."""

    __slots__ = ("tracer", "name", "trace", "attrs", "span_id",
                 "parent_id", "t0")

    def __init__(self, tracer: "Tracer", name: str, trace: str,
                 attrs: Optional[dict], span_id: int, parent_id: int):
        self.tracer = tracer
        self.name = name
        self.trace = trace
        self.attrs = attrs
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = time.monotonic()

    def __enter__(self) -> "Span":
        self.tracer._push(self)
        return self

    def __exit__(self, *exc) -> bool:
        self.tracer._pop_and_record(self)
        return False

    def set(self, **attrs) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)


class Tracer:
    def __init__(
        self,
        component: str = "scheduler",
        sample_rate: Optional[float] = None,
        seed: int = 0,
        max_events: int = DEFAULT_MAX_EVENTS,
        retain_s: float = DEFAULT_RETAIN_S,
        registry=None,
        enabled: bool = True,
        dump_dir: Optional[str] = None,
    ):
        self.component = component
        self.enabled = enabled
        if sample_rate is None:
            sample_rate = _env_sample_rate()
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)
        self._seed_prefix = f"{self.seed}:".encode()
        self._sample_cut = int(self.sample_rate * _SAMPLE_DENOM)
        self.retain_s = float(retain_s)
        self.max_events = int(max_events)
        self._ring: deque = deque(maxlen=self.max_events)
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._tids: Dict[int, str] = {}
        self._epoch_mono = time.monotonic()
        self._epoch_wall = time.time()
        self._dump_dir = dump_dir or os.environ.get("KTPU_TRACE_DUMP_DIR")
        self._dump_seq = itertools.count(1)
        self._dump_lock = threading.Lock()
        self._last_dump_mono: Dict[str, float] = {}
        self._last_dump_paths: Dict[str, str] = {}
        self.last_dump_path: Optional[str] = None
        self._crash_armed = False
        self._phase_hist = _phase_histogram(registry)

    # -- sampling ------------------------------------------------------
    def sampled(self, uid: str, inbound: Optional[bool] = None) -> bool:
        """Deterministic head-based sampling decision for a trace id
        (pod uid): every component agrees on the same pods without
        shared state, so sampled traces are complete end-to-end. Runs
        once or twice per scheduled pod on the hot paths — one crc32
        over a short byte string, no allocation beyond the encode.

        ``inbound`` is an explicit decision propagated on the wire
        (``X-Ktpu-Trace``); when present it WINS over local crc32
        re-derivation both ways — a pod sampled at the ingesting
        client stays sampled in every process it touches even if
        seeds/rates disagree, and an unsampled one stays out. A
        disabled tracer still records nothing."""
        if not self.enabled:
            return False
        if inbound is not None:
            return bool(inbound)
        rate = self.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        return (zlib.crc32(self._seed_prefix + uid.encode())
                & 0xFFFFFFFF) < self._sample_cut

    # -- recording -----------------------------------------------------
    def span(self, name: str, trace: str = "", **attrs) -> Span:
        """Open a nested span (context manager). Parent is the innermost
        open span on this thread."""
        parent = 0
        stack = getattr(self._local, "stack", None)
        if stack:
            top = stack[-1]
            parent = top.span_id
            if not trace:
                trace = top.trace
        return Span(self, name, trace, attrs or None,
                    next(self._ids), parent)

    def _push(self, span: Span) -> None:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(span)

    def _pop_and_record(self, span: Span) -> None:
        stack = getattr(self._local, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()
        elif stack and span in stack:      # out-of-order exit
            stack.remove(span)
        if not self.enabled:
            return
        end = time.monotonic()
        self._append(span.name, _PH_SPAN, end, end - span.t0, span.trace,
                     span.span_id, span.parent_id, span.attrs)

    def record(self, name: str, start_mono: float,
               end_mono: Optional[float] = None, trace: str = "",
               parent_id: int = 0, **attrs) -> None:
        """Record a completed span from explicit monotonic timestamps —
        the cross-component path (e.g. a queue-wait span whose start was
        stamped at enqueue time by a different thread)."""
        if not self.enabled:
            return
        if end_mono is None:
            end_mono = time.monotonic()
        self._append(name, _PH_SPAN, end_mono, end_mono - start_mono,
                     trace, next(self._ids), parent_id, attrs or None)

    def event(self, name: str, trace: str = "",
              at_mono: Optional[float] = None, parent_id: int = 0,
              **attrs) -> None:
        """Record an instant event (a point in time, no duration).
        ``at_mono`` back-dates the event to an already-captured
        monotonic timestamp (e.g. a Trace step stamped earlier)."""
        if not self.enabled:
            return
        self._append(name, _PH_INSTANT,
                     time.monotonic() if at_mono is None else at_mono,
                     0.0, trace, next(self._ids), parent_id,
                     attrs or None)

    def current_span_id(self) -> int:
        """Span id of the innermost open span on this thread (0 when
        none) — what an outgoing request stamps as the wire parent."""
        stack = getattr(self._local, "stack", None)
        return stack[-1].span_id if stack else 0

    def annotate_current(self, **attrs) -> bool:
        """Attach attributes to the innermost open span on this thread
        (e.g. the per-object uid list of a bulk request — ONE attribute
        on one span, not N headers). False when no span is open."""
        stack = getattr(self._local, "stack", None)
        if not stack:
            return False
        stack[-1].set(**attrs)
        return True

    def _append(self, name: str, ph: str, end: float, dur: float,
                trace: str, span_id: int, parent_id: int,
                attrs: Optional[dict]) -> None:
        tid = threading.get_ident()
        if tid not in self._tids:
            self._tids[tid] = threading.current_thread().name
        # deque.append with maxlen is GIL-atomic: no tracer lock on the
        # hot path, eviction of the oldest record is free
        self._ring.append(
            (name, ph, end, dur, trace, span_id, parent_id, tid, attrs))
        if ph == _PH_SPAN and self._phase_hist is not None:
            try:
                self._phase_hist.observe(dur, name)
            except Exception:   # pragma: no cover — must never break paths
                pass

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)

    # -- derived stats (the bench's diag source) -----------------------
    def phase_stats(self, window_s: Optional[float] = None
                    ) -> Dict[str, Dict[str, float]]:
        """Per-phase {count, total_s, p50_s, p99_s} computed from the
        ring's spans (EXACT percentiles, unlike the bucket-interpolated
        /metrics histogram) — the bench ``diag:`` line and
        ``tools/trace_report`` read latency breakdowns from here instead
        of hand-rolled counters. ``window_s`` bounds the lookback;
        default: everything still in the ring."""
        cut = None if window_s is None else time.monotonic() - window_s
        durs: Dict[str, List[float]] = {}
        for rec in list(self._ring):
            name, ph, end, dur = rec[0], rec[1], rec[2], rec[3]
            if ph != _PH_SPAN or (cut is not None and end < cut):
                continue
            durs.setdefault(name, []).append(dur)
        out: Dict[str, Dict[str, float]] = {}
        for name, vals in durs.items():
            vals.sort()
            n = len(vals)
            out[name] = {
                "count": n,
                "total_s": sum(vals),
                "p50_s": vals[n // 2] if n else 0.0,
                "p99_s": vals[min(n - 1, int(n * 0.99))] if n else 0.0,
            }
        return out

    # -- export --------------------------------------------------------
    def export_perfetto(self, window_s: Optional[float] = None) -> dict:
        """Chrome/Perfetto ``trace_event`` JSON for the trailing
        ``window_s`` (default: the recorder's retention window). Loads
        in https://ui.perfetto.dev as-is."""
        now = time.monotonic()
        cut = now - (self.retain_s if window_s is None else window_s)
        pid = os.getpid()
        events: List[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": self.component},
        }]
        tids_seen = set()
        for rec in list(self._ring):
            name, ph, end, dur, trace, span_id, parent_id, tid, attrs = rec
            if end < cut:
                continue
            ts_us = (end - dur - self._epoch_mono) * 1e6
            ev: Dict[str, Any] = {
                "name": name, "ph": ph, "ts": ts_us,
                "pid": pid, "tid": tid,
                "args": {"trace": trace, "id": span_id,
                         "parent": parent_id},
            }
            if attrs:
                ev["args"].update(attrs)
            if ph == _PH_SPAN:
                ev["dur"] = dur * 1e6
            else:
                ev["s"] = "t"
            events.append(ev)
            tids_seen.add(tid)
        for tid in tids_seen:
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "ts": 0, "args": {"name": self._tids.get(tid, str(tid))},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "component": self.component,
                "epoch_wall": self._epoch_wall,
                "epoch_mono": self._epoch_mono,
                "sample_rate": self.sample_rate,
                "seed": self.seed,
            },
        }

    def dump(self, path: Optional[str] = None, reason: str = "manual",
             window_s: Optional[float] = None,
             min_interval_s: float = 0.0) -> Optional[str]:
        """Write a flight-recorder dump to disk; returns the path (None
        on failure — dumping is best-effort by contract: it runs from
        degraded-mode entry and crash handlers). ``min_interval_s``
        rate-limits per reason AND reuses one stable filename for that
        reason: a chaos run flapping in and out of degraded mode must
        not serialize the ring on every flap nor fill the dump dir."""
        # non-blocking: a concurrent dump already has the postmortem in
        # hand, and the SIGTERM handler runs on the main thread — if the
        # signal lands while this thread is mid-dump, a blocking acquire
        # of a lock the same thread holds would hang shutdown forever
        if not self._dump_lock.acquire(blocking=False):
            return self.last_dump_path
        try:
            stable = min_interval_s > 0.0
            now = time.monotonic()
            if stable:
                last = self._last_dump_mono.get(reason)
                if last is not None and now - last < min_interval_s:
                    return self._last_dump_paths.get(reason)
            if path is None:
                base = self._dump_dir or os.environ.get("TMPDIR", "/tmp")
                os.makedirs(base, exist_ok=True)
                # rate-limited auto-dumps reuse ONE file per reason: a
                # flapping trigger overwrites the last postmortem
                # instead of growing the dump dir without bound
                suffix = "" if stable else f"-{next(self._dump_seq)}"
                path = os.path.join(
                    base,
                    f"schedtrace-{self.component}-{os.getpid()}-"
                    f"{reason}{suffix}.json")
            doc = self.export_perfetto(window_s)
            doc["otherData"]["reason"] = reason
            with open(path, "w") as f:
                json.dump(doc, f)
            # rate-limit state only advances on SUCCESS: a failed
            # best-effort write must not suppress the retry window
            self._last_dump_mono[reason] = now
            self._last_dump_paths[reason] = path
            self.last_dump_path = path
            return path
        except Exception:   # noqa: BLE001 — best-effort by contract
            return None
        finally:
            self._dump_lock.release()

    # -- crash dumps (atexit + SIGTERM, best-effort) -------------------
    def arm_crash_dump(self, dump_dir: Optional[str] = None) -> None:
        """Dump the flight recorder on interpreter exit and on SIGTERM
        (best-effort: SIGKILL is uncatchable by definition; the chaos
        ring's WAL restore covers that case). Idempotent."""
        if dump_dir:
            self._dump_dir = dump_dir
        if self._crash_armed:
            return
        self._crash_armed = True
        import atexit

        def _on_exit() -> None:
            if self.enabled and len(self._ring):
                self.dump(reason="exit")

        atexit.register(_on_exit)
        try:
            import signal

            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                if self.enabled and len(self._ring):
                    self.dump(reason="sigterm")
                if callable(prev):
                    prev(signum, frame)
                elif prev is signal.SIG_IGN:
                    # the process deliberately ignored SIGTERM; arming
                    # tracing must not change that into an exit
                    return
                else:
                    raise SystemExit(143)

            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            # not the main thread / embedded interpreter: atexit alone
            pass

    # -- runtime reconfiguration (tests, bench A/B) --------------------
    def configure(self, enabled: Optional[bool] = None,
                  sample_rate: Optional[float] = None,
                  seed: Optional[int] = None,
                  retain_s: Optional[float] = None) -> None:
        if enabled is not None:
            self.enabled = enabled
        if sample_rate is not None:
            self.sample_rate = float(sample_rate)
            self._sample_cut = int(self.sample_rate * _SAMPLE_DENOM)
        if seed is not None:
            self.seed = int(seed)
            self._seed_prefix = f"{self.seed}:".encode()
        if retain_s is not None:
            self.retain_s = float(retain_s)


def _env_sample_rate() -> float:
    """KTPU_TRACE_SAMPLE: a probability ("0.1") or a denominator
    ("64" = 1-in-64). Invalid values fall back to the default."""
    raw = os.environ.get("KTPU_TRACE_SAMPLE", "")
    if not raw:
        return DEFAULT_SAMPLE_RATE
    try:
        v = float(raw)
    except ValueError:
        return DEFAULT_SAMPLE_RATE
    if v > 1.0:
        return 1.0 / v
    return max(0.0, v)


def _phase_histogram(registry=None):
    """``schedtrace_phase_duration_seconds{phase=...}`` in the process
    registry — reused if already registered (multiple Tracer instances
    in one process share series, the fabric_metrics pattern)."""
    try:
        from kubernetes_tpu.metrics import default_registry
        from kubernetes_tpu.metrics.registry import Histogram

        reg = registry if registry is not None else default_registry()
        existing = reg.get("schedtrace_phase_duration_seconds")
        if isinstance(existing, Histogram):
            return existing
        return reg.register(Histogram(
            "schedtrace_phase_duration_seconds",
            "Span-derived latency breakdown per scheduling phase "
            "(REST ingest, queue wait, encode, device solve, commit, "
            "bind), recorded by the flight-recorder tracer",
            ("phase",),
        ))
    except Exception:   # pragma: no cover — tracing must not break startup
        return None


_default: Optional[Tracer] = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    """Process-wide tracer (the legacyregistry pattern). Disabled
    entirely with KTPU_TRACE=off."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                t = Tracer(
                    enabled=os.environ.get("KTPU_TRACE", "") != "off")
                if os.environ.get("KTPU_TRACE_DUMP_DIR"):
                    t.arm_crash_dump()
                _default = t
    return _default


def set_tracer(tracer: Tracer) -> Tracer:
    global _default
    _default = tracer
    return tracer
