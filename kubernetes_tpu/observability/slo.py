"""Live SLO evaluation with multi-window burn-rate alerting.

The metric this project is judged on — p99 schedule latency at 5k
nodes / 30k pods — existed only as a post-hoc bench number until this
module. Here it (and the freshness SLIs PR 8 added) becomes a LIVE
objective, evaluated continuously the way an SRE would run it
(the Google SRE workbook's multi-window, multi-burn-rate alerts):

- an **SLO** is an objective over an SLI expressed as a good-event
  ratio: "99% of pods schedule in ≤ 1s", "99% of watch events deliver
  in ≤ 500ms", "99.9% of requests are not 429/503-rejected". Latency
  SLOs count histogram observations above the threshold bucket as bad;
  error-ratio SLOs read bad/total counter pairs.
- the engine samples the backing series on a fixed tick and evaluates
  every SLO over a rolling **fast** and **slow** window. The
  **burn rate** is bad_fraction ÷ allowed_fraction: burn 1.0 spends
  the error budget exactly at sustainable speed; the alert fires only
  when BOTH windows burn hot (fast ≥ 14.4 × budget AND slow ≥ 6 ×,
  the classic 5m/1h page) — a blip can't page, a sustained breach
  can't hide. Windows scale to bench timescales via ``reset``.
- on a burn-rate breach the engine fires the PR 2 flight recorder
  (``tracer.dump(reason="slo-<name>")``, rate-limited, stable
  filename) so the postmortem is on disk before anyone asks, and
  mirrors every verdict into gauges (``slo_burn_rate{slo,window}``,
  ``slo_violated{slo}``, ``slo_alerts_total{slo}``) so ``/metrics``
  and ``/debug/slo`` can never disagree.

``/debug/slo`` (apiserver/rest.py, ADMIN_ROUTES exemption envelope)
serves ``evaluate()`` for the live process; ``tools/slo_report.py``
renders the human table from that endpoint or from a committed bench
artifact's ``freshness`` sub-objects.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Google SRE workbook multi-window page thresholds (5m/1h), reused at
# whatever window pair the engine is configured with
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 6.0
DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0


@dataclass
class SLODef:
    """One objective over one SLI.

    ``kind="latency"``: ``metric`` names a histogram; good events are
    observations ≤ ``threshold_s`` (evaluated at the first bucket edge
    ≥ the threshold, so pick thresholds on bucket edges). ``labels``
    selects one series; None aggregates every series of the metric.

    ``kind="error_ratio"``: ``metric`` names the BAD-event counter,
    ``total_metric`` the good-event counter; total = good + bad.
    """

    name: str
    description: str
    metric: str
    kind: str = "latency"               # "latency" | "error_ratio"
    threshold_s: float = 1.0
    objective: float = 0.99             # required good-event fraction
    labels: Optional[Tuple[str, ...]] = None
    total_metric: str = ""


def default_slos() -> List[SLODef]:
    """The cluster's standing objectives. Thresholds sit on bucket
    edges of their backing histograms."""
    return [
        SLODef(
            name="schedule_latency",
            description="99% of pods schedule (e2e, algorithm+binding) "
                        "within 1s",
            metric="scheduler_e2e_scheduling_duration_seconds",
            labels=("scheduled",),
            threshold_s=1.0, objective=0.99,
        ),
        SLODef(
            name="watch_delivery",
            description="99% of watch events reach client decode "
                        "within 500ms of store commit",
            metric="watch_delivery_seconds",
            threshold_s=0.5, objective=0.99,
        ),
        SLODef(
            name="snapshot_staleness",
            description="99% of solve cycles run against a snapshot "
                        "no older than 2s",
            metric="snapshot_staleness_seconds",
            threshold_s=2.0, objective=0.99,
        ),
        SLODef(
            name="rest_availability",
            description="99.9% of admitted API requests are not "
                        "rejected with 429/503 by flow control",
            metric="apf_rejected_requests_total",
            kind="error_ratio",
            total_metric="apf_dispatched_requests_total",
            objective=0.999,
        ),
    ]


@dataclass
class _Sample:
    t: float
    bad: float
    total: float
    # latency SLOs also carry the aggregated bucket vector + edges so
    # windowed quantiles come from bucket DELTAS, not lifetime counts
    counts: Optional[List[int]] = None
    edges: Optional[Tuple[float, ...]] = None


@dataclass
class _SLOState:
    slo: SLODef
    samples: List[_Sample] = field(default_factory=list)
    alerting: bool = False


def _quantile_from_counts(counts: List[int], edges: Tuple[float, ...],
                          q: float) -> float:
    """Bucket-interpolated quantile over a windowed delta vector — the
    shared ``registry.quantile_from_counts`` math."""
    from kubernetes_tpu.metrics.registry import quantile_from_counts

    return quantile_from_counts(counts, edges, q)


class SLOEngine:
    """Samples SLI series on a tick, evaluates rolling-window burn
    rates, alerts on the multi-window condition. One per process via
    ``get_slo_engine()``; harnesses ``reset()`` it per bench row with
    the row scheduler's registry attached and bench-scaled windows."""

    def __init__(
        self,
        slos: Optional[List[SLODef]] = None,
        registries: Optional[list] = None,
        fast_window_s: float = DEFAULT_FAST_WINDOW_S,
        slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
        fast_burn: float = DEFAULT_FAST_BURN,
        slow_burn: float = DEFAULT_SLOW_BURN,
        enabled: Optional[bool] = None,
        clock=time.monotonic,
    ):
        if enabled is None:
            enabled = os.environ.get("KTPU_SLO", "") != "off"
        self.enabled = enabled
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self._clock = clock
        self._lock = threading.Lock()
        self._extra_registries: list = list(registries or [])
        self._states: Dict[str, _SLOState] = {}
        for slo in (slos if slos is not None else default_slos()):
            self._states[slo.name] = _SLOState(slo)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._gauges = None

    # -- wiring --------------------------------------------------------
    def _registries(self) -> list:
        from kubernetes_tpu.metrics import default_registry

        return [default_registry()] + list(self._extra_registries)

    def add_registry(self, registry) -> None:
        """Attach another registry to search for SLI series (e.g. a
        Scheduler's own — the e2e latency histogram lives there).
        Newest attach wins: ``_find_metric`` returns the FIRST match,
        and a process that runs schedulers sequentially (chaos/elastic
        harnesses attach one per scenario without a reset between)
        must read the live scheduler's series, not a dead
        predecessor's frozen histogram."""
        with self._lock:
            if registry in self._extra_registries:
                self._extra_registries.remove(registry)
            self._extra_registries.insert(0, registry)

    def reset(self, extra_registries: Optional[list] = None,
              fast_window_s: Optional[float] = None,
              slow_window_s: Optional[float] = None,
              slos: Optional[List[SLODef]] = None) -> None:
        """Fresh evaluation window (per bench row): drops every sample
        and alert latch, replaces the attached registries, optionally
        rescales the windows to bench timescales or swaps the SLO set."""
        with self._lock:
            if extra_registries is not None:
                self._extra_registries = list(extra_registries)
            if fast_window_s is not None:
                self.fast_window_s = float(fast_window_s)
            if slow_window_s is not None:
                self.slow_window_s = float(slow_window_s)
            if slos is not None:
                self._states = {s.name: _SLOState(s) for s in slos}
            else:
                for st in self._states.values():
                    st.samples = []
                    st.alerting = False

    def configure(self, enabled: Optional[bool] = None) -> None:
        if enabled is not None:
            self.enabled = enabled

    # -- sampling ------------------------------------------------------
    def _find_metric(self, name: str):
        for reg in self._registries():
            m = reg.get(name)
            if m is not None:
                return m
        return None

    def _snapshot(self, slo: SLODef) -> Optional[_Sample]:
        from kubernetes_tpu.metrics.registry import Histogram

        now = self._clock()
        if slo.kind == "error_ratio":
            bad_m = self._find_metric(slo.metric)
            total_m = self._find_metric(slo.total_metric)
            bad = sum(v for _n, _k, v in bad_m.collect()) \
                if bad_m is not None else 0.0
            good = sum(v for _n, _k, v in total_m.collect()) \
                if total_m is not None else 0.0
            return _Sample(now, bad, bad + good)
        m = self._find_metric(slo.metric)
        if not isinstance(m, Histogram):
            return _Sample(now, 0.0, 0.0)
        edges = tuple(float(b) for b in m.buckets)
        agg = [0] * (len(edges) + 1)
        for labels, counts, _sum, _count in m.collect_full():
            if slo.labels is not None and tuple(labels) != slo.labels:
                continue
            for i, c in enumerate(counts):
                agg[i] += c
        total = sum(agg)
        # good = observations in buckets whose upper edge ≤ threshold
        good = 0
        for i, edge in enumerate(edges):
            if edge <= slo.threshold_s:
                good += agg[i]
            else:
                break
        return _Sample(now, float(total - good), float(total),
                       counts=agg, edges=edges)

    def tick(self, now: Optional[float] = None) -> None:
        """Sample every SLO's backing series. Cheap (a few collect()s);
        driven by the background thread or called directly by tests
        with an injected clock."""
        if not self.enabled:
            return
        with self._lock:
            states = list(self._states.values())
        for st in states:
            sample = self._snapshot(st.slo)
            if sample is None:
                continue
            if now is not None:
                sample.t = now
            with self._lock:
                st.samples.append(sample)
                # prune beyond the slow window (keep one anchor before
                # the window edge so deltas always have a base)
                cut = sample.t - self.slow_window_s
                keep = 0
                for i, s in enumerate(st.samples):
                    if s.t >= cut:
                        keep = max(0, i - 1)
                        break
                else:
                    keep = max(0, len(st.samples) - 2)
                if keep:
                    st.samples = st.samples[keep:]

    # -- evaluation ----------------------------------------------------
    def _window_delta(self, st: _SLOState, window_s: float,
                      now: float):
        """(Δbad, Δtotal, Δcounts) between now and the newest sample at
        or before the window start (earliest available as fallback)."""
        samples = st.samples
        if not samples:
            return 0.0, 0.0, None
        end = samples[-1]
        start = samples[0]
        cut = now - window_s
        for s in samples:
            if s.t <= cut:
                start = s
            else:
                break
        d_bad = max(0.0, end.bad - start.bad)
        d_total = max(0.0, end.total - start.total)
        d_counts = None
        if end.counts is not None and start.counts is not None \
                and len(end.counts) == len(start.counts):
            d_counts = [max(0, e - s) for e, s in
                        zip(end.counts, start.counts)]
        elif end.counts is not None:
            d_counts = list(end.counts)
        return d_bad, d_total, d_counts

    def evaluate(self, now: Optional[float] = None,
                 tick: bool = True) -> dict:
        """Evaluate every SLO over the fast and slow windows. Fires
        flight-recorder dumps on NEW multi-window burn alerts and
        mirrors verdicts into the slo_* metrics. The returned dict is
        the /debug/slo body."""
        if not self.enabled:
            return {"enabled": False, "slos": {}}
        if tick:
            self.tick(now=now)
        if now is None:
            now = self._clock()
        out: Dict[str, dict] = {}
        healthy = True
        for st in list(self._states.values()):
            slo = st.slo
            allowed = max(1e-9, 1.0 - slo.objective)
            bad_f, total_f, counts_f = self._window_delta(
                st, self.fast_window_s, now)
            bad_s, total_s, _ = self._window_delta(
                st, self.slow_window_s, now)
            frac_f = bad_f / total_f if total_f > 0 else 0.0
            frac_s = bad_s / total_s if total_s > 0 else 0.0
            burn_f = frac_f / allowed
            burn_s = frac_s / allowed
            violated = total_f > 0 and burn_f >= 1.0
            alerting = (total_f > 0 and burn_f >= self.fast_burn
                        and burn_s >= self.slow_burn)
            status = {
                "description": slo.description,
                "kind": slo.kind,
                "objective": slo.objective,
                "window_fast_s": self.fast_window_s,
                "window_slow_s": self.slow_window_s,
                "events_fast": total_f,
                "bad_fast": bad_f,
                "burn_fast": round(burn_f, 3),
                "burn_slow": round(burn_s, 3),
                "violated": violated,
                "alerting": alerting,
                # budget left in the slow window at the current spend
                "budget_remaining_pct": round(
                    max(0.0, 1.0 - frac_s / allowed) * 100.0, 2),
            }
            if slo.kind == "latency":
                status["threshold_s"] = slo.threshold_s
                if counts_f and st.samples and \
                        st.samples[-1].edges is not None:
                    status["sli_fast_p99_s"] = round(
                        _quantile_from_counts(
                            counts_f, st.samples[-1].edges, 0.99), 4)
            healthy = healthy and not violated
            # read-modify the alert latch under the lock: the tick
            # thread and a concurrent /debug/slo evaluation must not
            # both observe "not yet alerting" and double-fire the
            # breach counter + dump
            with self._lock:
                newly_alerting = alerting and not st.alerting
                st.alerting = alerting
            out[slo.name] = status
            self._mirror(slo.name, status)
            if newly_alerting:
                self._on_breach(slo.name, status)
        return {"enabled": True, "healthy": healthy, "slos": out}

    # -- side effects --------------------------------------------------
    def _metrics(self):
        if self._gauges is None:
            from kubernetes_tpu.metrics import default_registry
            from kubernetes_tpu.metrics.fabric_metrics import (
                _counter,
                _gauge,
            )

            reg = default_registry()
            self._gauges = {
                "burn": _gauge(
                    reg, "slo_burn_rate",
                    "Error-budget burn rate per SLO and window (1.0 = "
                    "budget spent exactly at sustainable speed)",
                    ("slo", "window")),
                "violated": _gauge(
                    reg, "slo_violated",
                    "1 while the SLO's fast-window SLI breaches its "
                    "objective", ("slo",)),
                "alerts": _counter(
                    reg, "slo_alerts_total",
                    "Multi-window burn-rate alerts fired, per SLO",
                    ("slo",)),
            }
        return self._gauges

    def _mirror(self, name: str, status: dict) -> None:
        try:
            g = self._metrics()
            g["burn"].set(status["burn_fast"], name, "fast")
            g["burn"].set(status["burn_slow"], name, "slow")
            g["violated"].set(1.0 if status["violated"] else 0.0, name)
        except Exception:  # noqa: BLE001 — mirroring must never break
            pass

    def _on_breach(self, name: str, status: dict) -> None:
        """A burn-rate alert just latched: counter + flight-recorder
        dump (PR 2 machinery — stable filename + rate limit, exactly
        the degraded-mode dump contract)."""
        try:
            self._metrics()["alerts"].inc(name)
        except Exception:  # noqa: BLE001
            pass
        try:
            from kubernetes_tpu.observability import get_tracer

            tracer = get_tracer()
            if tracer.enabled:
                tracer.event("slo.burn_alert", slo=name,
                             burn_fast=status["burn_fast"],
                             burn_slow=status["burn_slow"])
                tracer.dump(reason=f"slo-{name}", min_interval_s=5.0)
        except Exception:  # noqa: BLE001 — dumping is best-effort
            pass

    # -- background drive ---------------------------------------------
    def start(self, interval_s: float = 1.0) -> None:
        """Begin ticking on a daemon thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def _run() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 — the loop must survive
                    pass

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="slo-engine")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


_default: Optional[SLOEngine] = None
_default_lock = threading.Lock()


def get_slo_engine() -> SLOEngine:
    """Process-wide SLO engine (the legacyregistry pattern). Disabled
    entirely with KTPU_SLO=off."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = SLOEngine()
    return _default


def set_slo_engine(engine: SLOEngine) -> SLOEngine:
    global _default
    _default = engine
    return engine
