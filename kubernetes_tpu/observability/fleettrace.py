"""Fleet trace federation + cross-process critical-path attribution.

The bench/chaos harnesses spawn real child processes (partitioned
apiservers, scheduler replicas, a reshard coordinator) and each keeps
its own flight-recorder ring (``observability/tracer.py``) — until
this module a pod's causal story died at every REST hop.  This is the
tracing sibling of ``metrics/federation.py``:

- ``TraceFederation.scrape`` pulls each process's ``/debug/trace``
  Perfetto dump.  The scrape request carries an ``echo_mono`` query
  parameter (this process's ``time.monotonic()`` at send); the server
  echoes it next to its own ``server_mono`` stamped at export, so the
  federation estimates the per-connection clock offset as
  ``server_mono - (t0 + rtt/2)`` — the classic half-RTT echo.  The
  correction is *bounded*: the true offset lies within ±rtt/2 of the
  estimate, and that bound is recorded as ``skew_ms`` on every
  imported span (the merged timeline is honest about how far two
  processes' spans may really be apart).
- ``merged()`` renders ONE Chrome/Perfetto document with a track per
  process (``pid`` = import order, ``process_name`` = instance), all
  timestamps skew-corrected onto the federation's own monotonic
  timeline and shifted so the earliest span starts at 0.
- ``critical_path()`` is a pure analysis pass over the merged
  document: it walks each sampled pod's stitched span set
  (rest.ingest → rest.{verb} → queue.wait → encode → solve → commit →
  bind, across partition/replica/seam boundaries) plus the batch-level
  cycle spans and ``seam:<epoch>`` freeze/roll spans that overlap the
  pod's in-flight window, and emits a per-pod critical path and a
  per-phase fleet aggregate — the ``critical_path`` sub-object every
  bench row carries (phase shares, ``unattributed_share``,
  ``max_skew_ms``).

Everything here is best-effort by the same contract as metrics
federation: a dying child must not fail the bench row, so scrape
failures land in ``scrape_errors`` and the analysis runs on whatever
was imported.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional, Tuple

from kubernetes_tpu.observability.tracer import Tracer

SEAM_PREFIX = "seam:"

# Phase classification for the critical-path sweep.  When two spans
# overlap the same instant of a pod's in-flight window, the LATER
# pipeline phase wins (a pod inside solve.commit is committing even if
# its queue.wait span — closed late by a different thread — still
# covers that instant).  Seam spans (reshard freeze, upgrade roll)
# rank above nothing but unattributed time: they explain a stall only
# where no scheduling phase already does.
PHASE_PRIORITY = ("bind", "commit", "solve", "encode", "queue",
                  "rest", "watch", "seam")
_PRIO = {p: i for i, p in enumerate(PHASE_PRIORITY)}


def phase_of(name: str) -> Optional[str]:
    """Span name → pipeline phase (None = not a pipeline span)."""
    if name.startswith("sched.bind") or name.startswith("bind"):
        return "bind"
    if name == "solve.commit":
        return "commit"
    if name in ("solve.encode", "solve.pack"):
        return "encode"
    if name.startswith("solve"):
        return "solve"
    if name.startswith("queue"):
        return "queue"
    if name.startswith("rest") or name.startswith("route"):
        return "rest"
    if name.startswith("watch"):
        return "watch"
    if (name.startswith("reshard") or name.startswith("upgrade")
            or name.startswith("fed") or name.startswith("seam")):
        return "seam"
    return None


class TraceFederation:
    """Scrapes per-process ``/debug/trace`` dumps and maintains the
    skew-corrected merged fleet timeline (see module docstring)."""

    def __init__(self):
        # instance -> list of normalized records; a record is
        # {name, ph, t0 (abs local-monotonic, corrected), dur_s,
        #  trace, id, parent, tid, attrs, skew_ms}
        self._spans: Dict[str, List[dict]] = {}
        self._threads: Dict[str, Dict[int, str]] = {}
        self._offsets: Dict[str, float] = {}
        self._skew_ms: Dict[str, float] = {}
        self._meta: Dict[str, dict] = {}
        self.scrape_errors: List[str] = []

    # -- ingestion -----------------------------------------------------
    def scrape(self, url: str, instance: str, token: str = "",
               timeout: float = 10.0,
               window_s: Optional[float] = None) -> bool:
        """HTTP GET a component's ``/debug/trace`` and absorb it with
        half-RTT clock-offset correction. ``url`` is the server base
        (``http://host:port``). Best-effort: failures land in
        ``scrape_errors`` and return False."""
        import http.client
        import json as _json

        rest = url.split("://", 1)[-1]
        hostport = rest.split("/", 1)[0]
        host, _, port = hostport.partition(":")
        t0 = time.monotonic()
        path = f"/debug/trace?echo_mono={t0!r}"
        if window_s is not None:
            path += f"&window={float(window_s)!r}"
        try:
            conn = http.client.HTTPConnection(
                host, int(port or 80), timeout=timeout)
            try:
                headers = {"Authorization": f"Bearer {token}"} \
                    if token else {}
                conn.request("GET", path, headers=headers)
                resp = conn.getresponse()
                body = resp.read()
                t1 = time.monotonic()
                if resp.status != 200:
                    raise RuntimeError(f"HTTP {resp.status} from {url}")
                doc = _json.loads(body)
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 — scraping is best-effort
            self.scrape_errors.append(f"{instance} {url}: {e}")
            return False
        other = doc.get("otherData", {})
        server_mono = other.get("server_mono")
        rtt = max(0.0, t1 - t0)
        if server_mono is None:
            # pre-PR-17 server: no echo — import uncorrected with an
            # honest worst-case skew bound of the full RTT
            offset, skew_ms = 0.0, rtt * 1000.0
        else:
            # the server stamped server_mono somewhere inside [t0, t1];
            # midpoint estimate, true offset within ±rtt/2
            offset = float(server_mono) - (t0 + rtt / 2.0)
            skew_ms = (rtt / 2.0) * 1000.0
        self.absorb_doc(doc, instance, offset=offset, skew_ms=skew_ms)
        return True

    def absorb_local(self, tracer: Tracer, instance: str,
                     window_s: Optional[float] = None) -> None:
        """Mirror a LOCAL tracer into the federation (the parent
        process is a component too) — zero offset, zero skew: its
        monotonic clock IS the federation's reference timeline."""
        self.absorb_doc(tracer.export_perfetto(window_s), instance,
                        offset=0.0, skew_ms=0.0)

    def absorb_doc(self, doc: dict, instance: str, offset: float = 0.0,
                   skew_ms: float = 0.0) -> None:
        """Normalize one process's Perfetto dump onto the federation
        timeline: event ``ts`` is relative to the source's
        ``epoch_mono``; corrected absolute time = ts + epoch_mono −
        offset. The skew bound is recorded on every imported span."""
        other = doc.get("otherData", {})
        epoch_mono = float(other.get("epoch_mono", 0.0))
        self._offsets[instance] = offset
        self._skew_ms[instance] = skew_ms
        self._meta[instance] = {
            "component": other.get("component", instance),
            "epoch_wall": other.get("epoch_wall"),
            "sample_rate": other.get("sample_rate"),
            "seed": other.get("seed"),
        }
        spans = self._spans[instance] = []
        threads = self._threads.setdefault(instance, {})
        for ev in doc.get("traceEvents", []):
            ph = ev.get("ph")
            if ph == "M":
                if ev.get("name") == "thread_name":
                    threads[ev.get("tid", 0)] = \
                        ev.get("args", {}).get("name", "")
                continue
            if ph not in ("X", "i"):
                continue
            args = dict(ev.get("args") or {})
            t0 = ev.get("ts", 0.0) / 1e6 + epoch_mono - offset
            spans.append({
                "name": ev.get("name", ""), "ph": ph, "t0": t0,
                "dur_s": ev.get("dur", 0.0) / 1e6,
                "trace": args.pop("trace", ""),
                "id": args.pop("id", 0),
                "parent": args.pop("parent", 0),
                "tid": ev.get("tid", 0),
                "attrs": args or None,
                "skew_ms": skew_ms,
            })

    def forget_instance(self, instance: str) -> None:
        for table in (self._spans, self._threads, self._offsets,
                      self._skew_ms, self._meta):
            table.pop(instance, None)

    def clear(self) -> None:
        for table in (self._spans, self._threads, self._offsets,
                      self._skew_ms, self._meta):
            table.clear()
        self.scrape_errors = []

    def instances(self) -> List[str]:
        return list(self._spans)

    # -- export --------------------------------------------------------
    def merged(self) -> dict:
        """One fleet Perfetto document: a track per process (pid =
        import order), skew-corrected timestamps shifted so the
        earliest record starts at 0, ``instance`` + ``skew_ms`` on
        every span."""
        base = None
        for spans in self._spans.values():
            for rec in spans:
                if base is None or rec["t0"] < base:
                    base = rec["t0"]
        base = base or 0.0
        events: List[dict] = []
        for pid, (instance, spans) in enumerate(
                sorted(self._spans.items()), start=1):
            events.append({
                "ph": "M", "name": "process_name", "pid": pid,
                "tid": 0, "ts": 0,
                "args": {"name": instance},
            })
            for tid, tname in self._threads.get(instance, {}).items():
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "ts": 0, "args": {"name": tname},
                })
            for rec in spans:
                ev = {
                    "name": rec["name"], "ph": rec["ph"],
                    "ts": (rec["t0"] - base) * 1e6,
                    "pid": pid, "tid": rec["tid"],
                    "args": {"trace": rec["trace"], "id": rec["id"],
                             "parent": rec["parent"],
                             "instance": instance,
                             "skew_ms": round(rec["skew_ms"], 3)},
                }
                if rec["attrs"]:
                    ev["args"].update(rec["attrs"])
                if rec["ph"] == "X":
                    ev["dur"] = rec["dur_s"] * 1e6
                else:
                    ev["s"] = "t"
                events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "fleet": True,
                "instances": {
                    inst: {
                        "offset_s": round(self._offsets.get(inst, 0.0),
                                          6),
                        "skew_ms": round(self._skew_ms.get(inst, 0.0),
                                         3),
                        **self._meta.get(inst, {}),
                    }
                    for inst in self._spans
                },
                "scrape_errors": list(self.scrape_errors),
            },
        }


# -- critical-path attribution (pure analysis) -------------------------

def _sweep(intervals: List[Tuple[float, float, str]],
           lo: float, hi: float) -> Tuple[Dict[str, float], float]:
    """Priority interval sweep over [lo, hi]: for every elementary
    segment, the highest-priority covering phase owns it. Returns
    ({phase: seconds}, attributed seconds)."""
    points = {lo, hi}
    for s, e, _p in intervals:
        if e > lo and s < hi:
            points.add(max(s, lo))
            points.add(min(e, hi))
    cuts = sorted(points)
    shares: Dict[str, float] = {}
    attributed = 0.0
    for a, b in zip(cuts, cuts[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        best = None
        for s, e, p in intervals:
            if s <= mid < e and (best is None
                                 or _PRIO[p] < _PRIO[best]):
                best = p
        if best is not None:
            shares[best] = shares.get(best, 0.0) + (b - a)
            attributed += b - a
    return shares, attributed


def critical_path(doc: dict, skew_bound_ms: float = 50.0,
                  max_pods: int = 0) -> dict:
    """Walk each sampled pod's stitched span set in a merged fleet
    document and attribute its arrival→bind window to pipeline phases.

    Per pod: the window is [earliest own record, latest own record];
    candidate intervals are the pod's own spans PLUS batch-level cycle
    spans (encode/solve/commit/bind.bulk plus the covering
    ``queue.cycle`` drain→commit span, none of which carry a pod
    trace) and ``seam:<epoch>`` spans overlapping the window; the
    priority
    sweep (later pipeline phase wins) yields per-phase seconds and the
    unattributed remainder.

    Returns the fleet aggregate the bench row carries: phase shares
    over the summed pod windows, ``top``/``top_share``,
    ``unattributed_share``, ``max_skew_ms``, ``fully_attributed``
    (fraction of pods with own unattributed_share ≤ 0.05), and per-pod
    paths (bounded by ``max_pods``; 0 = all)."""
    by_pod: Dict[str, List[dict]] = {}
    cycle: List[Tuple[float, float, str]] = []
    seams: List[Tuple[float, float, str]] = []
    max_skew = 0.0
    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        if ph not in ("X", "i"):
            continue
        args = ev.get("args") or {}
        max_skew = max(max_skew, float(args.get("skew_ms", 0.0)))
        trace = args.get("trace", "") or ""
        t0 = ev.get("ts", 0.0) / 1e6
        t1 = t0 + ev.get("dur", 0.0) / 1e6
        if trace.startswith(SEAM_PREFIX):
            if ph == "X":
                seams.append((t0, t1, "seam"))
            continue
        if trace:
            by_pod.setdefault(trace, []).append(
                {"name": ev.get("name", ""), "ph": ph,
                 "t0": t0, "t1": t1,
                 "instance": args.get("instance", "")})
        elif ph == "X":
            p = phase_of(ev.get("name", ""))
            if p in ("encode", "solve", "commit", "bind", "queue"):
                cycle.append((t0, t1, p))
    pods: List[dict] = []
    agg: Dict[str, float] = {}
    total_window = 0.0
    total_attr = 0.0
    fully = 0
    for uid, recs in sorted(by_pod.items()):
        lo = min(r["t0"] for r in recs)
        hi = max(r["t1"] for r in recs)
        if hi <= lo:
            continue
        intervals: List[Tuple[float, float, str]] = []
        for r in recs:
            if r["ph"] != "X":
                continue
            p = phase_of(r["name"])
            if p is not None and r["t1"] > r["t0"]:
                intervals.append((r["t0"], r["t1"], p))
        intervals.extend(i for i in cycle if i[1] > lo and i[0] < hi)
        intervals.extend(i for i in seams if i[1] > lo and i[0] < hi)
        shares, attributed = _sweep(intervals, lo, hi)
        window = hi - lo
        unatt = max(0.0, 1.0 - attributed / window)
        if unatt <= 0.05:
            fully += 1
        total_window += window
        total_attr += attributed
        for p, s in shares.items():
            agg[p] = agg.get(p, 0.0) + s
        top = max(shares, key=shares.get) if shares else ""
        pods.append({
            "trace": uid,
            "window_ms": round(window * 1000.0, 3),
            "top": top,
            "phases_ms": {p: round(s * 1000.0, 3)
                          for p, s in sorted(shares.items())},
            "unattributed_share": round(unatt, 4),
            "instances": sorted({r["instance"] for r in recs
                                 if r["instance"]}),
        })
    n = len(pods)
    phase_shares = {p: round(s / total_window, 4)
                    for p, s in sorted(agg.items())} \
        if total_window > 0 else {}
    top = max(phase_shares, key=phase_shares.get) if phase_shares \
        else ""
    out = {
        "pods": n,
        "fully_attributed": round(fully / n, 4) if n else 0.0,
        "phase_shares": phase_shares,
        "top": top,
        "top_share": phase_shares.get(top, 0.0),
        "unattributed_share": round(
            1.0 - total_attr / total_window, 4)
        if total_window > 0 else 1.0,
        "max_skew_ms": round(max_skew, 3),
        "skew_bound_ms": skew_bound_ms,
        "seam_windows": len(seams),
    }
    out["per_pod"] = pods if not max_pods else pods[:max_pods]
    return out


def collect_fleet_trace(
        remote: Iterable[Tuple[str, str]] = (),
        local: Iterable[Tuple[str, Tracer]] = (),
        token: str = "",
        window_s: Optional[float] = None,
        max_pods: int = 0) -> Tuple[dict, dict]:
    """One-call harness entry point: scrape ``(instance, url)`` pairs,
    absorb ``(instance, tracer)`` locals, return (merged fleet doc,
    critical-path aggregate). Best-effort end to end — scrape failures
    are listed in the doc's ``otherData.scrape_errors``."""
    fed = TraceFederation()
    for instance, url in remote:
        fed.scrape(url, instance, token=token, window_s=window_s)
    for instance, tracer in local:
        fed.absorb_local(tracer, instance, window_s=window_s)
    doc = fed.merged()
    return doc, critical_path(doc, max_pods=max_pods)
