"""End-to-end scheduling tracer + in-memory flight recorder.

One instrumentation layer feeding four consumers: structured logs
(``utils/trace.py`` LogIfLong compat shim), Prometheus histograms
(``schedtrace_phase_duration_seconds{phase=...}`` on ``/metrics``),
bench diagnostics (``bench.py``'s ``diag:`` line), and Chrome/Perfetto
``trace_event`` dumps (``/debug/trace``, degraded-mode entry, crash).
"""

from kubernetes_tpu.observability.tracer import (
    TRACE_HEADER,
    Span,
    TraceContext,
    Tracer,
    format_trace_header,
    get_tracer,
    parse_trace_header,
    set_tracer,
)

__all__ = ["Span", "Tracer", "get_tracer", "set_tracer",
           "TRACE_HEADER", "TraceContext", "format_trace_header",
           "parse_trace_header",
           "get_slo_engine", "set_slo_engine"]


def get_slo_engine():
    """Lazy re-export (slo.py imports metrics modules; keeping the
    import deferred keeps ``observability`` cheap for the hot paths
    that only need the tracer)."""
    from kubernetes_tpu.observability.slo import get_slo_engine as _g

    return _g()


def set_slo_engine(engine):
    from kubernetes_tpu.observability.slo import set_slo_engine as _s

    return _s(engine)
