"""End-to-end scheduling tracer + in-memory flight recorder.

One instrumentation layer feeding four consumers: structured logs
(``utils/trace.py`` LogIfLong compat shim), Prometheus histograms
(``schedtrace_phase_duration_seconds{phase=...}`` on ``/metrics``),
bench diagnostics (``bench.py``'s ``diag:`` line), and Chrome/Perfetto
``trace_event`` dumps (``/debug/trace``, degraded-mode entry, crash).
"""

from kubernetes_tpu.observability.tracer import (
    Span,
    Tracer,
    get_tracer,
    set_tracer,
)

__all__ = ["Span", "Tracer", "get_tracer", "set_tracer"]
