"""Device/solver profiling layer: per-solve-cycle hot-path telemetry.

The JAX solve path is the most expensive layer of the pipeline and was,
until this module, a black box: the bench ``diag:`` line hand-counted
commit/device/encode seconds, pad warms were *assumed* from bucket
bookkeeping, and "how much of device time is dispatch vs
``block_until_ready`` wait vs host↔device transfer" was unanswerable.
This recorder measures, per solve cycle:

- **XLA compile events** keyed by padded-shape bucket, via a
  ``jax.monitoring`` event-duration listener
  (``/jax/core/compile/backend_compile_duration``) with a
  timing-heuristic fallback for builds without the listener API —
  detecting *actual* recompiles, including the forbidden
  compile-inside-a-measured-cycle case the sidecar's pre-warm
  bookkeeping only prevents by convention;
- the **dispatch-vs-block split** around the solver call (async XLA
  dispatch time vs ``block_until_ready`` wait at materialization) — the
  direct input for the streaming-scheduler double-buffer design: block
  time is exactly the wall the host would win back by overlapping;
- **host↔device transfer bytes** computed from the encoded plane
  shapes/dtypes (pod stream up per cycle, static/state planes up per
  rebuild, assignments down per materialize);
- **pad occupancy** (real rows ÷ padded rows) per bucket — the scan
  length is the padded size, so waste here is device time burned on
  ghost pods.

Design constraints match ``tracer.py`` (the headline row schedules
thousands of pods/s through the instrumented path): recording is a few
float adds plus one GIL-atomic ``deque.append`` per solve *cycle* (not
per pod), so steady-state overhead is ~0 — the bar PR 2's tracer met,
re-measured by ``bench.py --config profab``.

Three consumers read the ring:

- ``kubernetes_tpu/metrics/solver_metrics.py`` mirrors each completed
  cycle into ``/metrics`` series (``solver_compiles_total{bucket}``,
  ``solver_device_wait_seconds``, ...);
- the bench telemetry stream: ``KTPU_TELEMETRY=<dir>`` writes one JSONL
  record per completed solve cycle, and ``summary()`` becomes the
  ``telemetry`` sub-object on every bench-row JSON;
- the flight recorder: cycle ids stamped on every record correlate with
  the tracer's ``solve.*`` spans, and a compile landing inside a
  measured cycle emits a ``solve.unexpected_compile`` instant plus a
  rate-limited flight-recorder dump (PR 2 machinery) so the postmortem
  is on disk before anyone asks.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_logger = logging.getLogger(__name__)

DEFAULT_MAX_CYCLES = 4096

# the jax.monitoring event that fires once per real XLA compilation
# (cache hits don't emit it — exactly the "actual recompile" signal)
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# timing-heuristic fallback thresholds (no listener API): a cycle whose
# device time exceeds BOTH `ratio × ` the bucket's best-seen time AND
# `floor` seconds above it is attributed a suspected compile
_HEURISTIC_RATIO = 4.0
_HEURISTIC_FLOOR_S = 0.25


class _Cycle(dict):
    """One solve cycle's record. A dict subclass so JSONL serialization
    and ring consumers get plain keys, with the few non-serialized
    control fields kept as attributes. ``dispatch_end`` is the
    monotonic instant the lazy dispatch returned — the start of the
    in-flight device window the streaming pipeline hides host work
    under; ``note_block`` turns it into the cycle's ``overlap_s``."""

    __slots__ = ("pending_block", "done", "dispatch_end")


class DevProfiler:
    """Lock-cheap per-solve-cycle recorder (ring-buffered like the
    tracer). One instance per process via ``get_devprof()``; the solver
    session opens a cycle around each solve, phases accumulate into the
    open record, and completion (at ``end_cycle`` or, for lazy solves,
    at the timed materializer's ``note_block``) mirrors the record into
    /metrics and the JSONL stream."""

    def __init__(
        self,
        enabled: Optional[bool] = None,
        max_cycles: int = DEFAULT_MAX_CYCLES,
        telemetry_dir: Optional[str] = None,
        use_listener: Optional[bool] = None,
    ):
        if enabled is None:
            enabled = os.environ.get("KTPU_DEVPROF", "") != "off"
        self.enabled = enabled
        self._ring: deque = deque(maxlen=max_cycles)
        self._local = threading.local()
        self._lock = threading.Lock()   # JSONL writes + best-time table
        self._seq = 0
        self.workload: str = ""
        # per-bucket best-seen device seconds (heuristic baseline) and
        # the warmed-compile ledger the sidecar's diag reads
        self._best_device_s: Dict[int, float] = {}
        self.warm_compiles = 0          # compiles inside warming cycles
        self.warm_compile_s = 0.0
        self.unexpected_compiles = 0    # compiles inside measured cycles
        self.background_compiles = 0    # compiles with no open cycle
        self._telemetry_path: Optional[str] = None
        self._telemetry_file = None
        tdir = telemetry_dir if telemetry_dir is not None \
            else os.environ.get("KTPU_TELEMETRY") or None
        if tdir:
            try:
                os.makedirs(tdir, exist_ok=True)
                self._telemetry_path = os.path.join(
                    tdir, f"solvercycles-{os.getpid()}.jsonl")
            except OSError:
                _logger.exception("KTPU_TELEMETRY dir unusable; stream off")
        # compile-event listener: jax.monitoring when available (and not
        # forced off for tests via KTPU_DEVPROF_HEURISTIC=1), else the
        # timing heuristic marks suspected compiles from device-time
        # outliers against the bucket's best-seen time
        if use_listener is None:
            use_listener = os.environ.get(
                "KTPU_DEVPROF_HEURISTIC", "") != "1"
        self.listener_active = bool(use_listener) and _install_listener()

    # -- cycle lifecycle ----------------------------------------------
    def begin_cycle(self, cycle: int = -1, pad: int = 0, real: int = 0,
                    warming: bool = False,
                    rebuild: str = "none") -> Optional[_Cycle]:
        """Open a per-solve-cycle record on this thread. ``cycle`` is
        the scheduling-cycle id (the tracer's correlation key), ``pad``
        the padded batch bucket, ``real`` the real (un-padded) pod
        count. ``rebuild`` marks the full/state_only re-encode paths so
        their one-off upload cost never pollutes the steady-state
        dispatch/block series."""
        if not self.enabled:
            return None
        rec = _Cycle(
            seq=self._next_seq(),
            wall=time.time(),
            workload=self.workload,
            cycle=int(cycle),
            pad=int(pad),
            real=int(real),
            warming=bool(warming),
            rebuild=rebuild,
            encode_s=0.0,
            pack_s=0.0,
            scatter_s=0.0,
            dispatch_s=0.0,
            block_s=0.0,
            compiles=0,
            compile_s=0.0,
            compile_suspected=False,
            h2d_bytes=0,
            d2h_bytes=0,
            donated_bytes=0,
            # device-mirror catch-up h2d (index/value triples) — a
            # subset of h2d_bytes kept separately attributable
            scatter_bytes=0,
        )
        rec.pending_block = False
        rec.done = False
        rec.dispatch_end = None
        self._local.active = rec
        self._ring.append(rec)
        return rec

    def phase(self, name: str, seconds: float) -> None:
        """Accumulate a phase duration (encode/pack/dispatch/block) into
        the open cycle. A few float adds — safe on the hot path."""
        rec = getattr(self._local, "active", None)
        if rec is not None and not rec.done:
            rec[name + "_s"] += seconds

    def note_staleness(self, rec: Optional[_Cycle],
                       seconds: float) -> None:
        """Record the snapshot-staleness SLI on an open cycle record
        (age of the newest watch event reflected in the planes this
        cycle solves against) — set once per cycle by the session."""
        if rec is not None and not rec.done:
            rec["staleness_s"] = round(float(seconds), 6)

    def add_bytes(self, direction: str, n: int) -> None:
        """Account a host↔device transfer (direction: h2d | d2h),
        computed by the caller from the encoded array shapes/dtypes —
        measuring the planes we *ship*, not interconnect counters.
        Direction ``donated`` is the separate ledger for planes a
        donated device-persistent buffer made REUSABLE this cycle —
        bytes that never crossed the link. They are excluded from the
        h2d total and the ``solver_transfer_bytes_total`` mirror (a
        resident buffer counted as an upload would make the transfer
        metric lie), but surfaced in ``summary()`` so the donation win
        is a number."""
        rec = getattr(self._local, "active", None)
        if rec is not None and not rec.done:
            rec[direction + "_bytes"] += int(n)

    def end_cycle(self, rec: Optional[_Cycle],
                  pending_block: bool = False) -> None:
        """Close the open cycle. With ``pending_block`` (a lazy solve
        whose materialization — and so its ``block_until_ready`` wait —
        happens cycles later in the commit pipeline) the record stays
        open for ``note_block`` to complete; everything else completes
        now."""
        if rec is None:
            return
        if getattr(self._local, "active", None) is rec:
            self._local.active = None
        if pending_block:
            # the in-flight device window opens HERE: host time spent
            # before the materializer finally blocks is work the
            # pipeline hid under the dispatched solve (overlap_s)
            rec.pending_block = True
            rec.dispatch_end = time.monotonic()
            return
        self._complete(rec)

    def abort(self, rec: Optional[_Cycle]) -> None:
        """Discard an open record that turned out to describe no solve
        (e.g. the incremental encode fell through to a rebuild): removed
        from the ring, never mirrored or streamed."""
        if rec is None:
            return
        rec.done = True
        if getattr(self._local, "active", None) is rec:
            self._local.active = None
        try:
            self._ring.remove(rec)
        except ValueError:
            pass

    def note_block(self, rec: Optional[_Cycle], seconds: float,
                   d2h_bytes: int = 0,
                   start_mono: Optional[float] = None) -> None:
        """Late completion for lazy solves: the timed materializer calls
        this with the measured ``block_until_ready`` wait and the
        assignments' device→host bytes. May run on a different thread
        and several cycles after ``end_cycle`` (the sidecar pipelines
        commit N while N+1 solves). ``start_mono`` is the monotonic
        instant the materializer began blocking: the gap back to this
        cycle's ``dispatch_end`` is host work performed WHILE the solve
        was in flight — the pipeline's ``overlap_s``, the time the
        double-buffered loop won back from the old barrier."""
        if rec is None or rec.done:
            return
        if start_mono is not None and rec.dispatch_end is not None:
            rec["overlap_s"] = round(
                max(0.0, start_mono - rec.dispatch_end), 6)
        rec["block_s"] += seconds
        rec["d2h_bytes"] += int(d2h_bytes)
        rec.pending_block = False
        self._complete(rec)

    # -- compile detection --------------------------------------------
    def on_compile(self, seconds: float) -> None:
        """Called by the process-wide jax.monitoring listener for every
        real XLA compilation. Attribution: the cycle open on the
        compiling thread (jit compiles synchronously inside the dispatch
        call), else background (warmup helpers, unrelated jit use)."""
        if not self.enabled:
            return
        rec = getattr(self._local, "active", None)
        if rec is None or rec.done:
            self.background_compiles += 1
            return
        rec["compiles"] += 1
        rec["compile_s"] += seconds

    def _heuristic_compiles(self, rec: _Cycle) -> None:
        """No listener API: flag a suspected compile when this bucket's
        device time is an extreme outlier against its best-seen time.
        Conservative by design (ratio AND absolute floor) — a tunnel
        stall can double a cycle, but a 4× + 250ms excursion on a warmed
        bucket is a compile or something equally dump-worthy."""
        device_s = rec["dispatch_s"] + rec["block_s"]
        bucket = rec["pad"]
        with self._lock:
            best = self._best_device_s.get(bucket)
            if best is None or device_s < best:
                self._best_device_s[bucket] = device_s
        if (
            best is not None
            and device_s > best * _HEURISTIC_RATIO
            and device_s > best + _HEURISTIC_FLOOR_S
        ):
            rec["compiles"] += 1
            rec["compile_suspected"] = True

    # -- completion ----------------------------------------------------
    def _complete(self, rec: _Cycle) -> None:
        if rec.done:
            return
        rec.done = True
        if not self.listener_active:
            self._heuristic_compiles(rec)
        if rec["compiles"]:
            if rec["warming"]:
                self.warm_compiles += rec["compiles"]
                self.warm_compile_s += rec["compile_s"]
            else:
                # the forbidden case: a compile landed inside a measured
                # cycle — the sidecar's pre-warm discipline failed, and
                # thousands of pods just absorbed the compile into their
                # e2e latency. Counter + tracer instant + flight dump.
                self.unexpected_compiles += rec["compiles"]
                self._flag_unexpected(rec)
        self._mirror_metrics(rec)
        self._write_jsonl(rec)

    def _flag_unexpected(self, rec: _Cycle) -> None:
        try:
            from kubernetes_tpu.metrics.solver_metrics import solver_metrics

            solver_metrics().unexpected_compiles_total.inc(
                amount=rec["compiles"])
        except Exception:  # pragma: no cover — metrics must never break
            pass
        try:
            from kubernetes_tpu.observability import get_tracer

            tracer = get_tracer()
            if tracer.enabled:
                tracer.event("solve.unexpected_compile",
                             cycle=rec["cycle"], pad=rec["pad"],
                             compile_s=round(rec["compile_s"], 4),
                             suspected=rec["compile_suspected"])
                # same stable-filename + rate-limit contract as the
                # degraded-mode dump: a compile storm overwrites one
                # postmortem instead of filling the dump dir
                tracer.dump(reason="unexpected-compile",
                            min_interval_s=5.0)
        except Exception:  # pragma: no cover — dumping is best-effort
            pass

    def _mirror_metrics(self, rec: _Cycle) -> None:
        if rec["warming"]:
            return
        try:
            from kubernetes_tpu.metrics.solver_metrics import solver_metrics

            sm = solver_metrics()
            bucket = str(rec["pad"])
            if rec["compiles"]:
                sm.compiles_total.inc(bucket, amount=rec["compiles"])
                if rec["compile_s"]:
                    sm.compile_seconds.observe(rec["compile_s"])
            sm.device_wait_seconds.observe(rec["block_s"])
            sm.dispatch_seconds.observe(rec["dispatch_s"])
            if rec["pad"]:
                sm.pad_occupancy_ratio.set(
                    rec["real"] / rec["pad"], bucket)
            if rec["h2d_bytes"]:
                sm.transfer_bytes_total.inc(
                    "h2d", amount=float(rec["h2d_bytes"]))
            if rec["d2h_bytes"]:
                sm.transfer_bytes_total.inc(
                    "d2h", amount=float(rec["d2h_bytes"]))
        except Exception:  # pragma: no cover — metrics must never break
            pass

    def _write_jsonl(self, rec: _Cycle) -> None:
        if self._telemetry_path is None:
            return
        try:
            with self._lock:
                if self._telemetry_file is None:
                    self._telemetry_file = open(self._telemetry_path, "a")
                self._telemetry_file.write(json.dumps(rec) + "\n")
                self._telemetry_file.flush()
        except OSError:
            _logger.exception("telemetry stream write failed; stream off")
            self._telemetry_path = None

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    # -- consumers -----------------------------------------------------
    def cycles(self, include_warming: bool = False) -> List[dict]:
        """Completed cycle records still in the ring, oldest first."""
        return [r for r in list(self._ring)
                if r.done and (include_warming or not r["warming"])]

    def summary(self) -> dict:
        """Aggregate the ring's measured (non-warming) cycles into the
        ``telemetry`` sub-object every bench row carries: compile count,
        device-wait share, pad waste, transfer bytes, and the slowest
        cycle's phase attribution (which phase made the max cycle slow
        is the first question every blown p99 asks)."""
        recs = self.cycles()
        out = {
            "cycles": len(recs),
            "compiles": 0,
            "compile_s": 0.0,
            "unexpected_compiles": self.unexpected_compiles,
            "warm_compiles": self.warm_compiles,
            "device_wait_share": 0.0,
            "overlap_share": 0.0,
            "overlap_s": 0.0,
            "overlapped_cycles": 0,
            "dispatch_s": 0.0,
            "block_s": 0.0,
            "encode_s": 0.0,
            "scatter_s": 0.0,
            "encode_share": 0.0,
            "pad_waste_pct": 0.0,
            "h2d_bytes": 0,
            "d2h_bytes": 0,
            "donated_bytes": 0,
            "scatter_bytes": 0,
            "compile_detector": "listener" if self.listener_active
            else "heuristic",
        }
        if not recs:
            return out
        tot = {"encode_s": 0.0, "pack_s": 0.0, "scatter_s": 0.0,
               "dispatch_s": 0.0, "block_s": 0.0}
        real = padded = 0
        slowest = None
        slowest_total = -1.0
        max_staleness = None
        # pipeline overlap: judged over the LAZY cycles only (the ones
        # that actually opened an in-flight device window) — an eager
        # cycle's block is a barrier by construction and must not
        # dilute the share of the window the host managed to hide
        ov_total = ov_block = 0.0
        overlapped = 0
        for r in recs:
            for k in tot:
                tot[k] += r.get(k, 0.0)
            ov = r.get("overlap_s")
            if ov is not None:
                ov_total += ov
                ov_block += r["block_s"]
                overlapped += 1
            out["compiles"] += r["compiles"]
            out["compile_s"] += r["compile_s"]
            out["h2d_bytes"] += r["h2d_bytes"]
            out["d2h_bytes"] += r["d2h_bytes"]
            out["donated_bytes"] += r.get("donated_bytes", 0)
            out["scatter_bytes"] += r.get("scatter_bytes", 0)
            stale = r.get("staleness_s")
            if stale is not None and (max_staleness is None
                                      or stale > max_staleness):
                max_staleness = stale
            real += r["real"]
            padded += r["pad"] if r["pad"] else r["real"]
            cycle_total = (r["encode_s"] + r["pack_s"] + r["dispatch_s"]
                           + r["block_s"] + r["compile_s"])
            if cycle_total > slowest_total:
                slowest_total, slowest = cycle_total, r
        phase_total = sum(tot.values())
        out["dispatch_s"] = round(tot["dispatch_s"], 4)
        out["block_s"] = round(tot["block_s"], 4)
        out["encode_s"] = round(tot["encode_s"] + tot["pack_s"], 4)
        out["scatter_s"] = round(tot["scatter_s"], 4)
        out["compile_s"] = round(out["compile_s"], 4)
        if phase_total > 0:
            out["device_wait_share"] = round(
                tot["block_s"] / phase_total, 4)
            # the mirror proof metric: host CLUSTER-PLANE build share
            # of the measured phase time. Pod-row delta encode (the
            # drained pods' h2d prep, inherent per-batch work) books
            # under pack_s and is excluded — the mirror's claim is that
            # node-column/full-plane encodes vanish from the sustained
            # row, not that drained pods stop needing encoding.
            out["encode_share"] = round(
                tot["encode_s"] / phase_total, 4)
        out["overlap_s"] = round(ov_total, 4)
        out["overlapped_cycles"] = overlapped
        if ov_total + ov_block > 0:
            # share of the in-flight device window hidden under host
            # work (drain/encode/commit of neighboring batches): 1.0 =
            # the materializer never waited, 0.0 = pure barrier
            out["overlap_share"] = round(
                ov_total / (ov_total + ov_block), 4)
        if padded > 0:
            out["pad_waste_pct"] = round(100.0 * (1.0 - real / padded), 2)
        if max_staleness is not None:
            # freshness SLI: the oldest snapshot any measured cycle
            # solved against (bench rows surface it as
            # freshness.max_snapshot_staleness_ms)
            out["max_staleness_s"] = round(max_staleness, 4)
        if slowest is not None:
            out["max_cycle"] = {
                "cycle": slowest["cycle"],
                "total_s": round(slowest_total, 4),
                "encode_s": round(
                    slowest["encode_s"] + slowest["pack_s"], 4),
                "dispatch_s": round(slowest["dispatch_s"], 4),
                "block_s": round(slowest["block_s"], 4),
                "compiles": slowest["compiles"],
                "rebuild": slowest["rebuild"],
            }
        return out

    def reset(self, workload: str = "") -> None:
        """Fresh window for a new bench row (mirrors the tracer's
        per-row ``clear``): the ring, per-run compile ledgers, and the
        heuristic baseline all restart; the /metrics counters keep
        accumulating (they are process-lifetime by contract)."""
        self._ring.clear()
        self._local = threading.local()
        self.workload = workload
        self.warm_compiles = 0
        self.warm_compile_s = 0.0
        self.unexpected_compiles = 0
        self.background_compiles = 0
        with self._lock:
            self._best_device_s.clear()

    def configure(self, enabled: Optional[bool] = None) -> None:
        if enabled is not None:
            self.enabled = enabled

    def close(self) -> None:
        with self._lock:
            if self._telemetry_file is not None:
                try:
                    self._telemetry_file.close()
                except OSError:
                    pass
                self._telemetry_file = None


# -- process-wide wiring (the legacyregistry pattern) ------------------

_listener_installed = False


def _install_listener() -> bool:
    """Register ONE process-wide jax.monitoring listener that routes
    compile events to whatever profiler is current (jax has no
    per-listener unregister, so the closure indirects through
    ``get_devprof``). Returns False when the API is unavailable — the
    caller falls back to the timing heuristic."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from jax import monitoring

        if not hasattr(monitoring, "register_event_duration_secs_listener"):
            return False

        def _on_event(name: str, seconds: float, **kw) -> None:
            if name == _COMPILE_EVENT:
                prof = _default
                if prof is not None:
                    prof.on_compile(seconds)

        monitoring.register_event_duration_secs_listener(_on_event)
        _listener_installed = True
        return True
    except Exception:  # noqa: BLE001 — profiling must never break solves
        _logger.exception("jax.monitoring listener unavailable; "
                          "falling back to the timing heuristic")
        return False


_default: Optional[DevProfiler] = None
_default_lock = threading.Lock()


def get_devprof() -> DevProfiler:
    """Process-wide device profiler. Disabled with KTPU_DEVPROF=off;
    KTPU_TELEMETRY=<dir> streams one JSONL record per solve cycle."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = DevProfiler()
    return _default


def set_devprof(prof: DevProfiler) -> DevProfiler:
    global _default
    _default = prof
    return prof
