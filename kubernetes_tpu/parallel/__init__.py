from kubernetes_tpu.parallel.sharded import (
    ShardedBackend,
    make_mesh,
    solve_scan_sharded,
)
