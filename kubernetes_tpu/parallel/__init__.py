from kubernetes_tpu.parallel.sharded import (
    make_mesh,
    solve_scan_sharded,
)
