"""Multi-chip sharded solver: the node axis distributed over a device mesh.

When 5k nodes x 30k pods exceeds one chip (or one chip's HBM bandwidth
budget), the node axis of every per-node plane shards across devices over
ICI (the moral analog of tensor parallelism; SURVEY.md section 5
"long-context" mapping). Uses the same gather-free per-node planes
representation as the single-chip backends (``ops.pallas_solver``):

- per device: feasibility + scores for the local node shard (dense
  vector ops, no gathers);
- ONE fused ``all_gather`` per pod carries each shard's local best
  (score, lowest candidate global index) together with that candidate's
  topology codes — every shard then resolves the global argmax, the
  lowest-index tie-break (matching ``jnp.argmax``), and the winner-code
  broadcast locally from the gathered [shards, 2+SC+T] row block. This
  replaces the naive pmax(score) + pmin(index) + 2x psum(codes) chain:
  collectives are latency-bound on ICI (the payload is tiny), so the
  sequential-dependency DEPTH per scan step, not bytes, is what the
  mesh pays for;
- per-constraint domain minima via local min + ``pmin`` — emitted only
  when the batch actually carries a hard topology-spread constraint
  (a static property of the encoded batch, so it is a compile-time
  branch): the common no-hard-spread batch runs ONE collective per pod.

A separate 2D phase (``batch`` x ``nodes``) computes the batched static
feasibility counts — the data-parallel analog — before the sequential
commit; both run under one ``shard_map`` jit so XLA schedules ICI
collectives, not host transfers.

``ShardedBackend`` packages all of this behind the ``SolverSession``
backend contract (prepare / solve_lazy / materialize), so the full
workload path — sidecar drain, pipelined commit, mirror-validity
accounting — can run on a device mesh. The jitted solve is cached per
(mesh, params, shape) signature: session rebuilds reuse the compiled
executable as long as the constraint space doesn't change shape.
"""

from __future__ import annotations

import os
import time
from functools import lru_cache, partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions: older runtimes (< 0.5)
    only ship ``jax.experimental.shard_map.shard_map``, whose
    replication-check kwarg is ``check_rep`` rather than ``check_vma``.
    Same semantics either way; this shim keeps the solver runnable on
    the baked-in toolchain."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as esm

    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)

from kubernetes_tpu.ops.encode import EncodedBatch, EncodedCluster
from kubernetes_tpu.ops.pallas_solver import (
    LANES,
    _state_planes,
    _static_planes,
    prepare,
)
from kubernetes_tpu.ops.solver import (
    BIG,
    NEG_INF,
    SolverParams,
    pack_podin,
    place_podin,
)


def make_mesh(n_devices: Optional[int] = None, batch_axis: int = 1) -> Mesh:
    """Build a (batch, nodes) mesh. The node axis carries the sharded
    solve; the batch axis parallelizes batched precomputation."""
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = np.asarray(devices[:n]).reshape(batch_axis, n // batch_axis)
    return Mesh(devices, axis_names=("batch", "nodes"))


class SStatic(NamedTuple):
    """Solve-invariant arrays in the sharded planes layout."""

    sc_meta: jnp.ndarray     # [2, SC] int32
    ints: jnp.ndarray        # [C_s, N] int32 — static planes, node-sharded
    f32s: jnp.ndarray        # [U, N] float32
    has_dom: jnp.ndarray     # [U, SC] bool — static domain existence
    # static dims (part of the compile key)
    r: int
    sc: int
    t: int
    u: int
    v: int
    n: int
    # shared-volume attach plane count (0 = none)
    sv: int = 0
    # True iff any encoded spread constraint is hard (DoNotSchedule):
    # compile-time branch — soft-only batches skip the per-pod domain-min
    # pmin collective entirely
    any_hard: bool = True


class SState(NamedTuple):
    """Dynamic state carried across batches: node-sharded planes plus the
    small replicated per-term totals (a node shard can't see the global
    term columns, so totals ride outside the planes)."""

    planes: jnp.ndarray      # [C_d, N] int32
    totals: jnp.ndarray      # [T] int32


def _step(params, dims, so, do, cols, sc_meta, static_l, f32_l, has_dom_r,
          carry, pod):
    """One pod of the sequential commit scan, on this device's node
    shard. Differentially exact vs the single-chip solvers.

    ``dims`` carries three static solve-shape flags beyond the sizes:
    ``shards`` (mesh width), ``any_hard`` (whether the domain-min pmin
    exists at all), and ``collectives`` (False = the timing-ablation
    build: every cross-shard op replaced by a local stand-in of the same
    arithmetic shape, so full-minus-ablated wall time isolates pure
    collective cost — results are garbage, never use for scheduling)."""
    r, sc, t, u, v, shards, any_hard, collectives, sv = dims
    c_req, c_nonzero, c_profile, c_valid, c_pod_sc, c_sc_match, \
        c_match_by, c_own_aff, c_own_anti, c_sv = cols
    state, totals = carry
    row, pref_w = pod
    n_local = static_l.shape[1]
    shard_ix = jax.lax.axis_index("nodes")
    gidx = shard_ix * n_local + jnp.arange(n_local, dtype=jnp.int32)

    node_valid = static_l[so["node_valid"]] > 0
    alloc = static_l[so["alloc"]:so["alloc"] + r]
    sc_codes = static_l[so["sc_codes"]:so["sc_codes"] + sc]
    term_codes = static_l[so["term_codes"]:so["term_codes"] + t]
    sc_missing = sc_codes >= v
    t_missing = term_codes >= v
    max_skew = sc_meta[0]
    hard = sc_meta[1] > 0

    pod_valid = row[c_valid] > 0
    profile = row[c_profile]
    req = row[c_req:c_req + r]
    pod_sc = row[c_pod_sc:c_pod_sc + sc] > 0
    sc_match = row[c_sc_match:c_sc_match + sc] > 0
    match_by = row[c_match_by:c_match_by + t] > 0
    own_aff = row[c_own_aff:c_own_aff + t] > 0
    own_anti = row[c_own_anti:c_own_anti + t] > 0

    requested = state[do["requested"]:do["requested"] + r]
    fit = jnp.all(requested + req[:, None] <= alloc, axis=0)
    fit &= state[do["pod_count"]] < static_l[so["max_pods"]]
    if sv:
        # shared-volume attach demand is CONDITIONAL per node (1 only
        # where this pod's volume isn't attached yet) — entirely LOCAL:
        # the sv planes shard over nodes like every other plane, and
        # the winner update below touches only the chosen node's shard
        sv_planes = state[do["sv_attached"]:do["sv_attached"] + sv]
        sv_slot = row[c_sv]
        sv_col = row[c_sv + 1]
        sv_is_shared = sv_slot < sv
        slot_c = jnp.minimum(sv_slot, sv - 1)
        att = jnp.take(sv_planes, slot_c, axis=0)         # [n_local]
        sv_demand = jnp.where(sv_is_shared, 1 - att, 0)
        col_alloc = jnp.take(alloc, sv_col, axis=0)
        col_req = jnp.take(requested, sv_col, axis=0)
        col_pod = jnp.take(req, sv_col)
        fit &= col_req + col_pod + sv_demand <= col_alloc
    static_ok = static_l[so["masks"] + profile] > 0

    counts = state[do["sc_counts"]:do["sc_counts"] + sc]
    if any_hard:
        # hard-spread feasibility needs the GLOBAL per-domain count
        # minimum; soft-only batches never read it, so the pmin exists
        # only in builds whose batch has a DoNotSchedule constraint
        dom = jax.lax.dynamic_slice_in_dim(
            static_l, so["sc_domain"] + profile * sc, sc, axis=0
        ) > 0
        lmin = jnp.min(jnp.where(dom, counts, BIG), axis=1)
        gmin = jax.lax.pmin(lmin, "nodes") if collectives else lmin
        min_c = jnp.where(has_dom_r[profile], gmin, 0)
        skew = counts + sc_match[:, None].astype(jnp.int32) - min_c[:, None]
        active_hard = pod_sc & hard
        spread_violation = jnp.any(
            active_hard[:, None]
            & ((skew > max_skew[:, None]) | sc_missing),
            axis=0,
        )
    else:
        spread_violation = jnp.zeros(static_l.shape[1], dtype=bool)

    tcounts = state[do["term_counts"]:do["term_counts"] + t]
    towners = state[do["term_owners"]:do["term_owners"] + t]
    existing_anti = jnp.any(match_by[:, None] & (towners > 0), axis=0)
    own_anti_block = jnp.any(own_anti[:, None] & (tcounts > 0), axis=0)
    aff_here = (tcounts > 0) & ~t_missing
    aff_sat = jnp.all(~own_aff[:, None] | aff_here, axis=0)
    no_any = jnp.all(~own_aff | (totals == 0))
    self_all = jnp.all(~own_aff | match_by)
    has_aff = jnp.any(own_aff)
    aff_ok = ~has_aff | aff_sat | (no_any & self_all)

    feasible = (
        node_valid & static_ok & fit & ~spread_violation
        & ~existing_anti & ~own_anti_block & aff_ok & pod_valid
    )

    alloc_cpu = jnp.maximum(alloc[0], 1).astype(jnp.float32)
    alloc_mem = jnp.maximum(alloc[1], 1).astype(jnp.float32)
    nz = state[do["nonzero"]:do["nonzero"] + 2]
    cpu_frac = (nz[0] + row[c_nonzero]).astype(jnp.float32) / alloc_cpu
    mem_frac = (nz[1] + row[c_nonzero + 1]).astype(
        jnp.float32
    ) / alloc_mem
    over = (cpu_frac >= 1.0) | (mem_frac >= 1.0)
    balanced = jnp.where(
        over, 0.0, (1.0 - jnp.abs(cpu_frac - mem_frac)) * 100.0
    )
    least = (
        jnp.clip(1.0 - cpu_frac, 0.0, 1.0)
        + jnp.clip(1.0 - mem_frac, 0.0, 1.0)
    ) * 50.0
    active_soft = pod_sc & ~hard
    soft_counts = jnp.sum(
        jnp.where(active_soft[:, None], counts, 0), axis=0
    ).astype(jnp.float32)
    spread_score = jnp.where(
        jnp.any(active_soft), 100.0 / (1.0 + soft_counts), 0.0
    )
    pref_score = jnp.sum(
        pref_w[:, None] * tcounts.astype(jnp.float32), axis=0
    )
    score = (
        params.balanced_weight * balanced
        + params.least_weight * least
        + params.spread_weight * spread_score
        + params.affinity_weight * pref_score
        + params.static_weight * f32_l[profile]
    )
    score = jnp.where(feasible, score, NEG_INF)

    # fused winner selection: each shard's local best score, its lowest
    # candidate global index at that score, and THAT candidate's
    # topology codes ride one all_gather; the global argmax, the
    # lowest-index tie-break, and the winner-code broadcast then resolve
    # locally on every shard from the [shards, 2+SC+T] block. One
    # latency-bound collective where the naive chain pays four.
    lmax = jnp.max(score)
    lcand = jnp.min(jnp.where(feasible & (score >= lmax), gidx, BIG))
    lone = gidx == lcand
    l_sc = jnp.sum(jnp.where(lone[None], sc_codes, 0), axis=1)
    l_t = jnp.sum(jnp.where(lone[None], term_codes, 0), axis=1)
    # f32 payload is exact: node indices < 2^24, topology codes <= V
    payload = jnp.concatenate([
        jnp.stack([lmax, lcand.astype(jnp.float32)]),
        l_sc.astype(jnp.float32),
        l_t.astype(jnp.float32),
    ])
    if collectives:
        gathered = jax.lax.all_gather(payload, "nodes")  # [S, 2+SC+T]
    else:
        gathered = jnp.tile(payload[None], (shards, 1))
    scores_g = gathered[:, 0]
    gmx = jnp.max(scores_g)
    found = gmx > NEG_INF / 2
    # shards' gidx ranges are disjoint and ordered, so the min over
    # tying shards' candidates IS the global lowest-index winner
    cand_sel = jnp.where(scores_g >= gmx, gathered[:, 1],
                         jnp.float32(BIG))
    wshard = jnp.argmin(cand_sel)
    chosen = cand_sel[wshard].astype(jnp.int32)
    valid = found & pod_valid
    assignment = jnp.where(found, chosen, -1)

    onehot = (gidx == chosen) & valid
    inc = onehot.astype(jnp.int32)
    valid_i = valid.astype(jnp.int32)
    wrow = gathered[wshard]
    sc_code_j = wrow[2:2 + sc].astype(jnp.int32)
    t_code_j = wrow[2 + sc:2 + sc + t].astype(jnp.int32)
    sc_inc = (sc_codes == sc_code_j[:, None]).astype(jnp.int32) \
        * (sc_match.astype(jnp.int32) * valid_i)[:, None]
    t_same = (term_codes == t_code_j[:, None]).astype(jnp.int32)
    t_inc = t_same * (match_by.astype(jnp.int32) * valid_i)[:, None]
    o_inc = t_same * (own_anti.astype(jnp.int32) * valid_i)[:, None]

    new_requested = requested + inc[None] * req[:, None]
    pieces = [
        new_requested,
        nz + inc[None] * row[c_nonzero:c_nonzero + 2][:, None],
        (state[do["pod_count"]] + inc)[None],
        counts + sc_inc,
        tcounts + t_inc,
        towners + o_inc,
    ]
    if sv:
        sv_add = inc * sv_demand
        pieces[0] = new_requested.at[sv_col].add(sv_add)
        shared_i = jnp.where(sv_is_shared, 1, 0)
        pieces.append(sv_planes.at[slot_c].max(inc * shared_i))
    pieces.append(state[do["totals"]][None])
    new_state = jnp.concatenate(pieces)
    new_totals = totals + (
        match_by.astype(jnp.int32) * valid_i * (t_code_j < v)
    )
    return (new_state, new_totals), assignment


def _batched_static_feasibility(so, r, u, c_req, c_profile, static_l,
                                pods_ints_l):
    """2D-parallel precompute: static-mask x fit counts for this
    device's (batch, nodes) tile — the data-parallel analog phase.
    Returns per-pod statically-feasible-node counts (psum over the
    node axis), an unschedulability early-signal."""
    alloc = static_l[so["alloc"]:so["alloc"] + r]       # [R, n_local]
    node_ok = static_l[so["node_valid"]] > 0
    reqs = pods_ints_l[:, c_req:c_req + r]              # [B_local, R]
    fit = jnp.all(
        reqs[:, :, None] <= alloc[None, :, :], axis=1
    )                                                   # [B_local, n_local]
    profiles = pods_ints_l[:, c_profile]
    masks = (
        static_l[so["masks"]:so["masks"] + u] > 0
    )[profiles]                                         # [B_local, n_local]
    both = fit & masks & node_ok[None, :]
    return jax.lax.psum(
        jnp.sum(both.astype(jnp.int32), axis=1), "nodes"
    )


@lru_cache(maxsize=32)
def _build_solve(mesh: Mesh, params: SolverParams, r: int, sc: int, t: int,
                 u: int, v: int, with_counts: bool = True,
                 any_hard: bool = True, collectives: bool = True,
                 sv: int = 0, donate: bool = False):
    """Build (and cache) the jitted shard_map solve for one
    (mesh, params, shape) signature. Session rebuilds within the same
    constraint space reuse the compiled executable. ``with_counts=False``
    drops the batched static-feasibility phase — the session hot path
    doesn't consume it, so it shouldn't pay the [B x n_local] matrix and
    its psum every batch. ``any_hard=False`` (no DoNotSchedule spread
    constraint in the batch) compiles out the per-pod domain-min pmin.
    ``collectives=False`` builds the timing-ablation variant (local
    stand-ins for every cross-shard op; results are garbage).
    ``donate=True`` donates the state planes + totals inputs to XLA
    (aliased into the same-sharded outputs): the carried state lives in
    ONE device buffer per shard across the whole session instead of a
    fresh allocation per cycle — callers must treat the passed-in state
    as consumed (the session replaces its mirror with the returned
    state every solve, so the contract holds by construction; warm
    solves clone first, see ``ShardedBackend.warm_state``)."""
    so, _ = _static_planes(r, sc, t, u)
    do, _ = _state_planes(r, sc, t, sv)
    c_req, c_nonzero, c_profile, c_valid = 0, r, r + 2, r + 3
    c_pod_sc, c_sc_match = r + 4, r + 4 + sc
    c_match_by = r + 4 + 2 * sc
    c_own_aff = r + 4 + 2 * sc + t
    c_own_anti = r + 4 + 2 * sc + 2 * t
    c_sv = r + 4 + 2 * sc + 3 * t
    cols = (c_req, c_nonzero, c_profile, c_valid, c_pod_sc, c_sc_match,
            c_match_by, c_own_aff, c_own_anti, c_sv)
    dims = (r, sc, t, u, v, mesh.shape["nodes"], any_hard, collectives,
            sv)

    node_sharded = P(None, "nodes")

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            P(),                 # sc_meta (replicated)
            node_sharded,        # static planes
            node_sharded,        # static f32 planes
            node_sharded,        # state planes
            P(),                 # totals (replicated)
            P(),                 # pod ints (scan stream, replicated)
            P(),                 # pod floats
            # batch-parallel phase input: replicated when the phase is
            # disabled, so the session path carries no batch-axis
            # divisibility constraint on the pad size
            P("batch", None) if with_counts else P(),
            P(),                 # has_dom [U, SC] (replicated)
        ),
        out_specs=(P(), P("batch") if with_counts else P(), node_sharded,
                   P()),
        check_vma=False,
    )
    def run(sc_meta, static_l, f32_l, planes_l, totals_r, ints_r,
            floats_r, pods_batch_i, has_dom_r):
        if with_counts:
            feasible_counts = _batched_static_feasibility(
                so, r, u, c_req, c_profile, static_l, pods_batch_i
            )
        else:
            feasible_counts = jnp.zeros(
                pods_batch_i.shape[0], dtype=jnp.int32
            )
        (new_planes, new_totals), assignments = jax.lax.scan(
            partial(_step, params, dims, so, do, cols, sc_meta, static_l,
                    f32_l, has_dom_r),
            (planes_l, totals_r),
            (ints_r, floats_r),
        )
        return assignments, feasible_counts, new_planes, new_totals

    if donate:
        # planes_l (arg 3) and totals_r (arg 4) alias into new_planes /
        # new_totals: identical shape, dtype and sharding spec, so XLA
        # reuses the input buffers in place
        return jax.jit(run, donate_argnums=(3, 4))
    return jax.jit(run)


def _host_state_planes(cluster: EncodedCluster, batch: EncodedBatch,
                       t: int, sv: int):
    """Host-side [C_d, N] state planes + [T] totals (flat layout)."""
    from kubernetes_tpu.ops.pallas_solver import prepare_state

    pstate = prepare_state(cluster, batch, device=False)
    cd = pstate.planes.shape[0]
    n = pstate.planes.shape[1] * LANES
    do, _ = _state_planes(
        cluster.allocatable.shape[1], batch.sc_counts.shape[0], t, sv)
    planes2 = np.asarray(pstate.planes).reshape(cd, n)
    totals0 = planes2[do["totals"]][:t].copy()  # encoder pads t >= 1
    return planes2, totals0


def _prepare_sharded(cluster: EncodedCluster, batch: EncodedBatch,
                     mesh: Mesh):
    """Pack encoder output into the sharded planes layout, committed
    with NamedSharding placement: node-sharded planes land directly on
    their shard (no reshard at first dispatch), small meta arrays
    replicated."""
    pstatic, pstate = prepare(cluster, batch, device=False)
    r, sc, t, u, v = pstatic.r, pstatic.sc, pstatic.t, pstatic.u, pstatic.v
    n = pstatic.nb * LANES
    shards = mesh.shape["nodes"]
    if n % shards != 0:
        raise ValueError(
            f"padded node count {n} not divisible by mesh nodes axis "
            f"{shards}"
        )
    sv = pstatic.sv
    _, cs = _static_planes(r, sc, t, u)
    do, cd = _state_planes(r, sc, t, sv)
    static2 = np.asarray(pstatic.ints).reshape(cs, n)
    f32s2 = np.asarray(pstatic.f32s).reshape(u, n)
    planes2 = np.asarray(pstate.planes).reshape(cd, n)
    totals0 = planes2[do["totals"]][:t].copy()  # encoder pads t >= 1
    # static per-(profile, constraint) domain existence: hoisted out of
    # the scan so each step needs no pmax collective for it
    has_dom = batch.sc_domain[:, :, :v].any(axis=2)     # [U, SC]
    node_sh = NamedSharding(mesh, P(None, "nodes"))
    rep = NamedSharding(mesh, P())
    put_n = partial(jax.device_put, device=node_sh)
    put_r = partial(jax.device_put, device=rep)
    sstatic = SStatic(
        sc_meta=put_r(np.asarray(pstatic.sc_meta)),
        ints=put_n(static2),
        f32s=put_n(f32s2),
        has_dom=put_r(np.ascontiguousarray(has_dom)),
        r=r, sc=sc, t=t, u=u, v=v, n=n, sv=sv,
        any_hard=bool(np.asarray(batch.sc_hard).any()),
    )
    sstate = SState(planes=put_n(planes2), totals=put_r(totals0))
    return sstatic, sstate


def _tree_nbytes(tree) -> int:
    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree_util.tree_leaves(tree)
               if hasattr(leaf, "nbytes"))


@lru_cache(maxsize=None)
def _scatter_jit(mesh: Mesh, op: str):
    """Jitted donated row/column scatter into node-sharded [C, N]
    planes (the device-resident cluster mirror's update kernel). The
    input plane stack is DONATED — the update is in-place on device —
    and ``out_shardings`` pins the result to the same node-sharded
    placement, so GSPMD routes each (row, col) entry to the shard
    owning that node column; the host ships only the index/value
    triples. Cached per (mesh, op); jit caches per entry-count bucket."""
    sharding = NamedSharding(mesh, P(None, "nodes"))

    def run(planes, rows, cols, vals):
        if op == "add":
            return planes.at[rows, cols].add(vals)
        return planes.at[rows, cols].set(vals)

    return jax.jit(run, donate_argnums=(0,), out_shardings=sharding)


class ShardedBackend:
    """SolverSession backend running the planes scan over a device mesh
    (drop-in next to PallasBackend / XlaPlanesBackend / CppBackend): the
    node axis of every plane is sharded over the mesh's ``nodes`` axis,
    the batched static-feasibility phase over its ``batch`` axis. State
    carries across batches exactly like the single-chip backends — the
    scan's final carry is the next batch's initial state.

    Default-path contract (the sharded-by-default tier of
    ``ops.session.default_backend``):

    - uploads are **NamedSharding-placed**: every static/state plane is
      committed shard-by-shard onto the mesh at prepare time, so the
      jitted solve never pays a reshard at dispatch;
    - the jitted solve **donates** the state planes + totals
      (``donate_argnums``), so the carried state occupies one device
      buffer per shard for the whole session and per-cycle h↔d copies
      of reusable planes disappear. ``donate=False`` (or env
      ``KTPU_SHARDED_DONATE=0``) selects the staging reference arm the
      devscale bench A/Bs against: no device-persistent planes — state
      rides host↔device every cycle (readback + re-upload), the
      conservative no-aliasing pattern whose copy cost donation
      eliminates;
    - the backend **self-accounts** its plane transfer bytes into the
      open devprof cycle (``self_accounting``): real uploads/readbacks
      count as h2d/d2h, while donated device-resident planes count into
      the separate ``donated`` ledger — excluded from
      ``solver_transfer_bytes_total`` so the proof metric never counts
      bytes that never crossed the link."""

    name = "sharded"
    # the session must not _tree_nbytes-charge this backend's prepared
    # pytrees as h2d: the backend accounts its own plane transfers
    # (donated device-resident buffers are NOT uploads). Bytes are
    # handed over via take_transfer_bytes AFTER a successful solve —
    # the session's charge-only-after-success rule: a failed sharded
    # chain link's upload must not pollute the cycle of the backend
    # that actually solved.
    self_accounting = True

    def __init__(self, mesh: Optional[Mesh] = None,
                 donate: Optional[bool] = None):
        self.mesh = mesh or make_mesh()
        if donate is None:
            donate = os.environ.get("KTPU_SHARDED_DONATE", "1") != "0"
        self.donate = bool(donate)
        # the encode stage splits its node-column fill by the same
        # shard boundaries the mesh uses (ops.encode node_shards)
        self.encode_shards = int(self.mesh.shape["nodes"])
        # synchronous host↔device staging seconds of the last solve
        # (the donate=False arm): the session re-attributes this from
        # its dispatch timing into the block phase — time the pipeline
        # spent feeding the device is device wait, not dispatch work
        self._staging_s = 0.0
        # transfer ledgers pending hand-over to the session:
        # epoch-level (prepare's plane uploads — overwritten by the
        # next prepare, so a failed solve can't leak them into a later
        # cycle) and per-cycle (solve_lazy's donated/staging bytes —
        # reset at the top of every solve)
        self._epoch_bytes: dict = {}
        self._cycle_bytes: dict = {}

    def _node_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(None, "nodes"))

    def _replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def take_transfer_bytes(self) -> dict:
        """Consume the pending transfer ledgers (direction → bytes).
        The session calls this after a SUCCESSFUL solve and books the
        result into the open devprof cycle; on failure nothing is
        taken and the next prepare/solve resets the ledgers."""
        out: dict = dict(self._epoch_bytes)
        for k, v in self._cycle_bytes.items():
            out[k] = out.get(k, 0) + v
        self._epoch_bytes = {}
        self._cycle_bytes = {}
        return out

    def prepare(self, cluster, batch):
        sstatic, sstate = _prepare_sharded(cluster, batch, self.mesh)
        # NamedSharding-placed uploads are REAL transfers; pending
        # until the solve succeeds (overwrite: one prepare per epoch)
        self._epoch_bytes = {
            "h2d": _tree_nbytes(sstatic) + _tree_nbytes(sstate)}
        return sstatic, sstate

    def prepare_state_only(self, cluster, batch):
        """State-only rebuild (static planes bit-identical to the
        resident ones): re-upload just the dynamic planes, NamedSharding
        placed like the full prepare."""
        # shapes must match the resident static or the session's
        # fingerprint check would not have routed here
        t = batch.term_counts.shape[0]
        sv = 0 if cluster.sv_attached is None else \
            cluster.sv_attached.shape[0]
        planes2, totals0 = _host_state_planes(cluster, batch, t, sv)
        if planes2.shape[1] % self.mesh.shape["nodes"] != 0:
            raise ValueError("padded node count not divisible by mesh")
        state = SState(
            planes=jax.device_put(planes2, self._node_sharding()),
            totals=jax.device_put(totals0, self._replicated()),
        )
        self._epoch_bytes = {"h2d": _tree_nbytes(state)}
        return state

    def warm_state(self, sstate: SState) -> SState:
        """Disposable deep copy of the carried state for warm solves:
        the donated executable CONSUMES its state inputs, so warming
        against the live mirror would invalidate the resident buffers.
        Warm cost stays out of measured cycles by the session's
        contract, so the round-trip copy is fine."""
        return SState(
            planes=jax.device_put(np.asarray(sstate.planes),
                                  self._node_sharding()),
            totals=jax.device_put(np.asarray(sstate.totals),
                                  self._replicated()),
        )

    def take_staging_s(self) -> float:
        """Consume the synchronous staging seconds of the last solve
        (0.0 on the donated path). The session moves this out of its
        dispatch measurement into the block phase."""
        s, self._staging_s = self._staging_s, 0.0
        return s

    # -------- device-resident mirror scatter hooks (ops.mirror)
    def scatter_state_add(self, sstate: SState, rows, cols, vals):
        """Add (row, col, val) deltas into the donated dynamic planes;
        returns (new state, h2d bytes). Only the index/value triples
        cross the link — the planes stay resident."""
        fn = _scatter_jit(self.mesh, "add")
        with self.mesh:
            planes = fn(sstate.planes, rows, cols, vals)
        return (SState(planes=planes, totals=sstate.totals),
                int(rows.nbytes + cols.nbytes + vals.nbytes))

    def scatter_static_set(self, sstatic: SStatic, rows, cols, vals):
        """Set absolute values (node capacity updates) into the donated
        static int planes; returns (new static, h2d bytes)."""
        fn = _scatter_jit(self.mesh, "set")
        with self.mesh:
            ints = fn(sstatic.ints, rows, cols, vals)
        return (sstatic._replace(ints=ints),
                int(rows.nbytes + cols.nbytes + vals.nbytes))

    def solve_lazy(self, params, sstatic, sstate, pod_ints, pod_floats,
                   donate: Optional[bool] = None):
        donate = self.donate if donate is None else donate
        run = _build_solve(self.mesh, params, sstatic.r, sstatic.sc,
                           sstatic.t, sstatic.u, sstatic.v,
                           with_counts=False, any_hard=sstatic.any_hard,
                           sv=sstatic.sv, donate=donate)
        rep = self._replicated()
        ints, floats = place_podin(pod_ints, pod_floats, sharding=rep)
        # per-cycle ledgers start fresh: a FAILED earlier solve (chain
        # demotion, warm abort) must not leak its staging seconds or
        # byte counts into this cycle's attribution
        self._cycle_bytes = {}
        self._staging_s = 0.0
        planes, totals = sstate.planes, sstate.totals
        plane_bytes = int(planes.nbytes) + int(totals.nbytes)
        if donate:
            # device-persistent donated planes: nothing crosses the
            # link this cycle — record what WOULD have shipped in the
            # separate donated ledger (excluded from transfer totals)
            self._cycle_bytes["donated"] = plane_bytes
        else:
            # staging arm ("before" reference): no device-persistent
            # state — read the carried planes back and re-upload them,
            # the per-cycle h↔d copy of reusable planes that donation
            # removes. Synchronous feed time is device wait, so it is
            # handed to the session via take_staging_s for the block
            # phase. (The readback copies a long-finished buffer — the
            # previous cycle's solve completed before its commit — so
            # this does not serialize the pipeline.)
            t0 = time.monotonic()
            planes_host = np.asarray(planes)
            totals_host = np.asarray(totals)
            planes = jax.device_put(planes_host, self._node_sharding())
            totals = jax.device_put(totals_host, rep)
            jax.block_until_ready((planes, totals))
            self._staging_s += time.monotonic() - t0
            self._cycle_bytes["d2h"] = plane_bytes
            self._cycle_bytes["h2d"] = plane_bytes
        with self.mesh:
            assignments, _counts, new_planes, new_totals = run(
                sstatic.sc_meta, sstatic.ints, sstatic.f32s, planes,
                totals, ints, floats, ints, sstatic.has_dom,
            )
        return assignments, SState(planes=new_planes, totals=new_totals)

    @staticmethod
    def materialize(handle):
        return np.asarray(handle)

    def solve(self, params, sstatic, sstate, pod_ints, pod_floats):
        h, state = self.solve_lazy(params, sstatic, sstate, pod_ints,
                                   pod_floats)
        return self.materialize(h), state


def solve_scan_sharded(
    cluster: EncodedCluster,
    batch: EncodedBatch,
    mesh: Mesh,
    params: SolverParams = SolverParams(),
):
    """Sharded solve over `mesh` (axes ("batch","nodes")). Returns
    (assignments [B] int32 global node indices, feasible_counts [B]).
    Matches the single-chip solvers exactly (differential tests)."""
    sstatic, sstate = _prepare_sharded(cluster, batch, mesh)
    run = _build_solve(mesh, params, sstatic.r, sstatic.sc, sstatic.t,
                       sstatic.u, sstatic.v, any_hard=sstatic.any_hard,
                       sv=sstatic.sv)
    pod_ints, pod_floats = pack_podin(batch)
    b_axis = mesh.shape["batch"]
    if pod_ints.shape[0] % b_axis != 0:
        raise ValueError(
            f"padded batch size {pod_ints.shape[0]} not divisible by mesh "
            f"batch axis {b_axis}"
        )
    ints = jnp.asarray(pod_ints)
    floats = jnp.asarray(pod_floats)
    with mesh:
        assignments, feasible_counts, _, _ = run(
            sstatic.sc_meta, sstatic.ints, sstatic.f32s, sstate.planes,
            sstate.totals, ints, floats, ints, sstatic.has_dom,
        )
    return np.asarray(assignments), np.asarray(feasible_counts)
