"""Multi-chip sharded solver: the node axis distributed over a device mesh.

When 5k nodes x 30k pods exceeds one chip (or one chip's HBM bandwidth
budget), the node axis of every per-node tensor shards across devices over
ICI (the moral analog of tensor parallelism; SURVEY.md section 5
"long-context" mapping), while the small topology-count state stays
replicated with ``psum``'d deltas:

- per-device: feasibility + scores for the local node shard (vector ops);
- global argmax via ``pmax`` on (score, -global_index) pairs;
- the winning device broadcasts the chosen node's topology codes via
  ``psum`` (one-hot masked), so every replica applies identical count
  updates — replicated state never diverges.

A separate 2D phase (``batch`` x ``nodes``) computes the batched static
feasibility/score tensors — the data-parallel analog — before the
sequential commit; both run under one ``shard_map`` jit so XLA schedules
ICI collectives, not host transfers.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops.encode import EncodedBatch, EncodedCluster
from kubernetes_tpu.ops.solver import (
    NEG_INF,
    BIG,
    SolverParams,
    _PodIn,
    _State,
    _Static,
)


def make_mesh(n_devices: Optional[int] = None, batch_axis: int = 1) -> Mesh:
    """Build a (batch, nodes) mesh. The node axis carries the sharded
    solve; the batch axis parallelizes batched precomputation."""
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = np.asarray(devices[:n]).reshape(batch_axis, n // batch_axis)
    return Mesh(devices, axis_names=("batch", "nodes"))


def _sharded_step(params: SolverParams, static: _Static,
                  state: _State, pod: _PodIn):
    """One scan step on a node shard. Mirrors ops.solver._step, with the
    argmax and count updates turned into collectives."""
    axis = "nodes"
    n_local = static.allocatable.shape[0]
    shard_index = jax.lax.axis_index(axis)
    v = state.sc_counts.shape[1] - 1

    fit = jnp.all(
        state.requested + pod.request[None, :] <= static.allocatable, axis=1
    )
    fit &= state.pod_count < static.max_pods
    static_ok = static.static_masks[pod.profile]

    counts_at = jnp.take_along_axis(state.sc_counts, static.sc_codes, axis=1)
    domain = static.sc_domain[pod.profile]
    min_c = jnp.min(jnp.where(domain[:, :v], state.sc_counts[:, :v], BIG), axis=1)
    min_c = jnp.where(jnp.any(domain[:, :v], axis=1), min_c, 0)
    skew = counts_at + pod.pod_sc_match[:, None].astype(jnp.int32) - min_c[:, None]
    missing = static.sc_codes >= v
    active_hard = pod.pod_sc & static.sc_hard
    spread_violation = jnp.any(
        active_hard[:, None] & ((skew > static.sc_max_skew[:, None]) | missing),
        axis=0,
    )

    tcounts_at = jnp.take_along_axis(state.term_counts, static.term_codes, axis=1)
    towners_at = jnp.take_along_axis(state.term_owners, static.term_codes, axis=1)
    t_missing = static.term_codes >= v
    existing_anti_block = jnp.any(pod.match_by[:, None] & (towners_at > 0), axis=0)
    own_anti_block = jnp.any(pod.own_anti[:, None] & (tcounts_at > 0), axis=0)
    aff_here = (tcounts_at > 0) & ~t_missing
    aff_sat = jnp.all(~pod.own_aff[:, None] | aff_here, axis=0)
    totals = jnp.sum(state.term_counts[:, :v], axis=1)
    no_any = jnp.all(~pod.own_aff | (totals == 0))
    self_all = jnp.all(~pod.own_aff | pod.match_by)
    has_aff = jnp.any(pod.own_aff)
    aff_ok = jnp.where(has_aff, aff_sat | (no_any & self_all), True)

    feasible = (
        static.node_valid & static_ok & fit & ~spread_violation
        & ~existing_anti_block & ~own_anti_block & aff_ok & pod.valid
    )

    alloc_cpu = jnp.maximum(static.allocatable[:, 0], 1).astype(jnp.float32)
    alloc_mem = jnp.maximum(static.allocatable[:, 1], 1).astype(jnp.float32)
    cpu_frac = (state.nonzero_requested[:, 0] + pod.nonzero_request[0]).astype(
        jnp.float32
    ) / alloc_cpu
    mem_frac = (state.nonzero_requested[:, 1] + pod.nonzero_request[1]).astype(
        jnp.float32
    ) / alloc_mem
    over = (cpu_frac >= 1.0) | (mem_frac >= 1.0)
    balanced = jnp.where(over, 0.0, (1.0 - jnp.abs(cpu_frac - mem_frac)) * 100.0)
    least = (
        jnp.clip(1.0 - cpu_frac, 0.0, 1.0) + jnp.clip(1.0 - mem_frac, 0.0, 1.0)
    ) * 50.0
    active_soft = pod.pod_sc & ~static.sc_hard
    soft_counts = jnp.sum(
        jnp.where(active_soft[:, None], counts_at, 0), axis=0
    ).astype(jnp.float32)
    spread_score = jnp.where(
        jnp.any(active_soft), 100.0 / (1.0 + soft_counts), 0.0
    )
    pref_score = jnp.sum(
        pod.pref_weight[:, None] * tcounts_at.astype(jnp.float32), axis=0
    )
    score = (
        params.balanced_weight * balanced
        + params.least_weight * least
        + params.spread_weight * spread_score
        + params.affinity_weight * pref_score
        + params.static_weight * static.static_scores[pod.profile]
    )
    score = jnp.where(feasible, score, NEG_INF)

    # ---- global argmax over the sharded node axis --------------------
    local_best = jnp.argmax(score)
    local_score = score[local_best]
    global_index = shard_index * n_local + local_best
    # lexicographic (score, -index): highest score, lowest index wins
    pair = (local_score, -global_index.astype(jnp.int32))
    best_score = jax.lax.pmax(pair[0], axis)
    # among shards holding best_score, pick the lowest global index
    candidate_idx = jnp.where(local_score >= best_score, -pair[1], np.int32(2**30))
    best_global = -jax.lax.pmax(-candidate_idx, axis)
    found = best_score > NEG_INF / 2
    chosen_global = jnp.where(found, best_global, -1)
    valid = found & pod.valid

    # local one-hot commit
    local_chosen = chosen_global - shard_index * n_local
    onehot = (jnp.arange(n_local) == local_chosen) & valid
    inc = onehot.astype(jnp.int32)

    # chosen node's topo codes, broadcast to every replica via psum
    sc_chosen_code = jax.lax.psum(
        jnp.sum(jnp.where(onehot[None, :], static.sc_codes, 0), axis=1), axis
    )
    term_chosen_code = jax.lax.psum(
        jnp.sum(jnp.where(onehot[None, :], static.term_codes, 0), axis=1), axis
    )
    sc_chosen_code = jnp.where(valid, sc_chosen_code, v)
    term_chosen_code = jnp.where(valid, term_chosen_code, v)

    new_state = _State(
        requested=state.requested + inc[:, None] * pod.request[None, :],
        nonzero_requested=state.nonzero_requested
        + inc[:, None] * pod.nonzero_request[None, :],
        pod_count=state.pod_count + inc,
        sc_counts=state.sc_counts.at[
            jnp.arange(state.sc_counts.shape[0]), sc_chosen_code
        ].add((pod.pod_sc_match & valid).astype(jnp.int32)),
        term_counts=state.term_counts.at[
            jnp.arange(state.term_counts.shape[0]), term_chosen_code
        ].add((pod.match_by & valid).astype(jnp.int32)),
        term_owners=state.term_owners.at[
            jnp.arange(state.term_owners.shape[0]), term_chosen_code
        ].add((pod.own_anti & valid).astype(jnp.int32)),
    )
    return new_state, chosen_global


def _batched_static_feasibility(static: _Static, pods: _PodIn):
    """2D-parallel precompute: the [B_local, N_local] static-mask x fit
    tensor for this device's (batch, nodes) tile — the data-parallel
    analog phase that exercises both mesh axes before the sequential
    commit. Returned summed over nodes as a per-pod feasible-node count
    (useful as an unschedulability early-signal)."""
    fit = jnp.all(
        pods.request[:, None, :] <= static.allocatable[None, :, :], axis=2
    )
    mask = static.static_masks[pods.profile]  # [B_local, N_local]
    both = fit & mask & static.node_valid[None, :]
    local = jnp.sum(both.astype(jnp.int32), axis=1)
    return jax.lax.psum(local, "nodes")


def solve_scan_sharded(
    cluster: EncodedCluster,
    batch: EncodedBatch,
    mesh: Mesh,
    params: SolverParams = SolverParams(),
):
    """Sharded solve over `mesh` (axes ("batch","nodes")). Node-sharded
    arrays are laid out with NamedSharding so jit moves them once; the
    scan runs under shard_map with ICI collectives."""
    from jax import shard_map

    n_nodes_shards = mesh.shape["nodes"]
    n = cluster.allocatable.shape[0]
    if n % n_nodes_shards != 0:
        raise ValueError(f"padded node count {n} not divisible by mesh nodes "
                         f"axis {n_nodes_shards}")
    v = batch.num_values

    sc_codes = np.minimum(cluster.topo_codes[:, batch.sc_key_idx].T, v).astype(np.int32)
    term_codes = np.minimum(cluster.topo_codes[:, batch.term_key_idx].T, v).astype(np.int32)
    node_valid = np.zeros(n, dtype=bool)
    node_valid[: cluster.num_real_nodes] = True

    static = _Static(
        allocatable=jnp.asarray(cluster.allocatable),
        max_pods=jnp.asarray(cluster.max_pods),
        static_masks=jnp.asarray(batch.static_masks),
        static_scores=jnp.asarray(batch.static_scores),
        sc_codes=jnp.asarray(sc_codes),
        sc_max_skew=jnp.asarray(batch.sc_max_skew),
        sc_hard=jnp.asarray(batch.sc_hard),
        sc_domain=jnp.asarray(batch.sc_domain),
        term_codes=jnp.asarray(term_codes),
        node_valid=jnp.asarray(node_valid),
    )
    state = _State(
        requested=jnp.asarray(cluster.requested),
        nonzero_requested=jnp.asarray(cluster.nonzero_requested),
        pod_count=jnp.asarray(cluster.pod_count),
        sc_counts=jnp.asarray(batch.sc_counts),
        term_counts=jnp.asarray(batch.term_counts),
        term_owners=jnp.asarray(batch.term_owners),
    )
    b = batch.requests.shape[0]
    valid = np.zeros(b, dtype=bool)
    valid[: batch.num_real_pods] = True
    valid &= ~batch.inexpressible
    pods = _PodIn(
        request=jnp.asarray(batch.requests),
        nonzero_request=jnp.asarray(batch.nonzero_requests),
        profile=jnp.asarray(batch.profile_idx),
        valid=jnp.asarray(valid),
        pod_sc=jnp.asarray(batch.pod_sc),
        pod_sc_match=jnp.asarray(batch.pod_sc_match),
        match_by=jnp.asarray(batch.match_by),
        own_aff=jnp.asarray(batch.own_aff),
        own_anti=jnp.asarray(batch.own_anti),
        pref_weight=jnp.asarray(batch.pref_weight),
    )

    # shardings: node axis sharded; counts/pod streams replicated
    node_sharded = P(None, "nodes")
    static_specs = _Static(
        allocatable=P("nodes", None),
        max_pods=P("nodes"),
        static_masks=node_sharded,
        static_scores=node_sharded,
        sc_codes=node_sharded,
        sc_max_skew=P(),
        sc_hard=P(),
        sc_domain=P(),
        term_codes=node_sharded,
        node_valid=P("nodes"),
    )
    state_specs = _State(
        requested=P("nodes", None),
        nonzero_requested=P("nodes", None),
        pod_count=P("nodes"),
        sc_counts=P(),
        term_counts=P(),
        term_owners=P(),
    )
    pods_scan_specs = jax.tree.map(lambda _: P(), pods)
    pods_batch_specs = _PodIn(
        request=P("batch", None),
        nonzero_request=P("batch", None),
        profile=P("batch"),
        valid=P("batch"),
        pod_sc=P("batch", None),
        pod_sc_match=P("batch", None),
        match_by=P("batch", None),
        own_aff=P("batch", None),
        own_anti=P("batch", None),
        pref_weight=P("batch", None),
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(static_specs, state_specs, pods_scan_specs, pods_batch_specs),
        out_specs=(P(), P("batch")),
        check_vma=False,
    )
    def run(static_l, state_l, pods_scan, pods_batch):
        feasible_counts = _batched_static_feasibility(static_l, pods_batch)
        _, assignments = jax.lax.scan(
            partial(_sharded_step, params, static_l), state_l, pods_scan
        )
        return assignments, feasible_counts

    with mesh:
        jitted = jax.jit(run)
        assignments, feasible_counts = jitted(static, state, pods, pods)
    return np.asarray(assignments), np.asarray(feasible_counts)
