"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

A brand-new implementation of the capabilities of Kubernetes' kube-scheduler
(reference: upstream k8s ~v1.20/1.21, ``pkg/scheduler/``), re-designed
TPU-first: an authoritative host control path (watch-fed cluster cache,
incremental snapshot, pluggable scheduling framework, three-tier pending
queue, async binder) plus a JAX/XLA batch path that evaluates scheduling
predicates and scores as dense pod-by-node tensors and solves assignment on
device (serial-equivalent `lax.scan` commit, or sharded multi-chip solve via
`shard_map` over a `jax.sharding.Mesh`).

Layout (mirrors SURVEY.md section 2's component inventory):

- ``api/``        object model + apimachinery subset (Quantity, label selectors)
- ``apiserver/``  in-process state store with watches + Binding subresource
- ``scheduler/``  cache, snapshot, queue, framework, plugins, core, loop
- ``config/``     component config (profiles, plugin args), feature gates
- ``ops/``        JAX predicate/score kernels + device snapshot encoding
- ``parallel/``   mesh construction + sharded solver (multi-chip)
- ``harness/``    scheduler_perf-style declarative benchmark harness
- ``metrics/``    prometheus-style metrics registry
- ``utils/``      tracing, clocks, backoff, parallel helpers
"""

__version__ = "0.1.0"
