"""Component configuration (reference
``pkg/scheduler/apis/config/types.go:49-243`` KubeSchedulerConfiguration):
parallelism, percentage-of-nodes-to-score, backoff bounds, per-profile
enabled/disabled plugin sets with weights, typed per-plugin args, and
extender entries. ``from_dict`` accepts v1beta1-shaped dicts."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 0  # 0 = adaptive
MIN_FEASIBLE_NODES_TO_FIND = 100          # generic_scheduler.go:47-52
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5
DEFAULT_PARALLELISM = 16

EXTENSION_POINTS = (
    "queue_sort",
    "pre_filter",
    "filter",
    "post_filter",
    "pre_score",
    "score",
    "reserve",
    "permit",
    "pre_bind",
    "bind",
    "post_bind",
)

_CAMEL = {
    "queue_sort": "queueSort",
    "pre_filter": "preFilter",
    "filter": "filter",
    "post_filter": "postFilter",
    "pre_score": "preScore",
    "score": "score",
    "reserve": "reserve",
    "permit": "permit",
    "pre_bind": "preBind",
    "bind": "bind",
    "post_bind": "postBind",
}


@dataclass
class PluginEntry:
    name: str
    weight: int = 1


@dataclass
class PluginSet:
    enabled: List[PluginEntry] = field(default_factory=list)
    disabled: List[PluginEntry] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "PluginSet":
        d = d or {}
        return cls(
            enabled=[
                PluginEntry(e["name"], int(e.get("weight") or 1))
                for e in (d.get("enabled") or [])
            ],
            disabled=[
                PluginEntry(e["name"]) for e in (d.get("disabled") or [])
            ],
        )


@dataclass
class Plugins:
    queue_sort: PluginSet = field(default_factory=PluginSet)
    pre_filter: PluginSet = field(default_factory=PluginSet)
    filter: PluginSet = field(default_factory=PluginSet)
    post_filter: PluginSet = field(default_factory=PluginSet)
    pre_score: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    pre_bind: PluginSet = field(default_factory=PluginSet)
    bind: PluginSet = field(default_factory=PluginSet)
    post_bind: PluginSet = field(default_factory=PluginSet)

    def get(self, point: str) -> PluginSet:
        return getattr(self, point)

    def merge_defaults(self, defaults: "Plugins") -> "Plugins":
        """Profile plugins overlay the provider defaults: enabled appends,
        disabled removes ("*" disables all defaults) — reference
        apis/config/v1beta1 mergePlugins semantics."""
        out = Plugins()
        for point in EXTENSION_POINTS:
            dset, pset = defaults.get(point), self.get(point)
            disabled = {e.name for e in pset.disabled}
            enabled = []
            if "*" not in disabled:
                enabled = [e for e in dset.enabled if e.name not in disabled]
            enabled += [e for e in pset.enabled]
            out.get(point).enabled = enabled
        return out

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "Plugins":
        d = d or {}
        p = cls()
        for point in EXTENSION_POINTS:
            setattr(p, point, PluginSet.from_dict(d.get(_CAMEL[point])))
        return p


@dataclass
class PluginConfig:
    name: str
    args: Dict[str, Any] = field(default_factory=dict)


@dataclass
class KubeSchedulerProfile:
    scheduler_name: str = "default-scheduler"
    plugins: Optional[Plugins] = None
    plugin_config: List[PluginConfig] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "KubeSchedulerProfile":
        return cls(
            scheduler_name=d.get("schedulerName", "default-scheduler"),
            plugins=Plugins.from_dict(d["plugins"]) if "plugins" in d else None,
            plugin_config=[
                PluginConfig(c["name"], dict(c.get("args") or {}))
                for c in (d.get("pluginConfig") or [])
            ],
        )

    def get_plugin_args(self, name: str) -> Dict[str, Any]:
        for c in self.plugin_config:
            if c.name == name:
                return c.args
        return {}


@dataclass
class Extender:
    """Legacy HTTP extender config (reference apis/config types +
    core/extender.go)."""

    url_prefix: str = ""
    filter_verb: str = ""
    preempt_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    enable_https: bool = False
    http_timeout: float = 30.0
    node_cache_capable: bool = False
    managed_resources: List[str] = field(default_factory=list)
    ignorable: bool = False
    # test/in-process hook: a python object implementing the verbs directly
    implementation: Any = None

    def is_interested(self, pod) -> bool:
        if not self.managed_resources:
            return True
        names = set()
        for c in pod.spec.containers + pod.spec.init_containers:
            names.update(c.resources.requests)
            names.update(c.resources.limits)
        return bool(names & set(self.managed_resources))


@dataclass
class KubeSchedulerConfiguration:
    parallelism: int = DEFAULT_PARALLELISM
    percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    profiles: List[KubeSchedulerProfile] = field(
        default_factory=lambda: [KubeSchedulerProfile()]
    )
    extenders: List[Extender] = field(default_factory=list)
    feature_gates: Dict[str, bool] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Mapping) -> "KubeSchedulerConfiguration":
        cfg = cls(
            parallelism=int(d.get("parallelism", DEFAULT_PARALLELISM)),
            percentage_of_nodes_to_score=int(d.get("percentageOfNodesToScore", 0)),
            pod_initial_backoff_seconds=float(d.get("podInitialBackoffSeconds", 1)),
            pod_max_backoff_seconds=float(d.get("podMaxBackoffSeconds", 10)),
            feature_gates=dict(d.get("featureGates") or {}),
        )
        if d.get("profiles"):
            cfg.profiles = [KubeSchedulerProfile.from_dict(p) for p in d["profiles"]]
        if d.get("extenders"):
            cfg.extenders = [
                Extender(
                    url_prefix=e.get("urlPrefix", ""),
                    filter_verb=e.get("filterVerb", ""),
                    preempt_verb=e.get("preemptVerb", ""),
                    prioritize_verb=e.get("prioritizeVerb", ""),
                    bind_verb=e.get("bindVerb", ""),
                    weight=int(e.get("weight", 1)),
                    http_timeout=float(e.get("httpTimeout", 30)),
                    node_cache_capable=bool(e.get("nodeCacheCapable")),
                    managed_resources=[
                        m["name"] for m in (e.get("managedResources") or [])
                    ],
                    ignorable=bool(e.get("ignorable")),
                )
                for e in d["extenders"]
            ]
        return cfg

    def validate(self) -> List[str]:
        """Reference apis/config/validation: collect human-readable errors."""
        errs = []
        if self.parallelism <= 0:
            errs.append("parallelism must be positive")
        if not (0 <= self.percentage_of_nodes_to_score <= 100):
            errs.append("percentageOfNodesToScore must be in [0,100]")
        if self.pod_initial_backoff_seconds <= 0:
            errs.append("podInitialBackoffSeconds must be positive")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            errs.append("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
        names = [p.scheduler_name for p in self.profiles]
        if len(set(names)) != len(names):
            errs.append("profile schedulerNames must be unique")
        for p in self.profiles:
            if not p.scheduler_name:
                errs.append("schedulerName cannot be empty")
            if p.plugins is not None:
                for point in EXTENSION_POINTS:
                    for e in p.plugins.get(point).enabled:
                        if not e.name:
                            errs.append(
                                f"{p.scheduler_name}: {point} plugin "
                                "name cannot be empty")
                        if point == "score" and not 0 <= e.weight <= 100:
                            # framework MaxTotalScoreWeight discipline
                            # (apis/config/validation)
                            errs.append(
                                f"{p.scheduler_name}: score plugin "
                                f"{e.name!r} weight {e.weight} not in "
                                "[0,100]")
        binders = 0
        for ext in self.extenders:
            if not ext.url_prefix and ext.implementation is None:
                errs.append("extender urlPrefix cannot be empty")
            if ext.weight <= 0:
                errs.append("extender weight must be positive")
            if ext.bind_verb:
                binders += 1
        if binders > 1:
            # v1beta1 validation: only one extender may be the binder
            errs.append("only one extender can implement bind")
        return errs
