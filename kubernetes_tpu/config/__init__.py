from kubernetes_tpu.config.types import (
    Extender,
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    PluginConfig,
    PluginEntry,
    Plugins,
    PluginSet,
)
from kubernetes_tpu.config.feature_gates import FeatureGates, default_feature_gates
