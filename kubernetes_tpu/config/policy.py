"""Legacy Policy API → plugin configuration translation.

Behavioral equivalent of the reference's legacy config path
(``pkg/scheduler/factory.go:207-296 createFromConfig`` +
``framework/plugins/legacy_registry.go``): a v1 Policy JSON document
listing predicate/priority names (the pre-framework configuration
surface) is translated into the framework's per-extension-point plugin
sets. Mandatory plugins (QueueSort, Bind, PostFilter/preemption) are
always wired, exactly as ``createFromConfig`` appends them regardless of
the Policy content.

Usage::

    cfg = policy_to_config(json.loads(policy_text))
    sched = Scheduler.create(store, config=cfg)
"""

from __future__ import annotations

import json
from typing import Any, List, Mapping, Optional

from kubernetes_tpu.config.types import (
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    PluginConfig,
    PluginEntry,
    Plugins,
)

# legacy predicate name -> (plugin, extension points)
# (legacy_registry.go registeredPredicates)
PREDICATE_MAP = {
    "PodFitsResources": ("NodeResourcesFit", ("pre_filter", "filter")),
    "PodFitsHostPorts": ("NodePorts", ("pre_filter", "filter")),
    "HostName": ("NodeName", ("filter",)),
    "MatchNodeSelector": ("NodeAffinity", ("filter",)),
    "NoDiskConflict": ("VolumeRestrictions", ("filter",)),
    "NoVolumeZoneConflict": ("VolumeZone", ("filter",)),
    "PodToleratesNodeTaints": ("TaintToleration", ("filter",)),
    "CheckNodeUnschedulable": ("NodeUnschedulable", ("filter",)),
    "MaxCSIVolumeCountPred": ("NodeVolumeLimits", ("filter",)),
    "MaxEBSVolumeCount": ("EBSLimits", ("filter",)),
    "MaxGCEPDVolumeCount": ("GCEPDLimits", ("filter",)),
    "MaxAzureDiskVolumeCount": ("AzureDiskLimits", ("filter",)),
    "MatchInterPodAffinity": (
        "InterPodAffinity", ("pre_filter", "filter"),
    ),
    "EvenPodsSpread": ("PodTopologySpread", ("pre_filter", "filter")),
    "CheckVolumeBinding": (
        "VolumeBinding", ("pre_filter", "filter", "reserve", "pre_bind"),
    ),
    "TestServiceAffinity": ("ServiceAffinity", ("pre_filter", "filter")),
    "CheckNodeLabelPresence": ("NodeLabel", ("filter",)),
}

# legacy priority name -> (plugin, extension points)
# (legacy_registry.go registeredPriorities)
PRIORITY_MAP = {
    "LeastRequestedPriority": (
        "NodeResourcesLeastAllocated", ("score",),
    ),
    "MostRequestedPriority": ("NodeResourcesMostAllocated", ("score",)),
    "BalancedResourceAllocation": (
        "NodeResourcesBalancedAllocation", ("score",),
    ),
    "SelectorSpreadPriority": ("SelectorSpread", ("pre_score", "score")),
    "ServiceSpreadingPriority": ("SelectorSpread", ("pre_score", "score")),
    "InterPodAffinityPriority": ("InterPodAffinity", ("pre_score", "score")),
    "NodeAffinityPriority": ("NodeAffinity", ("score",)),
    "TaintTolerationPriority": ("TaintToleration", ("pre_score", "score")),
    "ImageLocalityPriority": ("ImageLocality", ("score",)),
    "NodePreferAvoidPodsPriority": ("NodePreferAvoidPods", ("score",)),
    "RequestedToCapacityRatioPriority": (
        "RequestedToCapacityRatio", ("score",),
    ),
    "EvenPodsSpreadPriority": ("PodTopologySpread", ("pre_score", "score")),
}


class PolicyError(ValueError):
    pass


def policy_to_config(
    policy: Mapping[str, Any],
    feature_gates: Optional[Mapping[str, bool]] = None,
) -> KubeSchedulerConfiguration:
    """Translate a v1 Policy document (dict) into a
    KubeSchedulerConfiguration with one default profile."""
    if policy.get("kind") not in (None, "Policy"):
        raise PolicyError(f"not a Policy document: kind={policy.get('kind')!r}")
    plugins = Plugins()

    def enable(point: str, name: str, weight: int = 1) -> None:
        """Idempotent for non-score points; score weights ACCUMULATE when
        two legacy priorities map to one plugin (legacy_registry.go: e.g.
        SelectorSpreadPriority + ServiceSpreadingPriority both feed
        SelectorSpread, and createFromConfig sums their weights)."""
        pset = plugins.get(point)
        for e in pset.enabled:
            if e.name == name:
                if point == "score":
                    e.weight += weight
                return
        pset.enabled.append(PluginEntry(name, weight))

    # mandatory wiring createFromConfig always applies (factory.go:253-272)
    enable("queue_sort", "PrioritySort")
    enable("bind", "DefaultBinder")
    enable("post_filter", "DefaultPreemption")

    predicates = policy.get("predicates")
    if predicates is None:
        # nil predicate list -> provider defaults for the filter side
        # (factory.go:215-222 applies the default provider's set)
        for name, (plugin, points) in PREDICATE_MAP.items():
            if name in _DEFAULT_PREDICATES:
                for point in points:
                    enable(point, plugin)
    else:
        for entry in predicates:
            name = entry.get("name")
            if name not in PREDICATE_MAP:
                raise PolicyError(f"unknown predicate {name!r}")
            plugin, points = PREDICATE_MAP[name]
            for point in points:
                enable(point, plugin)

    priorities = policy.get("priorities")
    if priorities is None:
        for name, (plugin, points) in PRIORITY_MAP.items():
            if name in _DEFAULT_PRIORITIES:
                for point in points:
                    enable(point, plugin)
    else:
        for entry in priorities:
            name = entry.get("name")
            weight = int(entry["weight"]) if entry.get("weight") is not None \
                else 1
            if weight <= 0 or weight >= 2**63 - 1:
                # reference createFromConfig: "priority ... should have
                # a positive weight applied to it or it has overflown"
                # (Weight <= 0 || Weight >= framework.MaxTotalScore) —
                # do not silently coerce an explicit 0 to 1
                raise PolicyError(
                    f"priority {name!r} weight must be positive and "
                    f"must not overflow"
                )
            if name not in PRIORITY_MAP:
                raise PolicyError(f"unknown priority {name!r}")
            plugin, points = PRIORITY_MAP[name]
            for point in points:
                enable(point, plugin, weight if point == "score" else 1)

    plugin_config: List[PluginConfig] = []
    hard_weight = policy.get("hardPodAffinitySymmetricWeight")
    if hard_weight is not None:
        if not 0 <= int(hard_weight) <= 100:
            raise PolicyError(
                "hardPodAffinitySymmetricWeight must be in [0,100]")
        plugin_config.append(PluginConfig(
            "InterPodAffinity",
            {"hardPodAffinityWeight": int(hard_weight)},
        ))

    # a Policy REPLACES the provider defaults (createFromConfig builds
    # the plugin set from scratch): disable "*" so merge_defaults keeps
    # only the translated set
    from kubernetes_tpu.config.types import EXTENSION_POINTS

    for point in EXTENSION_POINTS:
        plugins.get(point).disabled.append(PluginEntry("*"))

    profile = KubeSchedulerProfile(
        scheduler_name="default-scheduler",
        plugins=plugins,
        plugin_config=plugin_config,
    )
    cfg = KubeSchedulerConfiguration(
        profiles=[profile],
        feature_gates=dict(feature_gates or {}),
    )
    if policy.get("extenders"):
        cfg.extenders = KubeSchedulerConfiguration.from_dict(
            {"extenders": policy["extenders"]}
        ).extenders
    return cfg


def load_policy(text: str, **kw) -> KubeSchedulerConfiguration:
    """Parse Policy JSON text (the --policy-config-file path,
    scheduler.go:241-262)."""
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise PolicyError(f"invalid Policy JSON: {e}") from e
    return policy_to_config(doc, **kw)


# the default provider's legacy-name sets (algorithmprovider
# defaults expressed in Policy vocabulary)
_DEFAULT_PREDICATES = {
    "PodFitsResources", "PodFitsHostPorts", "HostName",
    "MatchNodeSelector", "NoVolumeZoneConflict", "PodToleratesNodeTaints",
    "CheckNodeUnschedulable", "MaxCSIVolumeCountPred",
    "MatchInterPodAffinity", "EvenPodsSpread", "CheckVolumeBinding",
    "NoDiskConflict",
}
_DEFAULT_PRIORITIES = {
    "LeastRequestedPriority", "BalancedResourceAllocation",
    "SelectorSpreadPriority", "InterPodAffinityPriority",
    "NodeAffinityPriority", "TaintTolerationPriority",
    "ImageLocalityPriority", "NodePreferAvoidPodsPriority",
    "EvenPodsSpreadPriority",
}
