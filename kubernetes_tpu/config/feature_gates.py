"""Feature gates (reference ``pkg/features/kube_features.go`` — 95 gates
checked at use-sites). We carry the scheduler-relevant subset plus this
framework's own gates, notably ``TPUBatchScheduler`` (the north-star flag
that enables the device batch path with clean fallback)."""

from __future__ import annotations

from typing import Dict, Mapping


_DEFAULTS: Dict[str, bool] = {
    # scheduler-relevant upstream gates (reference kube_features.go)
    "PreferNominatedNode": False,
    "DefaultPodTopologySpread": False,
    "PodOverhead": True,
    "BalanceAttachedNodeVolumes": False,
    "VolumeCapacityPriority": False,
    "NonPreemptingPriority": True,
    # this framework's gates
    "TPUBatchScheduler": False,
    "TPUShardedSolver": False,
}


class FeatureGates:
    def __init__(self, overrides: Mapping[str, bool] = ()):
        self._gates = dict(_DEFAULTS)
        self._gates.update(dict(overrides or {}))

    def enabled(self, name: str) -> bool:
        return self._gates.get(name, False)

    def set(self, name: str, value: bool) -> None:
        self._gates[name] = value

    @classmethod
    def from_string(cls, s: str) -> "FeatureGates":
        """Parse ``--feature-gates=A=true,B=false`` syntax."""
        overrides = {}
        for part in (s or "").split(","):
            part = part.strip()
            if not part:
                continue
            k, _, v = part.partition("=")
            overrides[k.strip()] = v.strip().lower() in ("true", "1", "")
        return cls(overrides)


def default_feature_gates() -> FeatureGates:
    return FeatureGates()
