"""Kubemark: hollow nodes for cluster-scale testing without machines.

Behavioral equivalent of the reference's kubemark
(``pkg/kubemark/hollow_kubelet.go`` — a REAL kubelet against a fake CRI;
``hollow_proxy.go`` — a real proxier against a no-op dataplane;
``cmd/kubemark``): each hollow node runs the genuine node-agent code path
(sync loop, status manager, device manager) with the in-memory runtime, so
control-plane components — scheduler, controllers, node-lifecycle health
monitoring — see a full-size cluster that behaves like real nodes, at the
cost of one thread per node instead of one machine.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubelet import DeviceManager, DevicePlugin, FakeRuntime, Kubelet
from kubernetes_tpu.kubelet.devicemanager import TPU_RESOURCE
from kubernetes_tpu.proxy import Proxier


class HollowNode:
    """A real Kubelet + real Proxier over fake infrastructure."""

    def __init__(
        self,
        store: ClusterStore,
        name: str,
        capacity: Optional[Dict[str, str]] = None,
        tpu_chips: int = 0,
        labels: Optional[Dict[str, str]] = None,
        heartbeat_fn=None,
        pod_subnet: Optional[str] = None,
    ):
        dm = DeviceManager()
        if tpu_chips:
            dm.register(
                DevicePlugin(
                    TPU_RESOURCE,
                    [f"{name}-tpu{i}" for i in range(tpu_chips)],
                    topology={
                        f"{name}-tpu{i}": (i % 4, i // 4) for i in range(tpu_chips)
                    },
                )
            )
        # each node owns a distinct pod subnet (the node-ipam podCIDR
        # model) — without it pod IPs collide across nodes and Endpoints
        # silently dedupe
        self.kubelet = Kubelet(
            store,
            name,
            capacity=capacity,
            runtime=FakeRuntime(pod_ip_prefix=pod_subnet or "10.88.0."),
            device_manager=dm,
            labels=labels,
            heartbeat_fn=heartbeat_fn,
        )
        self.proxier = Proxier(store, node_name=name)

    def start(self) -> "HollowNode":
        self.kubelet.start()
        self.proxier.start()
        return self

    def stop(self) -> None:
        self.kubelet.stop()
        self.proxier.stop()

    @property
    def name(self) -> str:
        return self.kubelet.node_name


class HollowCluster:
    """N hollow nodes against one store — the single-box analog of the
    reference's 5k-node kubemark rigs (``test/kubemark/``)."""

    def __init__(self, store: ClusterStore, heartbeat_fn=None):
        self.store = store
        self.nodes: List[HollowNode] = []
        self._heartbeat_fn = heartbeat_fn

    def start_nodes(
        self,
        count: int,
        capacity: Optional[Dict[str, str]] = None,
        tpu_chips: int = 0,
        zone_count: int = 3,
        name_prefix: str = "hollow",
        share_proxier: bool = True,
    ) -> List[HollowNode]:
        """Spin up count hollow nodes spread over zone_count zones.
        share_proxier: at scale, one rule table per node is redundant in a
        single process — only node 0 runs a proxier."""
        started = []
        base = len(self.nodes)
        for i in range(count):
            # global index: a second start_nodes call must not re-register
            # the first batch's node names or reuse their pod subnets
            idx = base + i
            node = HollowNode(
                self.store,
                f"{name_prefix}-{idx}",
                capacity=capacity,
                tpu_chips=tpu_chips,
                labels={
                    "topology.kubernetes.io/zone": f"zone-{idx % zone_count}",
                    "kubernetes.io/hostname": f"{name_prefix}-{idx}",
                },
                heartbeat_fn=self._heartbeat_fn,
                pod_subnet=f"10.{88 + idx // 256}.{idx % 256}.",
            )
            node.kubelet.start()
            if not share_proxier or idx == 0:
                node.proxier.start()
            started.append(node)
        self.nodes.extend(started)
        return started

    def stop(self) -> None:
        for node in self.nodes:
            node.stop()  # Proxier.stop is already a no-op if never started
        self.nodes.clear()
