"""Kubemark: hollow nodes for cluster-scale testing without machines.

Behavioral equivalent of the reference's kubemark
(``pkg/kubemark/hollow_kubelet.go`` — a REAL kubelet against a fake CRI;
``hollow_proxy.go`` — a real proxier against a no-op dataplane;
``cmd/kubemark``): each hollow node runs the genuine node-agent code path
(sync loop, status manager, device manager) with the in-memory runtime, so
control-plane components — scheduler, controllers, node-lifecycle health
monitoring — see a full-size cluster that behaves like real nodes, at the
cost of one thread per node instead of one machine.

Two tiers, matching the reference's own split between hollow *kubelets*
and raw scale rigs:

- ``HollowNode``/``HollowCluster`` — full Kubelet per node. The
  ``store`` seam accepts either the in-process ``ClusterStore`` (the
  fast default for unit tests) or a ``RestClusterClient`` — hollow
  traffic then exercises authn, API Priority & Fairness, and the watch
  fabric exactly like real kubelets (node registration POSTs, lease
  renewals through the lease verb, status writes through
  pods/{name}/status).
- ``HollowFleet`` — the 10×-tier shape: N Node *objects* bulk-registered
  through the client plus ONE shared heartbeat thread renewing every
  node's lease, no per-node sync loops. 50k hollow kubelets as 50k
  Python threads would measure the GIL, not the control plane; the
  fleet keeps the API-visible behavior (registration, heartbeats,
  capacity) at O(1) threads. ``scheduler_perf`` semantics make this
  sound: a bound pod is a finished pod, so nothing needs to *run* it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubelet import DeviceManager, DevicePlugin, FakeRuntime, Kubelet
from kubernetes_tpu.kubelet.devicemanager import TPU_RESOURCE
from kubernetes_tpu.proxy import Proxier


def _store_is_local(store) -> bool:
    """In-process stores expose the provider registries; REST clients
    don't (and a proxier's rule table is meaningless over the wire)."""
    return hasattr(store, "register_log_source")


class HollowNode:
    """A real Kubelet + real Proxier over fake infrastructure. ``store``
    may be a ClusterStore (in-process) or a RestClusterClient (the
    kubemark-over-REST deployment; the proxier is skipped there)."""

    def __init__(
        self,
        store: ClusterStore,
        name: str,
        capacity: Optional[Dict[str, str]] = None,
        tpu_chips: int = 0,
        labels: Optional[Dict[str, str]] = None,
        heartbeat_fn=None,
        pod_subnet: Optional[str] = None,
    ):
        dm = DeviceManager()
        if tpu_chips:
            dm.register(
                DevicePlugin(
                    TPU_RESOURCE,
                    [f"{name}-tpu{i}" for i in range(tpu_chips)],
                    topology={
                        f"{name}-tpu{i}": (i % 4, i // 4) for i in range(tpu_chips)
                    },
                )
            )
        # each node owns a distinct pod subnet (the node-ipam podCIDR
        # model) — without it pod IPs collide across nodes and Endpoints
        # silently dedupe
        self.kubelet = Kubelet(
            store,
            name,
            capacity=capacity,
            runtime=FakeRuntime(pod_ip_prefix=pod_subnet or "10.88.0."),
            device_manager=dm,
            labels=labels,
            heartbeat_fn=heartbeat_fn,
        )
        self.proxier = Proxier(store, node_name=name) \
            if _store_is_local(store) else None

    def start(self) -> "HollowNode":
        self.kubelet.start()
        if self.proxier is not None:
            self.proxier.start()
        return self

    def stop(self) -> None:
        self.kubelet.stop()
        if self.proxier is not None:
            self.proxier.stop()

    @property
    def name(self) -> str:
        return self.kubelet.node_name


class HollowCluster:
    """N hollow nodes against one store — the single-box analog of the
    reference's 5k-node kubemark rigs (``test/kubemark/``)."""

    def __init__(self, store: ClusterStore, heartbeat_fn=None):
        self.store = store
        self.nodes: List[HollowNode] = []
        self._heartbeat_fn = heartbeat_fn

    def start_nodes(
        self,
        count: int,
        capacity: Optional[Dict[str, str]] = None,
        tpu_chips: int = 0,
        zone_count: int = 3,
        name_prefix: str = "hollow",
        share_proxier: bool = True,
    ) -> List[HollowNode]:
        """Spin up count hollow nodes spread over zone_count zones.
        share_proxier: at scale, one rule table per node is redundant in a
        single process — only node 0 runs a proxier."""
        started = []
        base = len(self.nodes)
        for i in range(count):
            # global index: a second start_nodes call must not re-register
            # the first batch's node names or reuse their pod subnets
            idx = base + i
            node = HollowNode(
                self.store,
                f"{name_prefix}-{idx}",
                capacity=capacity,
                tpu_chips=tpu_chips,
                labels={
                    "topology.kubernetes.io/zone": f"zone-{idx % zone_count}",
                    "kubernetes.io/hostname": f"{name_prefix}-{idx}",
                },
                heartbeat_fn=self._heartbeat_fn,
                pod_subnet=f"10.{88 + idx // 256}.{idx % 256}.",
            )
            node.kubelet.start()
            if node.proxier is not None and (not share_proxier or idx == 0):
                node.proxier.start()
            started.append(node)
        self.nodes.extend(started)
        return started

    def stop(self) -> None:
        for node in self.nodes:
            node.stop()  # Proxier.stop is already a no-op if never started
        self.nodes.clear()


class HollowFleet:
    """The 10×-tier kubemark shape: N hollow Node objects registered in
    bulk through a (usually partition-aware REST) client, kept alive by
    ONE shared heartbeat thread renewing ``node-<name>`` leases in
    round-robin slices — the ``HeartbeatPump`` idea carried over the
    fabric. No kubelet sync loops: at 50k nodes those threads would
    measure the GIL, not the control plane."""

    def __init__(self, client, interval: float = 30.0,
                 lease_duration: float = 120.0,
                 beats_per_tick: Optional[int] = None):
        self.client = client
        self.interval = float(interval)
        self.lease_duration = float(lease_duration)
        # lease writes per tick. None (the default) auto-sizes so a
        # full rotation completes within HALF the lease duration — the
        # rotating slice de-synchronizes the herd, but a slice too
        # small to lap the fleet before leases expire would leave most
        # of a 50k-node tier perpetually NotReady (renewal rate must be
        # >= fleet_size / (lease_duration/2), not a fixed trickle)
        self.beats_per_tick = int(beats_per_tick) \
            if beats_per_tick is not None else None
        self.node_names: List[str] = []
        self._cursor = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, count: int, cpu: str = "32", memory: str = "128Gi",
                 pods: str = "256", name_prefix: str = "hollow",
                 zone_count: int = 8, chunk: int = 2000,
                 progress=None) -> List[str]:
        """Bulk-create ``count`` Node objects (NodeList POSTs of
        ``chunk``, fanned out per partition by the client) and adopt
        them into the heartbeat rotation."""
        from kubernetes_tpu.testing.wrappers import MakeNode

        base = len(self.node_names)
        nodes = []
        names = []
        for i in range(count):
            idx = base + i
            name = f"{name_prefix}-{idx}"
            builder = MakeNode().name(name).capacity(
                {"cpu": cpu, "memory": memory, "pods": pods})
            builder = builder.label("topology.kubernetes.io/zone",
                                    f"zone-{idx % zone_count}")
            builder = builder.label("kubernetes.io/hostname", name)
            nodes.append(builder.obj())
            names.append(name)
            if len(nodes) >= chunk:
                self.client.create_objects_bulk("Node", nodes)
                nodes = []
                if progress:
                    progress(f"hollow fleet: {idx + 1}/{count} registered")
        if nodes:
            self.client.create_objects_bulk("Node", nodes)
        self.node_names.extend(names)
        return names

    def start(self) -> "HollowFleet":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hollow-fleet-heartbeats")
        self._thread.start()
        return self

    def _slice_size(self, fleet: int) -> int:
        if self.beats_per_tick is not None:
            return min(self.beats_per_tick, fleet)
        # cover the whole fleet at least twice per lease lifetime
        import math

        need = math.ceil(fleet * self.interval
                         / max(self.lease_duration / 2.0, self.interval))
        return min(max(need, 1), fleet)

    def beat_slice(self) -> int:
        """Renew the next slice of node leases; returns how many."""
        names = self.node_names
        if not names:
            return 0
        n = self._slice_size(len(names))
        renew = getattr(self.client, "try_acquire_or_renew", None)
        if renew is None:
            return 0
        beaten = 0
        for _ in range(n):
            name = names[self._cursor % len(names)]
            self._cursor += 1
            try:
                renew(f"node-{name}", name, time.time(),
                      self.lease_duration)
                beaten += 1
            except Exception:  # noqa: BLE001 — a failed beat is a
                # missed heartbeat, exactly what it would be for a real
                # kubelet; the next rotation retries
                if self._stop.is_set():
                    break
        return beaten

    def _loop(self) -> None:
        # first beat immediately (HeartbeatPump.start does the same):
        # a fleet that waits a full interval before its first renewal
        # starts life with every lease expired
        self.beat_slice()
        while not self._stop.wait(self.interval):
            self.beat_slice()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
