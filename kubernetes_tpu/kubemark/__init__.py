from kubernetes_tpu.kubemark.hollow import HollowCluster, HollowFleet, HollowNode
