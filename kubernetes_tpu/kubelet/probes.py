"""Liveness/readiness probing.

Behavioral equivalent of the reference's prober subsystem
(``pkg/kubelet/prober/prober_manager.go`` + ``worker.go``): one worker per
(pod, container, probe-type), periodic probe with initial delay and
failure/success thresholds; readiness results feed the pod's Ready
condition, liveness failures tell the kubelet to restart the container.
Probes here are callables (the fake-CRI analog of exec/http/tcp handlers).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

LIVENESS, READINESS = "liveness", "readiness"
SUCCESS, FAILURE = "success", "failure"


@dataclass
class ProbeSpec:
    probe: Callable[[], bool]
    period: float = 1.0
    initial_delay: float = 0.0
    failure_threshold: int = 3
    success_threshold: int = 1


@dataclass
class _WorkerState:
    result: str = SUCCESS
    consecutive_failures: int = 0
    consecutive_successes: int = 0


class ProbeManager:
    """Synchronous-tick design: the kubelet's sync loop calls ``tick()``;
    deterministic under test clocks, no per-probe threads (the reference
    uses goroutine workers; a tick loop is the idiomatic single-threaded
    recast)."""

    def __init__(self, clock=None):
        from kubernetes_tpu.utils.clock import RealClock

        self._clock = clock or RealClock()
        self._lock = threading.Lock()
        # (pod_uid, container, kind) -> (spec, state, registered_at, last_run)
        self._workers: Dict[Tuple[str, str, str], list] = {}

    def add(self, pod_uid: str, container: str, kind: str, spec: ProbeSpec) -> None:
        with self._lock:
            self._workers[(pod_uid, container, kind)] = [
                spec, _WorkerState(), self._clock.now(), None
            ]

    def remove_pod(self, pod_uid: str) -> None:
        with self._lock:
            for k in [k for k in self._workers if k[0] == pod_uid]:
                del self._workers[k]

    def tick(self) -> None:
        """Run every due probe once; updates results by thresholds."""
        now = self._clock.now()
        with self._lock:
            due = []
            for key, rec in self._workers.items():
                spec, state, registered, last = rec
                if now - registered < spec.initial_delay:
                    continue
                if last is not None and now - last < spec.period:
                    continue
                rec[3] = now
                due.append((key, spec, state))
        for key, spec, state in due:
            try:
                ok = bool(spec.probe())
            except Exception:
                ok = False
            if ok:
                state.consecutive_successes += 1
                state.consecutive_failures = 0
                if state.consecutive_successes >= spec.success_threshold:
                    state.result = SUCCESS
            else:
                state.consecutive_failures += 1
                state.consecutive_successes = 0
                if state.consecutive_failures >= spec.failure_threshold:
                    state.result = FAILURE

    def result(self, pod_uid: str, container: str, kind: str) -> Optional[str]:
        with self._lock:
            rec = self._workers.get((pod_uid, container, kind))
            return rec[1].result if rec else None

    def pod_ready(self, pod_uid: str) -> bool:
        """All readiness probes of the pod pass (no probes → ready)."""
        with self._lock:
            for (uid, _c, kind), rec in self._workers.items():
                if uid == pod_uid and kind == READINESS and rec[1].result != SUCCESS:
                    return False
            return True

    def liveness_failed(self, pod_uid: str) -> Dict[str, bool]:
        """container -> liveness currently failing."""
        with self._lock:
            return {
                c: rec[1].result == FAILURE
                for (uid, c, kind), rec in self._workers.items()
                if uid == pod_uid and kind == LIVENESS
            }
