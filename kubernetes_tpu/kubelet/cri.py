"""Container Runtime Interface — the kubelet⇄runtime boundary.

Behavioral equivalent of the reference's CRI
(``staging/src/k8s.io/cri-api/pkg/apis/services.go``: RuntimeService /
ImageService over gRPC): pod sandboxes and containers with an explicit
state machine (CREATED → RUNNING → EXITED), plus an image store. The
in-process ``FakeRuntime`` is the moral twin of the hollow kubelet's fake
CRI (``pkg/kubemark/hollow_kubelet.go``) — full lifecycle bookkeeping, no
actual processes — which is exactly what scale testing needs; a real
runtime would implement the same ``RuntimeService`` surface.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# container states (CRI ContainerState enum)
CREATED, RUNNING, EXITED, UNKNOWN = "CREATED", "RUNNING", "EXITED", "UNKNOWN"
# sandbox states
SANDBOX_READY, SANDBOX_NOTREADY = "SANDBOX_READY", "SANDBOX_NOTREADY"

_id_counter = itertools.count(1)


@dataclass
class PodSandbox:
    id: str
    pod_uid: str
    name: str
    namespace: str
    state: str = SANDBOX_READY
    created_at: float = field(default_factory=time.time)
    ip: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class ContainerStatus:
    id: str
    sandbox_id: str
    name: str
    image: str
    state: str = CREATED
    exit_code: Optional[int] = None
    started_at: float = 0.0
    finished_at: float = 0.0
    restarts: int = 0


class RuntimeService:
    """The CRI surface the kubelet drives (subset with the lifecycle verbs
    the sync loop needs)."""

    # sandboxes
    def run_pod_sandbox(self, pod_uid: str, name: str, namespace: str,
                        labels: Optional[Dict[str, str]] = None) -> str:
        raise NotImplementedError

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        raise NotImplementedError

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        raise NotImplementedError

    def list_pod_sandboxes(self) -> List[PodSandbox]:
        raise NotImplementedError

    # containers
    def create_container(self, sandbox_id: str, name: str, image: str) -> str:
        raise NotImplementedError

    def start_container(self, container_id: str) -> None:
        raise NotImplementedError

    def stop_container(self, container_id: str, timeout_s: float = 30.0) -> None:
        raise NotImplementedError

    def remove_container(self, container_id: str) -> None:
        raise NotImplementedError

    def list_containers(self, sandbox_id: Optional[str] = None) -> List[ContainerStatus]:
        raise NotImplementedError

    def container_status(self, container_id: str) -> Optional[ContainerStatus]:
        raise NotImplementedError

    def exec_sync(self, container_id: str, payload) -> int:
        """Run a lifecycle hook / probe command in the container
        (CRI ExecSync); returns the exit code."""
        raise NotImplementedError

    def container_logs(self, container_id: str) -> List[str]:
        """The container's log lines (the kubelet serves these through
        the pods/log subresource)."""
        raise NotImplementedError


class ImageService:
    def pull_image(self, image: str) -> None:
        raise NotImplementedError

    def list_images(self) -> List[str]:
        raise NotImplementedError


class FakeRuntime(RuntimeService, ImageService):
    """In-memory CRI with correct state-machine bookkeeping.

    ``exit_after``: image name → seconds until the container exits 0
    (models batch workloads); containers of other images run until
    stopped. ``fail_images``: images whose containers exit nonzero
    immediately after start (models crash loops).
    """

    def __init__(self, exit_after: Optional[Dict[str, float]] = None,
                 fail_images: Optional[set] = None,
                 pod_ip_prefix: str = "10.88.0."):
        self._lock = threading.RLock()
        self._sandboxes: Dict[str, PodSandbox] = {}
        self._containers: Dict[str, ContainerStatus] = {}
        self._images: set = set()
        self.exit_after = dict(exit_after or {})
        self.fail_images = set(fail_images or ())
        self._ip_prefix = pod_ip_prefix
        self._ip_counter = itertools.count(2)
        # ExecSync record: (container id, payload) per lifecycle
        # hook/probe invocation — the observable the hook tests assert
        self.exec_records: List[tuple] = []
        # synthetic per-container log streams (kubectl logs parity):
        # lifecycle transitions append lines like a real runtime's
        # stdout capture
        self._logs: Dict[str, List[str]] = {}

    # -- sandboxes -----------------------------------------------------
    def run_pod_sandbox(self, pod_uid, name, namespace, labels=None) -> str:
        with self._lock:
            sid = f"sb-{next(_id_counter)}"
            self._sandboxes[sid] = PodSandbox(
                id=sid, pod_uid=pod_uid, name=name, namespace=namespace,
                ip=f"{self._ip_prefix}{next(self._ip_counter)}",
                labels=dict(labels or {}),
            )
            return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            if sb is None:
                return
            sb.state = SANDBOX_NOTREADY
            for c in self._containers.values():
                if c.sandbox_id == sandbox_id and c.state == RUNNING:
                    c.state = EXITED
                    c.exit_code = 137
                    c.finished_at = time.time()

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            if sb is not None and sb.state == SANDBOX_READY:
                raise RuntimeError(f"sandbox {sandbox_id} is still ready; stop first")
            self._sandboxes.pop(sandbox_id, None)
            self._containers = {
                cid: c for cid, c in self._containers.items()
                if c.sandbox_id != sandbox_id
            }

    def list_pod_sandboxes(self) -> List[PodSandbox]:
        with self._lock:
            return list(self._sandboxes.values())

    def sandbox_ip(self, sandbox_id: str) -> str:
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            return sb.ip if sb else ""

    # -- containers ----------------------------------------------------
    def create_container(self, sandbox_id: str, name: str, image: str) -> str:
        with self._lock:
            if sandbox_id not in self._sandboxes:
                raise KeyError(f"no sandbox {sandbox_id}")
            self.pull_image(image)
            cid = f"c-{next(_id_counter)}"
            self._containers[cid] = ContainerStatus(
                id=cid, sandbox_id=sandbox_id, name=name, image=image
            )
            return cid

    def _log(self, container_id: str, line: str) -> None:
        self._logs.setdefault(container_id, []).append(
            f"{time.strftime('%Y-%m-%dT%H:%M:%S')} {line}"
        )

    def start_container(self, container_id: str) -> None:
        with self._lock:
            c = self._require(container_id)
            if c.state not in (CREATED, EXITED):
                raise RuntimeError(f"container {container_id} is {c.state}")
            if c.state == EXITED:
                c.restarts += 1
            c.state = RUNNING
            c.started_at = time.time()
            c.exit_code = None
            self._log(container_id,
                      f"container started image={c.image} "
                      f"restarts={c.restarts}")
            if c.image in self.fail_images:
                c.state = EXITED
                c.exit_code = 1
                c.finished_at = time.time()
                self._log(container_id, "container exited code=1")

    def stop_container(self, container_id: str, timeout_s: float = 30.0) -> None:
        with self._lock:
            c = self._containers.get(container_id)
            if c is None or c.state != RUNNING:
                return
            c.state = EXITED
            c.exit_code = 137
            c.finished_at = time.time()
            self._log(container_id,
                      f"container stopped (grace {timeout_s:g}s) code=137")

    def remove_container(self, container_id: str) -> None:
        with self._lock:
            c = self._containers.get(container_id)
            if c is not None and c.state == RUNNING:
                raise RuntimeError(f"container {container_id} is running")
            self._containers.pop(container_id, None)

    def list_containers(self, sandbox_id=None) -> List[ContainerStatus]:
        with self._lock:
            self._tick()
            return [
                c for c in self._containers.values()
                if sandbox_id is None or c.sandbox_id == sandbox_id
            ]

    def container_status(self, container_id: str) -> Optional[ContainerStatus]:
        with self._lock:
            self._tick()
            return self._containers.get(container_id)

    def _require(self, container_id: str) -> ContainerStatus:
        c = self._containers.get(container_id)
        if c is None:
            raise KeyError(f"no container {container_id}")
        return c

    def _tick(self) -> None:
        """Advance modeled batch containers to EXITED(0) past their
        deadline."""
        now = time.time()
        for c in self._containers.values():
            if c.state != RUNNING:
                continue
            ttl = self.exit_after.get(c.image)
            if ttl is not None and now - c.started_at >= ttl:
                c.state = EXITED
                c.exit_code = 0
                c.finished_at = now

    def exec_sync(self, container_id: str, payload) -> int:
        with self._lock:
            c = self._containers.get(container_id)
            if c is None or c.state != RUNNING:
                return 1   # nothing to exec into
            self.exec_records.append((container_id, payload))
            self._log(container_id, f"exec: {payload!r}")
            return 0

    def container_logs(self, container_id: str) -> List[str]:
        with self._lock:
            return list(self._logs.get(container_id, ()))

    def serve_port(self, sandbox_id: str, port: int,
                   data: bytes) -> bytes:
        """Fake application endpoint for port-forward: a deterministic
        echo naming the sandbox and port (the reference forwards to the
        real container socket; the fake CRI answers for it)."""
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            if sb is None:
                raise LookupError(f"sandbox {sandbox_id!r} not found")
            name = sb.name
        return (f"pod {name} port {port} echo: ".encode() + data)

    # -- images --------------------------------------------------------
    def pull_image(self, image: str) -> None:
        with self._lock:
            self._images.add(image)

    def list_images(self) -> List[str]:
        with self._lock:
            return sorted(self._images)
