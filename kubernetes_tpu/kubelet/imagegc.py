"""Image garbage collector.

Behavioral equivalent of the reference's
``pkg/kubelet/images/image_gc_manager.go`` (realImageGCManager.GarbageCollect):
when image-disk usage crosses ``high_threshold_percent`` of capacity,
delete least-recently-used images not referenced by any pod on the node
until usage falls to ``low_threshold_percent``. Last-used times come
from pod sightings (``note_image_used``, the analog of detectImages'
imagesInUse scan); freed images leave ``node.status.images`` so the
scheduler's ImageLocality scoring sees the real cache state.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from kubernetes_tpu.api.types import ContainerImage


class ImageGCManager:
    # reference --image-gc-period is 5m; scaled for the harness
    GC_INTERVAL_SECONDS = 5.0
    FREED_LOG_CAP = 1024

    def __init__(self, store, node_name: str, capacity_bytes: int,
                 high_threshold_percent: int = 85,
                 low_threshold_percent: int = 80):
        self.store = store
        self.node_name = node_name
        self.capacity = capacity_bytes
        self.high = high_threshold_percent
        self.low = low_threshold_percent
        self._lock = threading.Lock()
        self._last_used: Dict[str, float] = {}   # image name -> ts
        self.freed: List[str] = []               # observability (capped)
        self._last_gc = 0.0

    def maybe_garbage_collect(self) -> List[str]:
        """Housekeeping entry point: rate-limits full passes to
        ``GC_INTERVAL_SECONDS`` (the kubelet tick is much hotter)."""
        now = time.time()
        if now - self._last_gc < self.GC_INTERVAL_SECONDS:
            return []
        self._last_gc = now
        return self.garbage_collect()

    # ------------------------------------------------------------------
    def note_image_used(self, image: str) -> None:
        """Pod sighting: refresh the image's last-used time
        (detectImages' imagesInUse accounting)."""
        with self._lock:
            self._last_used[image] = time.time()

    def _images_in_use(self) -> set:
        used = set()
        for p in self.store.list_pods():
            if p.spec.node_name != self.node_name:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            for c in p.spec.containers:
                if c.image:
                    used.add(c.image)
        return used

    def garbage_collect(self) -> List[str]:
        """One GC pass; returns the freed image names."""
        node = self.store.get_node(self.node_name)
        if node is None:
            return []
        images = list(node.status.images)
        # prune usage records for images no longer on the node (the
        # reference's detectImages drops absent records) — unbounded
        # growth otherwise, one entry per image name ever seen
        present = {n for i in images for n in i.names}
        with self._lock:
            self._last_used = {
                k: v for k, v in self._last_used.items() if k in present
            }
        usage = sum(i.size_bytes for i in images)
        if self.capacity <= 0 or \
                usage * 100 < self.high * self.capacity:
            return []
        target = self.low * self.capacity // 100
        in_use = self._images_in_use()
        with self._lock:
            def last_used(img: ContainerImage) -> float:
                return max(
                    (self._last_used.get(n, 0.0) for n in img.names),
                    default=0.0,
                )

            candidates = sorted(
                (i for i in images
                 if not any(n in in_use for n in i.names)),
                key=last_used,
            )
        freed: List[str] = []
        keep = list(images)
        for img in candidates:
            if usage <= target:
                break
            keep.remove(img)
            usage -= img.size_bytes
            freed.extend(img.names[:1])
        if not freed:
            return []
        freed_names = {n for i in images if i not in keep for n in i.names}

        def mutate(n) -> bool:
            # CAS merge against the LIVE image list: another node-status
            # writer (attachdetach, eviction) may have landed since the
            # read above, and blind last-write-wins would resurrect
            # their fields or our freed images
            n.status.images = [
                i for i in n.status.images
                if not any(name in freed_names for name in i.names)
            ]
            return True

        self.store.mutate_object("Node", "", self.node_name, mutate)
        self.freed = (self.freed + freed)[-self.FREED_LOG_CAP:]
        return freed
