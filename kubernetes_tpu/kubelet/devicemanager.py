"""Device manager: extended-resource allocation with checkpointing.

Behavioral equivalent of the reference's kubelet device-plugin manager
(``pkg/kubelet/cm/devicemanager/manager.go``): device plugins register a
resource name (here the canonical one is ``google.com/tpu`` rather than
``nvidia.com/gpu``) with a set of device IDs; the manager allocates
concrete IDs to containers at pod admission, reports
capacity/allocatable up to the node status, and checkpoints assignments
(``cm/devicemanager/checkpoint/checkpoint.go``) so a kubelet restart
doesn't double-allocate.

TPU-native twist: a plugin can expose a device *topology* (the chip's
position in the pod slice) so allocations prefer ICI-contiguous chips —
the analog of the reference's NUMA-aware TopologyManager hints
(``pkg/kubelet/cm/topologymanager``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.kubelet.checkpoint import CheckpointManager

TPU_RESOURCE = "google.com/tpu"


@dataclass
class DevicePlugin:
    """A registered plugin: resource name + healthy device IDs, with an
    optional (x, y) mesh coordinate per device for topology-aware
    allocation."""

    resource: str
    device_ids: List[str]
    topology: Dict[str, Tuple[int, int]] = field(default_factory=dict)


class DeviceAllocationError(Exception):
    pass


class DeviceManager:
    CHECKPOINT = "device_manager_state"

    def __init__(self, checkpoints: Optional[CheckpointManager] = None):
        self._lock = threading.Lock()
        self._plugins: Dict[str, DevicePlugin] = {}
        # resource -> {device_id -> "pod_uid/container"}
        self._allocated: Dict[str, Dict[str, str]] = {}
        self._checkpoints = checkpoints
        if checkpoints is not None:
            state = checkpoints.get(self.CHECKPOINT)
            if state:
                self._allocated = {
                    res: dict(assign) for res, assign in state.items()
                }

    # -- plugin registration -------------------------------------------
    def register(self, plugin: DevicePlugin) -> None:
        with self._lock:
            self._plugins[plugin.resource] = plugin
            self._allocated.setdefault(plugin.resource, {})
            # drop assignments for devices the plugin no longer reports
            live = set(plugin.device_ids)
            self._allocated[plugin.resource] = {
                d: owner
                for d, owner in self._allocated[plugin.resource].items()
                if d in live
            }
            self._save()

    def capacity(self) -> Dict[str, int]:
        with self._lock:
            return {r: len(p.device_ids) for r, p in self._plugins.items()}

    def allocatable(self) -> Dict[str, int]:
        with self._lock:
            return {
                r: len(p.device_ids) - len(self._allocated.get(r, {}))
                for r, p in self._plugins.items()
            }

    # -- allocation ----------------------------------------------------
    def allocate(self, pod_uid: str, container: str, resource: str,
                 count: int) -> List[str]:
        """Pick count free devices (topology-contiguous when the plugin
        reports coordinates), record + checkpoint the assignment."""
        with self._lock:
            plugin = self._plugins.get(resource)
            if plugin is None:
                raise DeviceAllocationError(f"no device plugin for {resource!r}")
            taken = self._allocated.setdefault(resource, {})
            free = [d for d in plugin.device_ids if d not in taken]
            if len(free) < count:
                raise DeviceAllocationError(
                    f"{resource}: want {count}, have {len(free)} free"
                )
            chosen = self._pick_contiguous(free, plugin.topology, count)
            owner = f"{pod_uid}/{container}"
            for d in chosen:
                taken[d] = owner
            self._save()
            return chosen

    @staticmethod
    def _pick_contiguous(free: Sequence[str],
                         topo: Dict[str, Tuple[int, int]],
                         count: int) -> List[str]:
        if not topo:
            return list(free[:count])
        # greedy nearest-neighbor walk over mesh coordinates: start at the
        # lexicographically smallest free coordinate, then repeatedly take
        # the free device closest (L1) to the chosen set — keeps multi-chip
        # allocations ICI-adjacent without solving full rectangle packing
        coords = {d: topo.get(d, (1 << 30, 1 << 30)) for d in free}
        remaining = sorted(free, key=lambda d: coords[d])
        chosen = [remaining.pop(0)]
        while len(chosen) < count:
            cx = [coords[d] for d in chosen]

            def dist(d):
                x, y = coords[d]
                return min(abs(x - a) + abs(y - b) for a, b in cx)

            nxt = min(remaining, key=dist)
            remaining.remove(nxt)
            chosen.append(nxt)
        return chosen

    def free(self, pod_uid: str) -> None:
        """Release every device held by the pod (pod deletion path)."""
        with self._lock:
            prefix = f"{pod_uid}/"
            for assign in self._allocated.values():
                for d in [d for d, o in assign.items() if o.startswith(prefix)]:
                    del assign[d]
            self._save()

    def devices_of(self, pod_uid: str) -> Dict[str, List[str]]:
        with self._lock:
            prefix = f"{pod_uid}/"
            out: Dict[str, List[str]] = {}
            for res, assign in self._allocated.items():
                ids = [d for d, o in assign.items() if o.startswith(prefix)]
                if ids:
                    out[res] = sorted(ids)
            return out

    def _save(self) -> None:
        if self._checkpoints is not None:
            self._checkpoints.create(self.CHECKPOINT, self._allocated)
