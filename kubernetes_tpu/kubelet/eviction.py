"""Node-pressure eviction manager.

Behavioral equivalent of the reference's ``pkg/kubelet/eviction``
(eviction_manager.go synchronize): observe node-local resource signals
through a stats provider, compare against configured thresholds, and
under pressure (a) publish the pressure node condition + its NoSchedule
taint so the scheduler steers away, (b) rank the node's pods by the
reference's eviction order — pods exceeding their requests first, then
by priority, then by usage — and evict one pod per pass until the
signal clears (evictPod + annotations, one victim per synchronize).

The stats provider is pluggable; the default ``CgroupStatsStub`` sums
the node's pod REQUESTS as "usage" so the harness (no real kernel)
exercises the full pipeline deterministically.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import PodCondition, Taint, shallow_copy
from kubernetes_tpu.scheduler.types import compute_pod_resource_request

MEMORY_PRESSURE = "MemoryPressure"
DISK_PRESSURE = "DiskPressure"
MEMORY_PRESSURE_TAINT = "node.kubernetes.io/memory-pressure"

# signal name -> node condition (eviction/api/types.go signals)
SIGNAL_MEMORY_AVAILABLE = "memory.available"


class CgroupStatsStub:
    """Deterministic stats provider: usage = sum of pod memory requests
    (a real node would read cgroup/cadvisor summaries)."""

    def __init__(self, store, node_name: str, capacity_bytes: int):
        self.store = store
        self.node_name = node_name
        self.capacity = capacity_bytes

    def memory_available(self) -> int:
        used = 0
        for p in self.store.list_pods():
            if p.spec.node_name != self.node_name:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            used += compute_pod_resource_request(p).memory
        return max(0, self.capacity - used)


class EvictionManager:
    def __init__(
        self,
        store,
        node_name: str,
        thresholds: Optional[Dict[str, str]] = None,
        stats: Optional[object] = None,
        recorder=None,
    ):
        self.store = store
        self.node_name = node_name
        raw = dict(thresholds or {SIGNAL_MEMORY_AVAILABLE: "100Mi"})
        self.thresholds = {
            k: int(parse_quantity(v).value()) for k, v in raw.items()
        }
        self.stats = stats
        self.recorder = recorder
        self._lock = threading.Lock()
        self.evicted: List[str] = []  # pod keys, observability

    # ------------------------------------------------------------------
    def synchronize(self) -> Optional[str]:
        """One pass (eviction_manager.go:231 synchronize): returns the
        evicted pod's key, or None when no eviction was needed."""
        threshold = self.thresholds.get(SIGNAL_MEMORY_AVAILABLE)
        if threshold is None or self.stats is None:
            return None
        available = self.stats.memory_available()
        under_pressure = available < threshold
        self._set_pressure(under_pressure)
        if not under_pressure:
            return None
        victims = self._rank_pods()
        for pod in victims:
            key = f"{pod.namespace}/{pod.name}"
            message = (
                "The node was low on resource: memory. "
                f"Threshold quantity: {threshold}, available: {available}"
            )
            if self.recorder is not None:
                self.recorder.eventf(pod, "Warning", "Evicted", "%s",
                                     message)

            # the reference's evictPod marks the pod Failed with
            # reason=Evicted rather than deleting it — the object stays
            # observable for workload controllers/operators; podgc or
            # the owner cleans it up later (eviction_manager.go
            # evictPod -> killPod, status_manager terminal phase)
            def mark(p):
                p.status.phase = "Failed"
                p.status.reason = "Evicted"
                p.status.message = message

            self.store.mutate_object("Pod", pod.namespace, pod.name, mark)
            with self._lock:
                self.evicted.append(key)
            return key  # one victim per pass, then re-observe
        return None

    def _rank_pods(self) -> List:
        """Eviction order (eviction/helpers.go rankMemoryPressure):
        usage-over-request first, then ascending priority, then largest
        usage. Per-pod usage comes from the stats provider's optional
        ``pod_memory_usage(pod)``; providers without it (the cgroup
        stub) fall back to usage = request, collapsing the order to
        priority-then-largest-request."""
        pods = [
            p for p in self.store.list_pods()
            if p.spec.node_name == self.node_name
            and p.status.phase not in ("Succeeded", "Failed")
            # already deletion-marked (e.g. waiting on a finalizer):
            # re-"evicting" it every pass would livelock while the
            # second-ranked pod never gets evicted
            and p.metadata.deletion_timestamp is None
        ]
        usage_fn = getattr(self.stats, "pod_memory_usage", None)

        def key(p):
            req = compute_pod_resource_request(p).memory
            usage = usage_fn(p) if usage_fn is not None else req
            over = usage > req
            return (not over, p.priority(), -usage)

        pods.sort(key=key)
        return pods

    # ------------------------------------------------------------------
    def _set_pressure(self, under: bool) -> None:
        def mutate(n) -> bool:
            have = any(
                c.type == MEMORY_PRESSURE and c.status == "True"
                for c in n.status.conditions
            )
            if have == under:
                return False
            n.status.conditions = [
                c for c in n.status.conditions
                if c.type != MEMORY_PRESSURE
            ] + [PodCondition(
                MEMORY_PRESSURE,
                "True" if under else "False",
                "KubeletHasInsufficientMemory" if under
                else "KubeletHasSufficientMemory",
            )]
            n.spec = shallow_copy(n.spec)
            taints = [t for t in n.spec.taints
                      if t.key != MEMORY_PRESSURE_TAINT]
            if under:
                taints.append(Taint(key=MEMORY_PRESSURE_TAINT,
                                    effect="NoSchedule"))
            n.spec.taints = taints
            return True

        # CAS mutate: other node-status writers (attachdetach, image
        # GC) must not be clobbered by a stale read
        self.store.mutate_object("Node", "", self.node_name, mutate)
