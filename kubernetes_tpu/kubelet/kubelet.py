"""Kubelet: the node agent.

Behavioral equivalent of the reference's kubelet core
(``pkg/kubelet/kubelet.go:1837 syncLoop`` → ``:1911 syncLoopIteration``):
register the node, heartbeat its lease, watch pods bound to this node, and
reconcile each pod against the container runtime through CRI — sandbox up,
containers created/started, restarts per policy, probes driving readiness
and liveness restarts, status written back through the pod status
subresource. Subsystems mirrored: pod workers (``pod_workers.go``), status
manager (``status/status_manager.go``), prober manager, volume manager
(mount bookkeeping — ``volumemanager/volume_manager.go``), device manager
with checkpointed allocations, and a checkpoint manager for local state.

There are no real containers behind ``FakeRuntime`` — matching the hollow
kubelet used for scale tests (``pkg/kubemark/hollow_kubelet.go``); any real
runtime plugs in via the same ``RuntimeService``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import (
    FAILED,
    RUNNING,
    SUCCEEDED,
    Node,
    Pod,
    PodCondition,
)
from kubernetes_tpu.apiserver.store import ADDED, DELETED, MODIFIED, ClusterStore, Event
from kubernetes_tpu.kubelet.cri import (
    EXITED,
    RUNNING as CRI_RUNNING,
    FakeRuntime,
    RuntimeService,
)
from kubernetes_tpu.kubelet.devicemanager import DeviceManager, TPU_RESOURCE
from kubernetes_tpu.kubelet.probes import LIVENESS, ProbeManager
from kubernetes_tpu.testing.wrappers import MakeNode

_logger = logging.getLogger(__name__)


class Kubelet:
    sync_interval = 0.2  # housekeeping tick (reference 1s; scaled down)

    def __init__(
        self,
        store: ClusterStore,
        node_name: str,
        capacity: Optional[Dict[str, str]] = None,
        runtime: Optional[RuntimeService] = None,
        device_manager: Optional[DeviceManager] = None,
        labels: Optional[Dict[str, str]] = None,
        heartbeat_fn=None,
        static_pod_manifests: Optional[List[dict]] = None,
    ):
        self.store = store
        self.node_name = node_name
        self.capacity = dict(capacity or {"cpu": "8", "memory": "16Gi", "pods": "110"})
        self.labels = dict(labels or {})
        self.runtime = runtime if runtime is not None else FakeRuntime()
        self.devices = device_manager or DeviceManager()
        # volume manager: desired/actual-state-of-world reconciler
        # (reference volumemanager/volume_manager.go:247); container
        # start gates on its WaitForAttachAndMount analog
        from kubernetes_tpu.kubelet.volumemanager import VolumeManager

        self.volumes = VolumeManager(store, node_name)
        self.probes = ProbeManager()
        self.heartbeat_fn = heartbeat_fn  # optional NodeLifecycle hookup
        # container manager: QoS tiers + pod cgroups + node-allocatable
        # admission (reference cm/container_manager_linux.go:210)
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.kubelet.cm import ContainerManager

        self.container_manager = ContainerManager(
            capacity_cpu_milli=int(parse_quantity(
                self.capacity.get("cpu", "0")).milli_value()),
            capacity_memory=int(parse_quantity(
                self.capacity.get("memory", "0")).value()),
        )
        # PLEG: runtime relist → lifecycle events → dirty pods
        # (reference pleg/generic.go:110; driven from the sync loop like
        # syncLoopIteration's plegCh branch)
        from kubernetes_tpu.kubelet.pleg import PLEG

        self.pleg = PLEG(self.runtime, self._on_pleg_event)
        # optional node-pressure eviction (kubelet/eviction.py); attach
        # an EvictionManager and housekeeping drives synchronize()
        self.eviction_manager = None
        # optional image GC (kubelet/imagegc.py); housekeeping drives
        # maybe_garbage_collect()
        self.image_gc_manager = None
        # static pods (reference pkg/kubelet/config/file.go: the
        # /etc/kubernetes/manifests source): run directly from local
        # manifests, never scheduled; each gets a MIRROR pod in the API
        # so the control plane can observe it (pkg/kubelet/pod/
        # mirror_client.go). The manifest set is fixed for this
        # kubelet's lifetime.
        self._static_manifests = list(static_pod_manifests or [])
        self._static_pods: Dict[str, Pod] = {}   # uid -> local truth
        # init-phase tracking: uid -> index of the RUNNING init
        # container (absent = init phase done or no init containers),
        # and the created init container ids for teardown
        self._init_progress: Dict[str, int] = {}
        self._init_cids: Dict[str, List[str]] = {}
        # graceful termination (reference pod_workers terminating state
        # + kuberuntime_container killContainer): per pod, the grace
        # period and preStop hooks captured at start; uid -> force-kill
        # deadline while draining
        self._graceful: Dict[str, tuple] = {}
        self._terminating: Dict[str, float] = {}
        self._sandbox_of: Dict[str, str] = {}  # pod uid -> sandbox id
        self._containers_of: Dict[str, Dict[str, str]] = {}  # uid -> {name: cid}
        self._terminal: set = set()  # uids already reported Succeeded/Failed
        self._key_of: Dict[str, tuple] = {}  # uid -> (namespace, name)
        self._work = threading.Event()
        self._stop = threading.Event()
        self._dirty: set = set()  # pod uids needing sync
        self._dirty_lock = threading.Lock()
        self._watch_handle = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def register_node(self) -> Node:
        """Create/refresh this node's API object, folding device-plugin
        capacity into extended resources (reference
        ``kubelet_node_status.go`` setNodeStatus)."""
        capacity = dict(self.capacity)
        for res, count in self.devices.capacity().items():
            capacity[res] = str(count)
        builder = MakeNode().name(self.node_name).capacity(capacity)
        for k, v in self.labels.items():
            builder = builder.label(k, v)
        node = builder.obj()
        existing = self.store.get_node(self.node_name)
        if existing is not None:
            node.metadata.uid = existing.metadata.uid
        self.store.add_node(node)
        return node

    def start(self) -> "Kubelet":
        self.register_node()
        self.heartbeat()
        self._adopt_runtime_state()
        self._load_static_pods()
        # watch pod events for this node; initial list picks up existing
        for pod in self.store.list_pods():
            if pod.spec.node_name == self.node_name:
                self._key_of[pod.uid] = (pod.namespace, pod.name)
                self._mark_dirty(pod.uid)
        self._watch_handle = self.store.watch(self._on_event)
        # pods/log provider (the apiserver proxies log requests to the
        # node's kubelet; this registry is that connection in-process).
        # A REST-backed store (kubemark hollow nodes over the fabric)
        # has no in-process registration surface — the proxy dial the
        # registry stands in for doesn't exist over plain HTTP — so the
        # providers are simply not offered there.
        if hasattr(self.store, "register_log_source"):
            self.store.register_log_source(self.node_name,
                                           self.container_logs)
            self.store.register_exec_source(self.node_name,
                                            self.container_exec)
            self.store.register_portforward_source(self.node_name,
                                                   self.forward_port)
        self._thread = threading.Thread(
            target=self._sync_loop, daemon=True, name=f"kubelet-{self.node_name}"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if hasattr(self.store, "unregister_log_source"):
            self.store.unregister_log_source(self.node_name)
            self.store.unregister_exec_source(self.node_name)
            self.store.unregister_portforward_source(self.node_name)
        if self._watch_handle is not None:
            self._watch_handle.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def container_logs(self, namespace: str, name: str,
                       container: str = "") -> str:
        """Log text for a pod on this node (kubectl logs; reference
        kubelet server's /containerLogs endpoint → CRI log files).
        Multi-container pods require ``container`` like real kubectl;
        init container logs resolve by name too. Raises LookupError for
        an unknown pod/container — the REST layer turns it into a
        client error, never silent-empty success. Called from apiserver
        handler threads: every kubelet map is read through a C-level
        dict/list copy (atomic under the GIL) so the sync loop's
        concurrent mutations cannot blow up the iteration."""
        key_of = dict(self._key_of)
        uid = next(
            (u for u, key in key_of.items()
             if key == (namespace, name)), None,
        )
        if uid is None:
            raise LookupError(
                f"pod {namespace}/{name} is not running on this node"
            )
        cids = dict(self._containers_of.get(uid, {}))
        init_cids = list(self._init_cids.get(uid, ()))
        if init_cids:
            pod = self._static_pods.get(uid) or self._find_pod(uid)
            if pod is not None:
                for i, cid in enumerate(init_cids):
                    if i < len(pod.spec.init_containers):
                        cids.setdefault(
                            pod.spec.init_containers[i].name, cid)
        if container:
            if container not in cids:
                raise LookupError(
                    f"container {container!r} is not valid for pod "
                    f"{name} (containers: {sorted(cids) or 'none'})"
                )
            chosen = {container: cids[container]}
        elif len(cids) == 1:
            chosen = cids
        else:
            raise LookupError(
                "a container name must be specified for pod "
                f"{name} (choose one of {sorted(cids)})"
            )
        lines: List[str] = []
        for cname, cid in sorted(chosen.items()):
            try:
                lines.extend(self.runtime.container_logs(cid))
            except Exception:  # noqa: BLE001 — runtime without logs
                pass
        return "\n".join(lines) + ("\n" if lines else "")

    def forward_port(self, namespace: str, name: str, port: int,
                     data: bytes) -> bytes:
        """Exchange one payload with a pod's port (kubectl
        port-forward; reference kubelet server /portForward → CRI).
        Raises LookupError for an unknown pod — the REST layer's 400."""
        key_of = dict(self._key_of)
        uid = next(
            (u for u, key in key_of.items()
             if key == (namespace, name)), None,
        )
        if uid is None or uid not in self._sandbox_of:
            raise LookupError(
                f"pod {namespace}/{name} is not running on this node"
            )
        return self.runtime.serve_port(self._sandbox_of[uid], port, data)

    def container_exec(self, namespace: str, name: str, container: str,
                       command: List[str]) -> tuple:
        """Run a command in a pod's container (kubectl exec; reference
        kubelet server /exec → CRI ExecSync). Returns (exit code,
        output text). Resolution mirrors ``container_logs``: unknown
        pod/container raises LookupError for the REST layer's 400."""
        key_of = dict(self._key_of)
        uid = next(
            (u for u, key in key_of.items()
             if key == (namespace, name)), None,
        )
        if uid is None:
            raise LookupError(
                f"pod {namespace}/{name} is not running on this node"
            )
        cids = dict(self._containers_of.get(uid, {}))
        if container:
            if container not in cids:
                raise LookupError(
                    f"container {container!r} is not valid for pod "
                    f"{name} (containers: {sorted(cids) or 'none'})"
                )
            cid = cids[container]
        elif len(cids) == 1:
            cid = next(iter(cids.values()))
        else:
            raise LookupError(
                "a container name must be specified for pod "
                f"{name} (choose one of {sorted(cids)})"
            )
        before = []
        try:
            before = list(self.runtime.container_logs(cid))
        except Exception:  # noqa: BLE001
            pass
        rc = self.runtime.exec_sync(cid, list(command))
        # the fake CRI records exec output on the container's log
        # stream; the delta is this exec's "stdout"
        after = []
        try:
            after = list(self.runtime.container_logs(cid))
        except Exception:  # noqa: BLE001
            pass
        out = "\n".join(after[len(before):])
        return rc, out + ("\n" if out else "")

    # -- event plumbing ------------------------------------------------
    def _on_event(self, event: Event) -> None:
        if event.kind != "Pod":
            return
        pod: Pod = event.obj
        mine = pod.spec.node_name == self.node_name
        was_mine = (
            event.old_obj is not None
            and getattr(event.old_obj.spec, "node_name", "") == self.node_name
        )
        if mine or was_mine or event.type == DELETED and pod.uid in self._sandbox_of:
            if event.type != DELETED:
                self._key_of[pod.uid] = (pod.namespace, pod.name)
            self._mark_dirty(pod.uid)

    def _mark_dirty(self, uid: str) -> None:
        with self._dirty_lock:
            self._dirty.add(uid)
        self._work.set()

    def _on_pleg_event(self, event) -> None:
        """PLEG sink: a container state delta re-syncs its pod (the
        reference's syncLoopIteration plegCh → HandlePodSyncs)."""
        self._mark_dirty(event.pod_uid)

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._work.wait(timeout=self.sync_interval)
            self._work.clear()
            with self._dirty_lock:
                dirty, self._dirty = self._dirty, set()
            known = set(self._sandbox_of)
            for uid in dirty | known:
                try:
                    self.sync_pod(uid)
                except Exception:
                    _logger.exception("sync_pod %s", uid)
            try:
                # runtime-truth pass: container crashes/exits surface
                # here even when no API event fired
                self.pleg.relist()
            except Exception:
                _logger.exception("pleg relist")
            try:
                # volume reconciler pass (reference reconciler.go:77
                # runs every 100ms): an attach landing re-syncs the
                # pods it unblocks so their containers start
                for uid in self.volumes.reconcile():
                    self._mark_dirty(uid)
            except Exception:
                _logger.exception("volume reconcile")
            self.probes.tick()
            if self.eviction_manager is not None:
                try:
                    self.eviction_manager.synchronize()
                except Exception:
                    _logger.exception("eviction synchronize")
            if self.image_gc_manager is not None:
                try:
                    self.image_gc_manager.maybe_garbage_collect()
                except Exception:
                    _logger.exception("image gc")
            self.heartbeat()

    def heartbeat(self) -> None:
        if self.heartbeat_fn is not None:
            self.heartbeat_fn(self.node_name)
        else:
            from kubernetes_tpu.utils.clock import RealClock

            self.store.try_acquire_or_renew(
                f"node-{self.node_name}", self.node_name, RealClock().now(), 40.0
            )

    def _adopt_runtime_state(self) -> None:
        """Rebuild the sandbox/container maps from the runtime's live
        truth before any sync runs — a restarted kubelet over a
        persistent runtime must ADOPT running workloads, never start a
        second copy (the reference's startup reconciliation against the
        CRI: kubelet.go HandlePodCleanups / pod-worker resurrection
        from the runtime cache)."""
        from kubernetes_tpu.kubelet.cri import SANDBOX_READY

        try:
            sandboxes = self.runtime.list_pod_sandboxes()
            containers = self.runtime.list_containers()
        except Exception:
            _logger.exception("runtime-state adoption failed")
            return
        by_sandbox: Dict[str, list] = {}
        for c in containers:
            by_sandbox.setdefault(c.sandbox_id, []).append(c)
        for sb in sandboxes:
            if sb.state != SANDBOX_READY:
                continue
            self._sandbox_of[sb.pod_uid] = sb.id
            self._containers_of[sb.pod_uid] = {
                c.name: c.id for c in by_sandbox.get(sb.id, ())
            }
            self._key_of.setdefault(sb.pod_uid, (sb.namespace, sb.name))
            self._mark_dirty(sb.pod_uid)

    # -- static / mirror pods ------------------------------------------
    MIRROR_ANNOTATION = "kubernetes.io/config.mirror"

    def _load_static_pods(self) -> None:
        for manifest in self._static_manifests:
            try:
                pod = Pod.from_dict(manifest)
            except Exception:
                _logger.exception("bad static pod manifest; skipped")
                continue
            if not pod.metadata.namespace:
                pod.metadata.namespace = "kube-system"
            # per-node name suffix (reference kubelet config/common.go
            # applyDefaults): two kubelets loading the same manifest
            # must not fight over one (namespace, name) mirror slot
            pod.metadata.name = f"{pod.metadata.name}-{self.node_name}"
            pod.spec.node_name = self.node_name
            # STABLE identity across kubelet restarts (the reference
            # hashes the manifest source): a fresh random uid per start
            # would make a surviving mirror look like a different pod
            # and double-start the workload
            pod.metadata.uid = (
                f"static-{self.node_name}-{pod.namespace}-"
                f"{pod.metadata.name}"
            )
            # the mirror annotation (kubernetes.io/config.mirror) is the
            # reference's config hash; the uid stands in for it — and it
            # is what NodeRestriction admission keys its mirror-pod
            # carve-out on
            pod.metadata.annotations.setdefault(
                self.MIRROR_ANNOTATION, pod.uid)
            self._static_pods[pod.uid] = pod
            self._key_of[pod.uid] = (pod.namespace, pod.name)
            self._mark_dirty(pod.uid)

    def _ensure_mirror(self, pod: Pod) -> bool:
        """Create (or recreate) the API mirror of a static pod — the
        control plane's read-only view; deleting it never stops the
        static pod, the kubelet just republishes (mirror_client.go
        CreateMirrorPod semantics). A DIFFERENT pod's mirror squatting
        the name (stale incarnation) is deleted and replaced, like the
        reference's hash-mismatch path; an unrelated NON-mirror pod
        blocks publication — returns False so the caller suppresses
        API status writes that would clobber the impostor by name."""
        existing = self.store.get_pod(pod.namespace, pod.name)
        if existing is not None:
            if existing.uid == pod.uid:
                return True
            if self.MIRROR_ANNOTATION in existing.metadata.annotations:
                self.store.delete_pod(pod.namespace, pod.name)
            else:
                _logger.warning(
                    "pod %s exists and is not this kubelet's mirror; "
                    "static pod runs unpublished", pod.full_name(),
                )
                return False
        from kubernetes_tpu.api.types import shallow_copy

        mirror = shallow_copy(pod)
        mirror.metadata = shallow_copy(pod.metadata)
        mirror.metadata.resource_version = ""
        mirror.status = shallow_copy(pod.status)
        if pod.uid in self._sandbox_of:
            # a republished mirror of an already-running static pod
            # must not read as Pending
            mirror.status.phase = RUNNING
        try:
            self.store.create_pod(mirror)
        except Exception:
            _logger.exception("mirror pod create failed: %s",
                              pod.full_name())
        return True

    # -- pod reconciliation --------------------------------------------
    def _find_pod(self, uid: str) -> Optional[Pod]:
        key = self._key_of.get(uid)
        if key is None:
            return None
        pod = self.store.get_pod(*key)
        # names are reusable; make sure this is still the same pod
        return pod if pod is not None and pod.uid == uid else None

    def sync_pod(self, uid: str) -> None:
        static = self._static_pods.get(uid)
        if static is not None:
            # local manifests are the source of truth: republish the
            # mirror if it was deleted, and keep the containers running
            # (even unpublished — the reference kubelet runs static
            # pods with the API entirely down)
            publish = self._ensure_mirror(static)
            if uid in self._terminal:
                return
            if self._sandbox_of.get(uid) is None:
                self._admit_and_start(static, publish=publish)
            else:
                self._reconcile_containers(static, publish=publish)
            return
        pod = self._find_pod(uid)
        if pod is None or pod.spec.node_name != self.node_name:
            self._teardown(uid)
            return
        if uid in self._terminal:
            return
        sandbox = self._sandbox_of.get(uid)
        if sandbox is None:
            self._admit_and_start(pod)
            return
        self._reconcile_containers(pod)

    def _admit_and_start(self, pod: Pod, publish: bool = True) -> None:
        # publish=False (an impostor pod owns the static pod's name):
        # run the containers, write nothing to the API by name
        # node-allocatable admission (cm enforcement): a pod the
        # scheduler raced past this node's allocatable fails here with
        # an OutOf* reason, like the reference kubelet's admit handlers
        reason = self.container_manager.admit(pod)
        if reason is not None:
            if publish:
                self.store.set_pod_phase(pod.namespace, pod.name, FAILED)
            self._terminal.add(pod.uid)
            _logger.warning("pod %s rejected: %s", pod.full_name(), reason)
            return
        # device admission next: unsatisfiable extended resources fail the
        # pod rather than half-starting it. A checkpointed assignment from
        # a previous kubelet incarnation satisfies admission as-is — that
        # is the whole point of the device checkpoint.
        try:
            if not self.devices.devices_of(pod.uid):
                for c in pod.spec.containers:
                    for res, qty in c.resources.requests.items():
                        if res == TPU_RESOURCE:
                            self.devices.allocate(pod.uid, c.name, res, qty.value())
        except Exception as e:
            # roll back devices granted to earlier containers of this pod
            self.devices.free(pod.uid)
            if publish:
                self.store.set_pod_phase(pod.namespace, pod.name, FAILED)
            self._terminal.add(pod.uid)
            _logger.warning("pod %s admission failed: %s", pod.full_name(), e)
            return
        # volume gate (reference WaitForAttachAndMount,
        # volume_manager.go:387): containers must not start before every
        # volume is mounted — claim-backed ones wait for the attachdetach
        # controller's volumesAttached handshake. The reconciler re-syncs
        # this pod when its volumes land; until then it stays Pending.
        self.volumes.add_pod(pod)
        # reconcile returns ONE-SHOT newly-ready notifications; any pod
        # they name (not just this one) must be re-synced or it strands
        # Pending — this call may consume the notification the sync
        # loop's own reconcile would otherwise have delivered
        for uid in self.volumes.reconcile():
            if uid != pod.uid:
                self._mark_dirty(uid)
        if not self.volumes.volumes_ready(pod):
            return
        # pod cgroup under its QoS tier (podContainerManager
        # EnsureExists before the sandbox starts)
        self.container_manager.create_pod_cgroup(pod)
        sid = self.runtime.run_pod_sandbox(pod.uid, pod.name, pod.namespace)
        self._sandbox_of[pod.uid] = sid
        if pod.spec.init_containers:
            # init phase (reference kuberuntime_manager.go
            # computePodActions: init containers run ONE at a time, each
            # to successful completion, before any app container starts)
            self._containers_of[pod.uid] = {}
            self._init_progress[pod.uid] = 0
            self._init_cids[pod.uid] = []
            if publish:
                self.store.patch_pod_condition(
                    pod.namespace, pod.name,
                    PodCondition("Initialized", "False",
                                 "ContainersNotInitialized", ""),
                )
            self._start_next_init(pod)
            return
        self._start_main_containers(pod, publish)

    def _start_next_init(self, pod: Pod) -> None:
        idx = self._init_progress[pod.uid]
        ic = pod.spec.init_containers[idx]
        sid = self._sandbox_of[pod.uid]
        cid = self.runtime.create_container(sid, ic.name, ic.image)
        self.runtime.start_container(cid)
        self._init_cids[pod.uid].append(cid)

    def _drive_init(self, pod: Pod, publish: bool) -> None:
        """One init-phase step: advance past completed init containers,
        restart failed ones per policy (the reference restarts a failed
        init container unless restartPolicy is Never, in which case the
        pod fails: kuberuntime_manager.go + pod_workers)."""
        uid = pod.uid
        cid = self._init_cids[uid][-1]
        st = self.runtime.container_status(cid)
        if st is None or st.state != EXITED:
            return                       # still running
        if st.exit_code == 0:
            nxt = self._init_progress[uid] + 1
            if nxt < len(pod.spec.init_containers):
                self._init_progress[uid] = nxt
                self._start_next_init(pod)
                return
            # init phase complete: app containers start now
            del self._init_progress[uid]
            if publish:
                self.store.patch_pod_condition(
                    pod.namespace, pod.name,
                    PodCondition("Initialized", "True", "", ""),
                )
            self._start_main_containers(pod, publish)
            return
        policy = getattr(pod.spec, "restart_policy", "Always")
        if policy == "Never":
            self._finish(pod, FAILED, publish=publish)
        else:
            self.runtime.start_container(cid)   # retry the failed init

    def _rebuild_init_state(self, pod: Pod) -> None:
        """Reconstruct _init_progress/_init_cids for a pod adopted from
        a persistent runtime mid-init (the reference re-derives pod
        actions from the runtime status every sync, so a restart cannot
        confuse init and app containers)."""
        uid = pod.uid
        cids = self._containers_of.get(uid, {})
        init_cids: List[str] = []
        pending_idx: Optional[int] = None
        for i, ic in enumerate(pod.spec.init_containers):
            cid = cids.get(ic.name)
            if cid is None:
                pending_idx = i       # this init was never created
                break
            init_cids.append(cid)
            st = self.runtime.container_status(cid)
            if st is None or st.state != EXITED or st.exit_code != 0:
                pending_idx = i       # running or failed: drive it
                break
        # app containers keep only their OWN entries
        self._containers_of[uid] = {
            c.name: cids[c.name]
            for c in pod.spec.containers if c.name in cids
        }
        if pending_idx is None:
            # init phase completed pre-restart; mains the crash window
            # swallowed (restart between init-done and app-start) are
            # created now, existing ones adopted as-is
            sid = self._sandbox_of[uid]
            for c in pod.spec.containers:
                if c.name not in self._containers_of[uid]:
                    cid = self.runtime.create_container(sid, c.name,
                                                        c.image)
                    self.runtime.start_container(cid)
                    self._run_post_start(c, cid)
                    self._containers_of[uid][c.name] = cid
            return
        self._init_progress[uid] = pending_idx
        self._init_cids[uid] = init_cids
        if len(init_cids) <= pending_idx:
            self._start_next_init(pod)

    def _capture_graceful(self, pod: Pod) -> None:
        """Record the pod's termination contract (grace period + preStop
        hooks): the pod object may be GONE from the store by the time
        teardown needs it. Also called for ADOPTED pods (restart over a
        persistent runtime) — their contract must survive the restart."""
        grace = pod.spec.termination_grace_period_seconds
        self._graceful[pod.uid] = (
            30.0 if grace is None else float(grace),
            [(c.name, c.lifecycle["preStop"])
             for c in pod.spec.containers
             if c.lifecycle and c.lifecycle.get("preStop")],
        )

    def _run_post_start(self, c, cid: str) -> None:
        """postStart runs immediately after the container starts
        (lifecycle.go:52 — failures kill the container in the
        reference; here best-effort, recorded by the runtime)."""
        if c.lifecycle and c.lifecycle.get("postStart"):
            try:
                self.runtime.exec_sync(cid, c.lifecycle["postStart"])
            except Exception:  # noqa: BLE001
                _logger.exception("postStart hook failed: %s", c.name)

    def _start_main_containers(self, pod: Pod, publish: bool) -> None:
        sid = self._sandbox_of[pod.uid]
        cids = {}
        self._capture_graceful(pod)
        for c in pod.spec.containers:
            cid = self.runtime.create_container(sid, c.name, c.image)
            self.runtime.start_container(cid)
            cids[c.name] = cid
            self._run_post_start(c, cid)
            # image sighting feeds the GC manager's LRU order
            if self.image_gc_manager is not None and c.image:
                self.image_gc_manager.note_image_used(c.image)
        self._containers_of[pod.uid] = cids
        ip = getattr(self.runtime, "sandbox_ip", lambda s: "")(sid)
        if publish:
            self.store.set_pod_phase(pod.namespace, pod.name, RUNNING,
                                     pod_ip=ip, host_ip=self.node_name)
            self._set_ready_condition(pod, True)

    def _reconcile_containers(self, pod: Pod, publish: bool = True) -> None:
        if pod.uid not in self._graceful:
            # adopted pod (kubelet restart): re-derive the termination
            # contract the old incarnation captured at start
            self._capture_graceful(pod)
        if pod.spec.init_containers and \
                pod.uid not in self._init_progress and any(
                    ic.name in self._containers_of.get(pod.uid, {})
                    for ic in pod.spec.init_containers):
            # adopted pod (restart over a persistent runtime): the
            # normal flow never maps init containers into
            # _containers_of, so their presence means the init-phase
            # bookkeeping must be re-derived from runtime truth
            self._rebuild_init_state(pod)
        if pod.uid in self._init_progress:
            self._drive_init(pod, publish)
            return
        cids = self._containers_of.get(pod.uid, {})
        statuses = {
            name: self.runtime.container_status(cid) for name, cid in cids.items()
        }
        # liveness restarts
        for cname, failing in self.probes.liveness_failed(pod.uid).items():
            if failing and cname in cids:
                st = statuses.get(cname)
                if st is not None and st.state == CRI_RUNNING:
                    self.runtime.stop_container(cids[cname])
                    statuses[cname] = self.runtime.container_status(cids[cname])
        states = [s.state for s in statuses.values() if s is not None]
        exit_codes = [
            s.exit_code for s in statuses.values() if s is not None and s.state == EXITED
        ]
        policy = getattr(pod.spec, "restart_policy", "Always")
        if states and all(s == EXITED for s in states):
            if all(code == 0 for code in exit_codes):
                if policy in ("Never", "OnFailure"):
                    self._finish(pod, SUCCEEDED, publish=publish)
                    return
            elif policy == "Never":
                self._finish(pod, FAILED, publish=publish)
                return
        # restart what policy says should run
        for name, st in statuses.items():
            if st is None or st.state != EXITED:
                continue
            if policy == "Always" or (policy == "OnFailure" and st.exit_code != 0):
                self.runtime.start_container(cids[name])
        if publish:
            self._set_ready_condition(pod, self.probes.pod_ready(pod.uid))

    def _finish(self, pod: Pod, phase: str, publish: bool = True) -> None:
        if publish:
            self.store.set_pod_phase(pod.namespace, pod.name, phase)
        self._terminal.add(pod.uid)
        self._release(pod.uid)

    def _teardown(self, uid: str) -> None:
        """Pod deleted or moved away: GRACEFUL termination (reference
        pod_workers terminating state): preStop hooks run, containers
        get a stop with the pod's grace period to drain, and only when
        every container exited — or the force-kill deadline passed —
        does the sandbox release. _release is idempotent and must run
        even without a sandbox — admission-failed pods can still hold
        device/volume state."""
        import time as _time

        cids = self._containers_of.get(uid, {})
        if uid in self._sandbox_of and uid not in self._terminating \
                and cids:
            grace, hooks = self._graceful.get(uid, (0.0, []))
            for cname, payload in hooks:
                cid = cids.get(cname)
                if cid is not None:
                    try:
                        self.runtime.exec_sync(cid, payload)
                    except Exception:  # noqa: BLE001 — hooks are best-effort
                        _logger.exception("preStop hook failed: %s", cname)
            for cid in cids.values():
                st = self.runtime.container_status(cid)
                if st is not None and st.state == CRI_RUNNING:
                    try:
                        self.runtime.stop_container(cid, timeout_s=grace)
                    except TypeError:
                        self.runtime.stop_container(cid)
                    except RuntimeError:
                        pass       # exited between status and stop
            self._terminating[uid] = _time.monotonic() + grace
            self._work.set()
        if uid in self._terminating:
            statuses = [self.runtime.container_status(c)
                        for c in cids.values()]
            drained = all(s is None or s.state == EXITED
                          for s in statuses)
            if not drained and _time.monotonic() < self._terminating[uid]:
                return             # grace window: containers draining
            del self._terminating[uid]
        self._release(uid)
        self._graceful.pop(uid, None)
        self._terminal.discard(uid)
        self._key_of.pop(uid, None)

    def _release(self, uid: str) -> None:
        sid = self._sandbox_of.pop(uid, None)
        if sid is not None:
            self.runtime.stop_pod_sandbox(sid)
            self.runtime.remove_pod_sandbox(sid)
        self._containers_of.pop(uid, None)
        self._init_progress.pop(uid, None)
        self._init_cids.pop(uid, None)
        self.devices.free(uid)
        # teardown ordering: the sandbox is stopped ABOVE, then the pod
        # leaves the volume manager's desired state; the reconcile
        # unmounts and shrinks volumesInUse, which is what finally lets
        # the attachdetach controller detach (never detach under a
        # running container)
        self.volumes.remove_pod(uid)
        for ready_uid in self.volumes.reconcile():
            self._mark_dirty(ready_uid)
        self.probes.remove_pod(uid)
        self.container_manager.delete_pod_cgroup(uid)

    def _set_ready_condition(self, pod: Pod, ready: bool) -> None:
        self.store.patch_pod_condition(
            pod.namespace,
            pod.name,
            PodCondition("Ready", "True" if ready else "False",
                         "ContainersReady" if ready else "ProbeFailure", ""),
        )

    # -- introspection --------------------------------------------------
    def running_pods(self) -> List[str]:
        return list(self._sandbox_of)
