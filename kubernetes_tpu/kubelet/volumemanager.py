"""Kubelet volume manager: the desired/actual-state-of-world reconciler.

Behavioral equivalent of the reference's kubelet volume manager
(``pkg/kubelet/volumemanager/volume_manager.go:247 NewVolumeManager``;
reconciler ``pkg/kubelet/volumemanager/reconciler/reconciler.go:77``):

- **Desired state of world**: every pod admitted to this node together
  with the volumes its spec mounts (``populator/
  desired_state_of_world_populator.go``: findAndAddNewPods /
  findAndRemoveDeletedPods — here the kubelet's sync path adds and
  removes pods explicitly, so no list rescan is needed).
- **Actual state of world**: which of those volumes this node has
  actually mounted.
- **Reconcile** (the reference's 100ms reconciler loop; here driven from
  the kubelet sync loop): claim-backed volumes wait for the attach/detach
  CONTROLLER to attach — ``node.status.volumesAttached`` is the handshake
  (``reconciler.go`` mountAttachVolumes → verify attached, matching
  ``kubelet.go`` WaitForAttachAndMount on the other side); node-local
  volumes (emptyDir, configMap projections, ephemeral scratch) mount
  immediately. Volumes whose last desired consumer is gone unmount.
- **volumesInUse** is published BY the reconciler, from the desired
  state (reference ``volume_manager.go`` GetVolumesInUse: "all volumes
  that implement the volume.Attacher interface ... in the desired state
  of world" — mounted or still mounting), so the attach/detach
  controller's safe-detach interlock covers an in-flight mount. Like the
  reference's markVolumesAsReportedInUse handshake, a claim-backed
  volume is mounted only after it appeared in a published report —
  never mount a volume detachable out from under the mount.

Container start gates on ``volumes_ready`` (the reference blocks the pod
worker in WaitForAttachAndMount, ``volume_manager.go:387``); unmount
happens at pod teardown AFTER the sandbox stopped, and detach only after
the resulting in-use shrink — the teardown ordering the reference
enforces between kubelet and the attachdetach controller.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set

from kubernetes_tpu.api.types import Pod

_logger = logging.getLogger(__name__)


class VolumeManager:
    def __init__(self, store, node_name: str):
        self.store = store
        self.node_name = node_name
        self._lock = threading.Lock()
        # DSW: pod uid -> {volume name: claim name or None (node-local)}
        self._dsw: Dict[str, Dict[str, Optional[str]]] = {}
        self._ns_of: Dict[str, str] = {}       # uid -> namespace
        # ASW: pod uid -> mounted volume names
        self._mounted: Dict[str, Set[str]] = {}
        # the last volumesInUse report that reached the API (the
        # reported-in-use handshake: mounts wait for it)
        self._reported_in_use: Set[str] = set()
        # (uid, volume name) -> PV name, pinned at first resolution:
        # the in-use report must keep covering a MOUNTED volume even if
        # its PVC object disappears mid-flight (namespace teardown, no
        # pvc-protection controller) — recomputing from the store would
        # shrink the report and let the attachdetach controller detach
        # under a running container
        self._pv_pin: Dict[tuple, str] = {}

    # -- desired state --------------------------------------------------
    def add_pod(self, pod: Pod) -> None:
        """Register the pod's volumes in the desired state (populator
        findAndAddNewPods). Idempotent."""
        with self._lock:
            self._dsw[pod.uid] = {
                v.name: (v.persistent_volume_claim or None)
                for v in pod.spec.volumes
            }
            self._ns_of[pod.uid] = pod.namespace

    def remove_pod(self, uid: str) -> None:
        """Drop the pod from the desired state (populator
        findAndRemoveDeletedPods); the next reconcile unmounts."""
        with self._lock:
            self._dsw.pop(uid, None)

    # -- queries --------------------------------------------------------
    def volumes_ready(self, pod: Pod) -> bool:
        """True when every volume the pod mounts is in the actual state
        (the WaitForAttachAndMount gate)."""
        with self._lock:
            mounted = self._mounted.get(pod.uid, set())
        return all(v.name in mounted for v in pod.spec.volumes)

    def mounted(self, uid: str) -> List[str]:
        with self._lock:
            return sorted(self._mounted.get(uid, ()))

    def pending_pods(self) -> List[str]:
        """Pods whose desired volumes are not all mounted yet."""
        with self._lock:
            return [
                uid for uid, vols in self._dsw.items()
                if set(vols) - self._mounted.get(uid, set())
            ]

    # -- reconcile ------------------------------------------------------
    def _pv_name(self, uid: str, vname: str, claim: str) -> Optional[str]:
        pin = self._pv_pin.get((uid, vname))
        if pin is not None:
            return pin
        pvc = self.store.get_pvc(self._ns_of.get(uid, "default"), claim)
        if pvc is not None and pvc.volume_name:
            self._pv_pin[(uid, vname)] = pvc.volume_name
            return pvc.volume_name
        return None

    def reconcile(self) -> List[str]:
        """One reconciler pass. Returns pod uids whose volumes became
        fully mounted in THIS pass (the kubelet re-syncs them so their
        containers start)."""
        with self._lock:
            dsw = {uid: dict(vols) for uid, vols in self._dsw.items()}
            mounted = {uid: set(vs) for uid, vs in self._mounted.items()}

        # 1. publish volumesInUse from the DESIRED state — before any
        #    mount, so the controller's detach interlock always covers
        #    the mount about to happen
        in_use: Set[str] = set()
        for uid, vols in dsw.items():
            for vname, claim in vols.items():
                if claim:
                    pv = self._pv_name(uid, vname, claim)
                    if pv:
                        in_use.add(pv)
        self._publish_in_use(in_use)

        # 2. mount pass: attach-requiring volumes need the controller's
        #    volumesAttached handshake AND a published in-use report
        node = self.store.get_node(self.node_name)
        attached = set(node.status.volumes_attached) if node else set()
        with self._lock:
            reported = set(self._reported_in_use)
        newly_ready: List[str] = []
        for uid, vols in dsw.items():
            have = mounted.get(uid, set())
            missing = set(vols) - have
            if not missing:
                continue
            for vname in sorted(missing):
                claim = vols[vname]
                if claim is None:
                    have.add(vname)          # node-local: mount directly
                    continue
                pv = self._pv_name(uid, vname, claim)
                if pv is not None and pv in attached and pv in reported:
                    have.add(vname)
            mounted[uid] = have
            if not set(vols) - have:
                newly_ready.append(uid)

        # 3. unmount pass: actual-state entries with no desired consumer
        for uid in list(mounted):
            if uid not in dsw:
                del mounted[uid]

        with self._lock:
            self._mounted = mounted
            for uid in list(self._ns_of):
                if uid not in self._dsw:
                    del self._ns_of[uid]
        for key in list(self._pv_pin):
            if key[0] not in dsw:
                del self._pv_pin[key]
        return newly_ready

    def _publish_in_use(self, in_use: Set[str]) -> None:
        report = sorted(in_use)

        def mutate(n) -> bool:
            if n.status.volumes_in_use == report:
                return False
            n.status.volumes_in_use = report
            return True

        try:
            self.store.mutate_object("Node", "", self.node_name, mutate)
        except Exception:  # noqa: BLE001 — node may not exist yet
            _logger.debug("volumesInUse report failed", exc_info=True)
            return
        with self._lock:
            self._reported_in_use = in_use
