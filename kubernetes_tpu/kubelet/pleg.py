"""PLEG — Pod Lifecycle Event Generator (reference
``pkg/kubelet/pleg/generic.go:110 NewGenericPLEG`` + ``relist``): the
kubelet's second eye on the world. The watch path tells it what the API
WANTS; the PLEG periodically relists the container RUNTIME and turns
state deltas into pod-scoped lifecycle events (ContainerStarted /
ContainerDied / ContainerRemoved), which the sync loop consumes to
reconcile pods whose containers changed underneath it — a crashed
container is observed here, not via the apiserver.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

CONTAINER_STARTED = "ContainerStarted"
CONTAINER_DIED = "ContainerDied"
CONTAINER_REMOVED = "ContainerRemoved"


@dataclass
class PodLifecycleEvent:
    pod_uid: str
    type: str
    data: str = ""  # container id


class PLEG:
    """Generic PLEG over the CRI runtime service. ``relist`` diffs the
    current container states against the previous relist (generic.go
    relist: computeEvents per pod) and hands each event to the sink
    (the kubelet marks the pod dirty); ``relist_period`` matches the
    reference's 1s GenericPLEG tick when driven by ``start``, but the
    kubelet may also call ``relist`` inline from its sync loop."""

    def __init__(self, runtime, sink: Callable[[PodLifecycleEvent], None],
                 relist_period: float = 1.0):
        self.runtime = runtime
        self.sink = sink
        self.relist_period = relist_period
        # (pod uid, container id) -> state at last relist
        self._last: Dict[Tuple[str, str], str] = {}
        self._last_relist: float = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.events_emitted = 0  # observability

    # -- the core -------------------------------------------------------
    def relist(self) -> List[PodLifecycleEvent]:
        """One relist pass; returns (and sinks) the generated events."""
        current: Dict[Tuple[str, str], str] = {}
        for sandbox in self.runtime.list_pod_sandboxes():
            for cs in self.runtime.list_containers(sandbox.id):
                current[(sandbox.pod_uid, cs.id)] = cs.state
        events: List[PodLifecycleEvent] = []
        with self._lock:
            for key, state in current.items():
                old = self._last.get(key)
                if old == state:
                    continue
                uid, cid = key
                if state == "RUNNING":
                    events.append(PodLifecycleEvent(
                        uid, CONTAINER_STARTED, cid))
                elif state in ("EXITED", "UNKNOWN"):
                    # ANY transition into exited generates ContainerDied
                    # (generic.go generateEvents) — including a container
                    # that started AND crashed between two relists
                    # (old CREATED or first sighting), or the pod never
                    # re-syncs and a crash-loop sits EXITED forever
                    events.append(PodLifecycleEvent(
                        uid, CONTAINER_DIED, cid))
            for key in self._last:
                if key not in current:
                    events.append(PodLifecycleEvent(
                        key[0], CONTAINER_REMOVED, key[1]))
            self._last = current
            self._last_relist = time.monotonic()
        for ev in events:
            self.events_emitted += 1
            try:
                self.sink(ev)
            except Exception:  # noqa: BLE001 — sink must not kill relist
                pass
        return events

    def healthy(self, threshold: float = 180.0) -> bool:
        """generic.go Healthy(): the PLEG is unhealthy when relist
        hasn't completed within the threshold (3m in the reference) —
        surfaced through the node's Ready condition."""
        with self._lock:
            last = self._last_relist
        return last == 0.0 or (time.monotonic() - last) < threshold

    # -- optional self-driving loop ------------------------------------
    def start(self) -> "PLEG":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pleg")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.relist_period):
            try:
                self.relist()
            except Exception:  # noqa: BLE001
                pass
