from kubernetes_tpu.kubelet.checkpoint import CheckpointManager, CorruptCheckpointError
from kubernetes_tpu.kubelet.cri import (
    CREATED,
    EXITED,
    FakeRuntime,
    ImageService,
    RuntimeService,
)
from kubernetes_tpu.kubelet.devicemanager import (
    DeviceAllocationError,
    DeviceManager,
    DevicePlugin,
    TPU_RESOURCE,
)
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.volumemanager import VolumeManager
from kubernetes_tpu.kubelet.probes import LIVENESS, READINESS, ProbeManager, ProbeSpec
