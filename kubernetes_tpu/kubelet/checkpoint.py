"""Checksummed local checkpoint store.

Behavioral equivalent of the reference's kubelet checkpoint manager
(``pkg/kubelet/checkpointmanager/checkpoint_manager.go`` +
``checksum/checksum.go``): named checkpoints persisted to local files with
an integrity checksum, verified on read so a torn write surfaces as
``CorruptCheckpointError`` instead of silent bad state. Used by the device
manager (``pkg/kubelet/cm/devicemanager/checkpoint/checkpoint.go``) to
survive kubelet restarts without losing device assignments.
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import Any, Dict, List, Optional


class CorruptCheckpointError(Exception):
    pass


def _checksum(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


class CheckpointManager:
    """File-per-checkpoint with atomic replace + CRC verification."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        if "/" in name or name.startswith("."):
            raise ValueError(f"invalid checkpoint name {name!r}")
        return os.path.join(self.directory, name + ".ckpt")

    def create(self, name: str, data: Any) -> None:
        """Write (atomically): a crash mid-write leaves the old file."""
        payload = json.dumps(data, sort_keys=True).encode()
        doc = json.dumps(
            {"checksum": _checksum(payload), "data": payload.decode()}
        ).encode()
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(doc)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(name))
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def get(self, name: str) -> Optional[Any]:
        """Read + verify; raises CorruptCheckpointError on checksum
        mismatch, returns None if absent."""
        path = self._path(name)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read())
            payload = doc["data"].encode()
            if _checksum(payload) != doc["checksum"]:
                raise CorruptCheckpointError(f"checkpoint {name!r} checksum mismatch")
            return json.loads(payload)
        except (json.JSONDecodeError, KeyError, UnicodeDecodeError) as e:
            raise CorruptCheckpointError(f"checkpoint {name!r} unreadable: {e}")

    def remove(self, name: str) -> None:
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass

    def list(self) -> List[str]:
        return sorted(
            f[: -len(".ckpt")]
            for f in os.listdir(self.directory)
            if f.endswith(".ckpt")
        )
