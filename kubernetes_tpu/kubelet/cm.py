"""Container manager: QoS classes + cgroup-tree accounting (reference
``pkg/kubelet/cm/container_manager_linux.go:210 NewContainerManager`` +
``cm/qos_container_manager_linux.go`` + ``cm/pod_container_manager_
linux.go``; QoS classification ``pkg/apis/core/v1/helper/qos/qos.go``).

The reference programs real cgroupfs; this build maintains the SAME
tree as in-process state — /kubepods with burstable/besteffort QoS
tiers, one pod cgroup per pod parented by QoS class, cpu shares/quota
and memory limits derived from requests/limits with the reference's
formulas (MilliCPUToShares: shares = max(2, milli*1024/1000);
MilliCPUToQuota: quota = milli*100000/1000) — so node-level resource
enforcement, the eviction manager's accounting, and operator
introspection see the hierarchy the reference kernel would.

Node allocatable (``cm/node_container_manager_linux.go``):
allocatable = capacity − kube-reserved − system-reserved; enforced by
admission (``_admit``) exactly like the reference's node allocatable
enforcement rejects pods past the kubepods cgroup limit.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.types import compute_pod_resource_request

GUARANTEED = "Guaranteed"
BURSTABLE = "Burstable"
BEST_EFFORT = "BestEffort"

MIN_SHARES = 2
SHARES_PER_CPU = 1024
QUOTA_PERIOD = 100_000


def pod_qos(pod: Pod) -> str:
    """qos.go GetPodQOS: Guaranteed iff every container has cpu+memory
    limits equal to its requests; BestEffort iff no container has any
    request or limit; else Burstable."""
    requests_seen = False
    limits_seen = False
    guaranteed = True
    for c in pod.spec.containers + pod.spec.init_containers:
        req = c.resources.requests
        lim = c.resources.limits
        for res in ("cpu", "memory"):
            r, l = req.get(res), lim.get(res)
            if r is not None:
                requests_seen = True
            if l is not None:
                limits_seen = True
            # milli-precision compare ("500m" vs "1" must differ;
            # Quantity.value() rounds sub-unit cpu up)
            if l is None or r is None or \
                    r.milli_value() != l.milli_value():
                guaranteed = False
    if not requests_seen and not limits_seen:
        return BEST_EFFORT
    if guaranteed:
        return GUARANTEED
    return BURSTABLE


def milli_cpu_to_shares(milli: int) -> int:
    """cm/helpers_linux.go MilliCPUToShares."""
    if milli <= 0:
        return MIN_SHARES
    return max(MIN_SHARES, milli * SHARES_PER_CPU // 1000)


def milli_cpu_to_quota(milli: int) -> int:
    """cm/helpers_linux.go MilliCPUToQuota (period 100ms); 0 = no
    quota (unlimited)."""
    if milli <= 0:
        return 0
    return milli * QUOTA_PERIOD // 1000


@dataclass
class CgroupConfig:
    """One node in the tree (cm/types.go CgroupConfig)."""

    name: str
    parent: str = ""
    cpu_shares: int = MIN_SHARES
    cpu_quota: int = 0      # 0 = unlimited
    memory_limit: int = 0   # 0 = unlimited
    pods: Dict[str, str] = field(default_factory=dict)  # uid -> qos


class ContainerManager:
    """The in-process cgroup hierarchy + QoS manager."""

    ROOT = "/kubepods"

    def __init__(self, capacity_cpu_milli: int = 0,
                 capacity_memory: int = 0,
                 kube_reserved_cpu_milli: int = 0,
                 kube_reserved_memory: int = 0,
                 system_reserved_cpu_milli: int = 0,
                 system_reserved_memory: int = 0):
        self._lock = threading.Lock()
        self.capacity_cpu = capacity_cpu_milli
        self.capacity_memory = capacity_memory
        self.allocatable_cpu = max(
            0, capacity_cpu_milli - kube_reserved_cpu_milli
            - system_reserved_cpu_milli,
        )
        self.allocatable_memory = max(
            0, capacity_memory - kube_reserved_memory
            - system_reserved_memory,
        )
        self.cgroups: Dict[str, CgroupConfig] = {}
        # the qos tiers (qosContainerManager Start): Guaranteed pods sit
        # directly under /kubepods; burstable/besteffort get sub-tiers
        self._ensure(self.ROOT, "", cpu_shares=milli_cpu_to_shares(
            self.allocatable_cpu), memory_limit=self.allocatable_memory)
        self._ensure(f"{self.ROOT}/burstable", self.ROOT)
        self._ensure(f"{self.ROOT}/besteffort", self.ROOT,
                     cpu_shares=MIN_SHARES)
        self._pod_cgroup: Dict[str, str] = {}   # uid -> cgroup path
        self._pod_usage: Dict[str, tuple] = {}  # uid -> (cpu, mem)

    def _ensure(self, name: str, parent: str, cpu_shares: int = MIN_SHARES,
                cpu_quota: int = 0, memory_limit: int = 0) -> CgroupConfig:
        cg = self.cgroups.get(name)
        if cg is None:
            cg = CgroupConfig(name=name, parent=parent,
                              cpu_shares=cpu_shares, cpu_quota=cpu_quota,
                              memory_limit=memory_limit)
            self.cgroups[name] = cg
        return cg

    # -- admission (node allocatable enforcement) ----------------------
    def admit(self, pod: Pod) -> Optional[str]:
        """None = admitted; else the rejection reason. The reference
        enforces node allocatable via the /kubepods cgroup limits; here
        the running pods' requests are summed against allocatable."""
        req = compute_pod_resource_request(pod)
        with self._lock:
            used_cpu = sum(u[0] for u in self._pod_usage.values())
            used_mem = sum(u[1] for u in self._pod_usage.values())
            if self.allocatable_cpu and \
                    used_cpu + req.milli_cpu > self.allocatable_cpu:
                return (
                    f"OutOfcpu: {used_cpu}+{req.milli_cpu}m over "
                    f"allocatable {self.allocatable_cpu}m"
                )
            if self.allocatable_memory and \
                    used_mem + req.memory > self.allocatable_memory:
                return (
                    f"OutOfmemory: {used_mem}+{req.memory} over "
                    f"allocatable {self.allocatable_memory}"
                )
        return None

    # -- pod cgroup lifecycle (podContainerManager) --------------------
    def create_pod_cgroup(self, pod: Pod) -> str:
        qos = pod_qos(pod)
        req = compute_pod_resource_request(pod)
        limits_cpu = 0
        limits_mem = 0
        for c in pod.spec.containers:
            lc = c.resources.limits.get("cpu")
            lm = c.resources.limits.get("memory")
            if lc is not None:
                limits_cpu += int(lc.milli_value())
            if lm is not None:
                limits_mem += int(lm.value())
        parent = {
            GUARANTEED: self.ROOT,
            BURSTABLE: f"{self.ROOT}/burstable",
            BEST_EFFORT: f"{self.ROOT}/besteffort",
        }[qos]
        path = f"{parent}/pod{pod.uid}"
        with self._lock:
            self._ensure(
                path, parent,
                cpu_shares=milli_cpu_to_shares(req.milli_cpu),
                cpu_quota=milli_cpu_to_quota(limits_cpu),
                memory_limit=limits_mem,
            )
            self.cgroups[parent].pods[pod.uid] = qos
            self._pod_cgroup[pod.uid] = path
            self._pod_usage[pod.uid] = (req.milli_cpu, req.memory)
            self._update_qos_tiers_locked()
        return path

    def delete_pod_cgroup(self, uid: str) -> None:
        with self._lock:
            path = self._pod_cgroup.pop(uid, None)
            self._pod_usage.pop(uid, None)
            if path is None:
                return
            cg = self.cgroups.pop(path, None)
            if cg is not None:
                parent = self.cgroups.get(cg.parent)
                if parent is not None:
                    parent.pods.pop(uid, None)
            self._update_qos_tiers_locked()

    def _update_qos_tiers_locked(self) -> None:
        """qos_container_manager_linux.go setCPUCgroupConfig: the
        burstable tier's shares track the sum of its pods' cpu
        requests; besteffort stays at the 2-share floor."""
        burst = self.cgroups[f"{self.ROOT}/burstable"]
        total = 0
        for uid in burst.pods:
            total += self._pod_usage.get(uid, (0, 0))[0]
        burst.cpu_shares = milli_cpu_to_shares(total)

    # -- introspection --------------------------------------------------
    def qos_of(self, uid: str) -> Optional[str]:
        with self._lock:
            path = self._pod_cgroup.get(uid)
            if path is None:
                return None
            cg = self.cgroups.get(path)
            parent = self.cgroups.get(cg.parent) if cg else None
            return parent.pods.get(uid) if parent else None

    def pod_cgroup(self, uid: str) -> Optional[CgroupConfig]:
        with self._lock:
            path = self._pod_cgroup.get(uid)
            return self.cgroups.get(path) if path else None

    def tree(self) -> Dict[str, CgroupConfig]:
        with self._lock:
            return dict(self.cgroups)
