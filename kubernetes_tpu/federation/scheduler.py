"""Federation scheduler: clusters are what-if solver columns.

The autoscaler already asks "would a new node help?" by appending
virtual node COLUMNS to the encoded planes and score-penalizing them
(``ops/solver.py`` ``solve_whatif``); the federation tier asks the
same question at cluster granularity — "which CLUSTER should take this
workload?" — with one synthetic node per cluster whose allocatable is
the cluster's remaining capacity (``CapacityLedger``). The penalty
tiers order placement preference exactly like the autoscaler's
real > upcoming > virtual ladder:

    home cluster (0) < remote cluster (REMOTE_CLUSTER_PENALTY)
        < saturated cluster (SATURATION_PENALTY) < dead (disabled)

so a workload lands at home while home has room, spills to a sibling
when home saturates (the spillover headline), and never routes to a
dead cell at all. Gangs fold into ONE synthetic unit pod (summed
request), so a gang is atomic by construction — the solver cannot
split what it sees as a single pod.

``place`` raises :class:`FederationUnavailable` when the layer is
marked down; callers (``FederatedClusterClient``) then fall back to
home routing — federation is an optimizer, never a single point of
failure (the degradation invariant).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from kubernetes_tpu.federation.ledger import CapacityLedger

# the gang labels trace.pod_dict stamps (the coscheduling contract)
GANG_NAME_LABEL = "pod-group.scheduling.k8s.io/name"

# Penalty tiers (same float32-safe magnitudes as the autoscaler's:
# real scores are O(hundreds), VIRTUAL_NODE_PENALTY is 1e6). Remote
# must stay far below saturation so a saturated home loses to a
# healthy sibling, and saturation must stay below the virtual tier so
# differential tests can still stack both without overflow.
REMOTE_CLUSTER_PENALTY = 1.0e4
SATURATION_PENALTY = 5.0e5


class FederationUnavailable(RuntimeError):
    """The federation layer is down; each cell schedules locally."""


@dataclass(frozen=True)
class FederationPolicy:
    """Placement knobs for the cluster-granularity solve."""

    remote_penalty: float = REMOTE_CLUSTER_PENALTY
    saturation_penalty: float = SATURATION_PENALTY
    saturation_threshold: float = 0.85   # utilization → penalized
    # the numpy per-unit oracle by default: a placement decision is a
    # K-column solve over a handful of units, where jit dispatch would
    # dominate; serial=False routes the identical question through the
    # device solve_whatif (the differential tests hold them equal)
    serial: bool = True
    pad_pods: int = 64


@dataclass
class PlacementUnit:
    """One atomically-placed workload: a single pod, or a whole gang
    folded into one summed request."""

    pods: List = field(default_factory=list)
    gang: str = ""
    milli: int = 0
    mem: int = 0
    namespace: str = "default"


@dataclass
class Placement:
    """One unit's verdict: the chosen cluster (None = no live cluster
    fits; the caller parks it at home, where it pends — never lost)."""

    unit: PlacementUnit
    cluster: Optional[int]
    home: Optional[int]

    @property
    def spilled(self) -> bool:
        return (self.cluster is not None and self.home is not None
                and self.cluster != self.home)


def group_units(pods: Sequence) -> List[PlacementUnit]:
    """Fold a pod batch into placement units: gang members (the
    ``pod-group.scheduling.k8s.io/name`` label) merge into one unit
    with the summed resource request; everything else is a singleton.
    Order-preserving for determinism."""
    from kubernetes_tpu.scheduler.types import (
        compute_pod_resource_request,
    )

    units: List[PlacementUnit] = []
    by_gang: Dict[str, PlacementUnit] = {}
    for pod in pods:
        req = compute_pod_resource_request(pod)
        gang = (pod.metadata.labels or {}).get(GANG_NAME_LABEL, "")
        if gang:
            unit = by_gang.get(gang)
            if unit is None:
                unit = PlacementUnit(
                    gang=gang,
                    namespace=pod.metadata.namespace or "default")
                by_gang[gang] = unit
                units.append(unit)
        else:
            unit = PlacementUnit(
                namespace=pod.metadata.namespace or "default")
            units.append(unit)
        unit.pods.append(pod)
        unit.milli += req.milli_cpu
        unit.mem += req.memory
    return units


class FederationScheduler:
    """Scores candidate clusters with the existing what-if machinery
    and places units atomically. One instance serves one federation."""

    def __init__(self, ledger: CapacityLedger,
                 policy: Optional[FederationPolicy] = None,
                 home_of: Optional[Callable[[str], Optional[int]]] = None):
        self.ledger = ledger
        self.policy = policy or FederationPolicy()
        # namespace → home cluster (None = no affinity, place freely);
        # the ClusterRebalancer's split/move actions rewrite this map
        self.home_of = home_of or (lambda ns: None)
        self._down = False
        self.solves = 0
        self.placed_units = 0
        self.unplaced_units = 0

    # -- degradation switch (the chaos family kills the layer) ---------
    def set_down(self, down: bool) -> None:
        self._down = bool(down)

    @property
    def down(self) -> bool:
        return self._down

    # -- the placement decision ----------------------------------------
    def place(self, pods: Sequence,
              trace_uid: str = "") -> List[Placement]:
        """Place a pod batch across the federation. Returns one
        :class:`Placement` per unit (gangs fold; see ``group_units``).
        Emits a ``fed.place`` span so placement cost attributes to the
        sampled pod's critical path (the seam-phase contract)."""
        if self._down:
            raise FederationUnavailable("federation layer is down")
        from kubernetes_tpu.observability import get_tracer

        t0 = time.monotonic()
        units = group_units(pods)
        by_home: Dict[Optional[int], List[PlacementUnit]] = {}
        for u in units:
            home = self.home_of(u.namespace)
            if home is not None and not self.ledger.alive(home):
                home = None
            by_home.setdefault(home, []).append(u)
        out: List[Placement] = []
        for home, group in by_home.items():
            out.extend(self._place_group(group, home))
        spilled = sum(1 for p in out if p.spilled)
        get_tracer().record(
            "fed.place", t0, trace=trace_uid,
            units=len(units), pods=len(list(pods)),
            clusters=len(self.ledger.clusters()), spilled=spilled,
            unplaced=sum(1 for p in out if p.cluster is None))
        return out

    def _place_group(self, units: List[PlacementUnit],
                     home: Optional[int]) -> List[Placement]:
        """One solve for all units sharing a home cluster (penalties
        are per-COLUMN, so a solve can express only one home)."""
        clusters = self.ledger.clusters()
        live = set(self.ledger.live_clusters())
        if not live:
            self.unplaced_units += len(units)
            return [Placement(unit=u, cluster=None, home=home)
                    for u in units]
        cluster, batch, col_cluster = self._encode(clusters, units)
        penalties: Dict[int, float] = {}
        disabled: List[int] = []
        for col, cid in enumerate(col_cluster):
            if cid not in live:
                disabled.append(col)
                continue
            pen = 0.0
            if home is not None and cid != home:
                pen += self.policy.remote_penalty
            if self.ledger.utilization(cid) \
                    >= self.policy.saturation_threshold:
                pen += self.policy.saturation_penalty
            if pen:
                penalties[col] = pen
        assignments = self._solve(cluster, batch, penalties, disabled)
        self.solves += 1
        out: List[Placement] = []
        for i, u in enumerate(units):
            col = int(assignments[i])
            cid = col_cluster[col] if 0 <= col < len(col_cluster) \
                else None
            if cid is not None:
                self.ledger.note_admitted(cid, u.pods)
                self.placed_units += 1
            else:
                self.unplaced_units += 1
            out.append(Placement(unit=u, cluster=cid, home=home))
        return out

    # -- encode clusters-as-nodes, units-as-pods ------------------------
    def _encode(self, clusters: List[int],
                units: List[PlacementUnit]):
        from kubernetes_tpu.api.types import Node, Pod
        from kubernetes_tpu.ops.encode import BatchEncoder
        from kubernetes_tpu.scheduler.snapshot import new_snapshot

        nodes = []
        for cid in clusters:
            milli, mem = self.ledger.remaining(cid)
            nodes.append(Node.from_dict({
                "metadata": {
                    "name": f"cluster-{cid}",
                    "labels": {
                        "kubernetes.io/hostname": f"cluster-{cid}"},
                },
                "status": {"capacity": {
                    "cpu": f"{max(milli, 0)}m",
                    "memory": str(max(mem, 0)),
                    # a cluster-node holds thousands of pods; the
                    # per-node 110 cap is a kubelet property, not a
                    # cluster one
                    "pods": "1000000"}},
            }))
        unit_pods = []
        for j, u in enumerate(units):
            pod = Pod.from_dict({
                "metadata": {"name": f"unit-{j}",
                             "namespace": u.namespace},
                "spec": {"containers": [
                    {"name": "c", "image": "registry/fake:1",
                     "resources": {"requests": {
                         "cpu": f"{u.milli}m",
                         "memory": str(u.mem)}}}]},
            })
            pod.metadata.uid = f"fu-{j}"
            unit_pods.append(pod)
        enc = BatchEncoder(new_snapshot([], nodes))
        cluster, batch = enc.encode(unit_pods,
                                    pad_pods=self.policy.pad_pods)
        # column → cluster id by name (the encoder preserves order,
        # but mapping by name keeps this correct under any reorder)
        by_name = {f"cluster-{cid}": cid for cid in clusters}
        col_cluster = [by_name.get(n) for n in cluster.node_names]
        return cluster, batch, col_cluster

    def _solve(self, cluster, batch, penalties: Dict[int, float],
               disabled: List[int]):
        from kubernetes_tpu.ops.solver import SolverParams

        if self.policy.serial:
            from kubernetes_tpu.autoscaler.simulator import (
                _serial_whatif,
            )

            solver = _serial_whatif
        else:
            from kubernetes_tpu.ops.solver import solve_whatif

            solver = solve_whatif
        assignments, _counts = solver(
            cluster, batch, SolverParams(),
            deprioritized_cols=penalties, disabled_cols=disabled)
        return assignments
