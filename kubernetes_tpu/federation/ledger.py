"""Per-cluster capacity + write ledgers (the federation tier's facts).

The partitioned control plane already keeps per-slot write ledgers
(``PartitionRebalancer`` reads them as rate deltas); the federated
tier needs the same discipline one level up — per-CLUSTER ledgers that
answer the two questions the federation layer asks:

- **placement**: how much capacity does each cluster have left right
  now (the what-if solver's synthetic cluster-node allocatable), and
  is it alive at all;
- **rebalancing**: which cluster / which tenant is taking the writes
  (``ClusterRebalancer`` feeds these counters to ``plan_rebalance``
  exactly like slot ledgers).

Capacity is observed (``refresh_from`` over a cluster's node/pod
lists) plus reserved (``note_admitted`` for placements the federation
layer has routed but the cell's own scheduler hasn't bound yet).
Reservations are pod-keyed: a refresh drops exactly the reservations
its observed pod list accounts for — a reservation noted AFTER the
list snapshot was read survives the refresh, so a placement landing
mid-refresh can never be double-spent (blanket-clearing here once let
the spill storm overcommit the saturated cell by one pod). jax-free
by design — the harness's liveness-probe thread and the REST children
import this.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.scheduler.types import (
    Resource,
    compute_pod_resource_request,
)


@dataclass
class ClusterCapacity:
    """One cluster's observed capacity snapshot + liveness."""

    cluster: int
    alive: bool = True
    nodes: int = 0
    allocatable_milli: int = 0
    allocatable_mem: int = 0
    used_milli: int = 0
    used_mem: int = 0
    bound: int = 0
    pending: int = 0
    # in-flight admissions the observed pod list does not account for
    # yet; aggregates of the ledger's pod-keyed reservation map, and
    # decayed per-pod as refreshes observe each routed pod
    admitted_milli: int = 0
    admitted_mem: int = 0
    admitted_pods: int = 0

    def remaining(self) -> Tuple[int, int]:
        """(milli-cpu, memory bytes) still uncommitted — observed usage
        AND in-flight reservations both subtract, so two placement
        rounds between refreshes cannot both spend the same capacity."""
        milli = self.allocatable_milli - self.used_milli \
            - self.admitted_milli
        mem = self.allocatable_mem - self.used_mem - self.admitted_mem
        return max(milli, 0), max(mem, 0)

    def utilization(self) -> float:
        """Committed share of cpu capacity (reservations included); a
        cluster with no observed capacity reads fully utilized — the
        saturation penalty then steers placements away until a refresh
        says otherwise."""
        if self.allocatable_milli <= 0:
            return 1.0
        return (self.used_milli + self.admitted_milli) \
            / self.allocatable_milli


class CapacityLedger:
    """Thread-safe per-cluster ledgers: capacity for the federation
    scheduler, cumulative write counters (per cluster and per
    namespace) for the ``ClusterRebalancer``'s rate deltas, and
    liveness flags fed by the harness's probe loop."""

    def __init__(self):
        self._lock = threading.Lock()
        self._caps: Dict[int, ClusterCapacity] = {}
        # cluster → pod key → (milli, mem): the in-flight reservations
        # backing the admitted_* aggregates, so a refresh can release
        # exactly the pods its observed list covers
        self._admitted: Dict[int, Dict[str, Tuple[int, int]]] = {}
        self._writes: Dict[int, float] = {}
        self._ns_writes: Dict[str, float] = {}

    @staticmethod
    def _pod_key(pod) -> str:
        return pod.metadata.uid or (
            f"{pod.metadata.namespace or 'default'}/{pod.metadata.name}")

    # -- membership / liveness -----------------------------------------
    def register(self, cluster: int) -> None:
        with self._lock:
            if cluster not in self._caps:
                self._caps[cluster] = ClusterCapacity(cluster=cluster)
                self._writes[cluster] = 0.0

    def clusters(self) -> List[int]:
        with self._lock:
            return sorted(self._caps)

    def live_clusters(self) -> List[int]:
        with self._lock:
            return sorted(c for c, cap in self._caps.items()
                          if cap.alive)

    def dead_clusters(self) -> List[int]:
        with self._lock:
            return sorted(c for c, cap in self._caps.items()
                          if not cap.alive)

    def alive(self, cluster: int) -> bool:
        with self._lock:
            cap = self._caps.get(cluster)
            return cap is not None and cap.alive

    def mark_dead(self, cluster: int) -> None:
        with self._lock:
            if cluster in self._caps:
                self._caps[cluster].alive = False

    def mark_alive(self, cluster: int) -> None:
        with self._lock:
            if cluster in self._caps:
                self._caps[cluster].alive = True

    # -- capacity -------------------------------------------------------
    def refresh_from(self, cluster: int, nodes, pods) -> ClusterCapacity:
        """Recompute a cluster's capacity from its live node/pod lists
        (one poll tick of the harness's ledger thread, or the
        in-process cells' direct store reads). Releases the in-flight
        reservations the observed pod list accounts for — and ONLY
        those: a pod routed after the caller read its list is not in
        ``pods`` yet, and clearing its reservation anyway would let the
        next placement spend the same capacity twice."""
        alloc_milli = alloc_mem = 0
        for node in nodes:
            r = Resource.from_resource_list(node.status.allocatable)
            alloc_milli += r.milli_cpu
            alloc_mem += r.memory
        used_milli = used_mem = bound = pending = 0
        observed = set()
        for pod in pods:
            req = compute_pod_resource_request(pod)
            observed.add(self._pod_key(pod))
            if pod.spec.node_name:
                bound += 1
                used_milli += req.milli_cpu
                used_mem += req.memory
            else:
                pending += 1
                # a pending pod is capacity already spoken for on this
                # cluster — its own scheduler will bind it
                used_milli += req.milli_cpu
                used_mem += req.memory
        with self._lock:
            cap = self._caps.setdefault(
                cluster, ClusterCapacity(cluster=cluster))
            cap.nodes = len(list(nodes)) if not hasattr(nodes, "__len__") \
                else len(nodes)
            cap.allocatable_milli = alloc_milli
            cap.allocatable_mem = alloc_mem
            cap.used_milli = used_milli
            cap.used_mem = used_mem
            cap.bound = bound
            cap.pending = pending
            slot = self._admitted.get(cluster)
            if slot:
                for key in [k for k in slot if k in observed]:
                    del slot[key]
            ents = self._admitted.get(cluster) or {}
            cap.admitted_milli = sum(m for m, _ in ents.values())
            cap.admitted_mem = sum(me for _, me in ents.values())
            cap.admitted_pods = len(ents)
            return ClusterCapacity(**vars(cap))

    def note_admitted(self, cluster: int, pods) -> None:
        """Reserve capacity for pods the federation layer just routed
        to ``cluster`` (and count the writes for the rebalancer).
        Reservations are pod-keyed; re-reserving the same pod replaces
        its entry rather than double-counting it."""
        entries: List[Tuple[str, int, int]] = []
        ns_counts: Dict[str, int] = {}
        for pod in pods:
            req = compute_pod_resource_request(pod)
            entries.append(
                (self._pod_key(pod), req.milli_cpu, req.memory))
            ns = pod.metadata.namespace or "default"
            ns_counts[ns] = ns_counts.get(ns, 0) + 1
        with self._lock:
            cap = self._caps.setdefault(
                cluster, ClusterCapacity(cluster=cluster))
            slot = self._admitted.setdefault(cluster, {})
            for key, milli, mem in entries:
                old = slot.get(key)
                if old is not None:
                    cap.admitted_milli -= old[0]
                    cap.admitted_mem -= old[1]
                    cap.admitted_pods -= 1
                slot[key] = (milli, mem)
                cap.admitted_milli += milli
                cap.admitted_mem += mem
                cap.admitted_pods += 1
            self._writes[cluster] = \
                self._writes.get(cluster, 0.0) + len(entries)
            for ns, c in ns_counts.items():
                self._ns_writes[ns] = self._ns_writes.get(ns, 0.0) + c

    def capacity(self, cluster: int) -> Optional[ClusterCapacity]:
        with self._lock:
            cap = self._caps.get(cluster)
            return ClusterCapacity(**vars(cap)) if cap is not None \
                else None

    def remaining(self, cluster: int) -> Tuple[int, int]:
        with self._lock:
            cap = self._caps.get(cluster)
            return cap.remaining() if cap is not None else (0, 0)

    def utilization(self, cluster: int) -> float:
        with self._lock:
            cap = self._caps.get(cluster)
            return cap.utilization() if cap is not None else 1.0

    # -- write ledgers (the rebalancer's observation surface) -----------
    def write_counts(self) -> Tuple[Dict[int, float], Dict[str, float]]:
        """CUMULATIVE (cluster → writes, namespace → writes) counters;
        the rebalancer differences consecutive ticks into rates, the
        same contract the per-slot ledgers honor."""
        with self._lock:
            return dict(self._writes), dict(self._ns_writes)
