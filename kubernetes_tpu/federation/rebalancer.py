"""ClusterRebalancer: ``plan_rebalance`` one level up.

The partition rebalancer's planner is a pure function over (slot
rates, namespace rates, topology, liveness) — nothing in it knows a
slot is an apiserver partition. At the federation tier the same
decision shapes recur with clusters in the slot role:

- a dead CLUSTER → **failover** (re-place its pods onto survivors;
  beats everything, exactly like a silent shard);
- one tenant dominating the fleet's writes → **split** (release the
  namespace from home-cluster affinity so placement spreads it);
- one hot cluster, siblings cold → **move** (re-home the hot
  cluster's hottest namespace onto the coldest sibling);
- buy/retire → recorded no-ops here (the fleet of clusters is fixed
  capital; the per-cluster NODE autoscalers own elasticity).

:class:`ClusterRebalancer` is a genuine subclass of
``PartitionRebalancer`` — same tick/differencing/sustain/cooldown
loop, same pure planner — fed by a driver that adapts the federation
surfaces (``CapacityLedger`` write counters, ``HomeMap``,
``FederatedClusterClient.failover_cluster``) to the driver contract.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from kubernetes_tpu.autoscaler.partitions import (
    PartitionGroup,
    PartitionRebalancer,
    RebalancePolicy,
)
from kubernetes_tpu.federation.client import FederatedClusterClient


class _ClusterTopologyView:
    """The planner's topology protocol with clusters as slots: K
    one-slot partitions, slot i owned by partition i. ``spread`` is
    the HomeMap's spread set (namespaces already released)."""

    def __init__(self, clusters: List[int], dead: List[int],
                 spread: set):
        self.partitions = (max(clusters) + 1) if clusters else 0
        self.retired = {p for p in range(self.partitions)
                        if p not in clusters}
        # owner[slot] = slot: a cluster IS its own slot
        self.owner = list(range(self.partitions))
        self.spread = set(spread)
        self._dead = set(dead)

    def slots_of_partition(self, p: int) -> List[int]:
        if p in self.retired or p in self._dead:
            return []
        return [p]


class _FederationDriver:
    """Adapts the federation tier to the rebalancer driver contract."""

    def __init__(self, client: FederatedClusterClient):
        self.client = client
        self.ledger = client.ledger
        self.home_map = client.home_map
        # a dead CLUSTER stays dead (unlike a partition, which failover
        # restarts) — report it dead exactly once or the planner would
        # re-fire failover every tick forever
        self._failed_over: set = set()

    def observe(self) -> dict:
        cluster_writes, ns_writes = self.ledger.write_counts()
        all_dead = self.ledger.dead_clusters()
        dead = [c for c in all_dead if c not in self._failed_over]
        # the topology keeps EVERY dead cluster slotless (a failed-over
        # cell must never look like a cold move target); only the
        # planner's failover trigger sees each death once
        topo = _ClusterTopologyView(
            self.ledger.clusters(), all_dead, self.home_map.spread)
        return {"epoch": 0, "topology": topo,
                "slot_writes": dict(cluster_writes),
                "ns_writes": dict(ns_writes), "dead": dead}

    def federate(self) -> None:
        """No metrics federation hop: the ledger is already the
        merged view."""

    def apply(self, action: Dict[str, Any]) -> dict:
        op = action["op"]
        if op == "failover":
            cid = action["partition"]
            self._failed_over.add(cid)
            replaced = self.client.failover_cluster(cid)
            return {"cluster": cid, "replaced": replaced}
        if op == "split":
            ns = action["namespace"]
            self.home_map.spread.add(ns)
            return {"namespace": ns, "spread": True}
        if op == "move":
            # assignments = {hot cluster: coldest cluster}; re-home the
            # hot cluster's dominant namespace onto the target
            moved: Dict[str, int] = {}
            for src, dst in action["assignments"].items():
                ns = self._hottest_ns_homed_on(src)
                if ns is not None:
                    self.home_map.overrides[ns] = dst
                    moved[ns] = dst
            return {"moved": moved}
        # buy/retire: the cluster fleet is fixed capital — record the
        # pressure signal, change nothing
        return {"noop": op}

    def _hottest_ns_homed_on(self, cid: int) -> Optional[str]:
        _, ns_writes = self.ledger.write_counts()
        best, best_rate = None, 0.0
        for ns, rate in ns_writes.items():
            if self.home_map.home_of(ns) == cid and rate > best_rate:
                best, best_rate = ns, rate
        return best


class ClusterRebalancer(PartitionRebalancer):
    """The partition rebalancer's loop pointed at clusters."""

    def __init__(self, client: FederatedClusterClient,
                 group: Optional[PartitionGroup] = None,
                 policy: Optional[RebalancePolicy] = None,
                 interval_s: float = 0.5):
        driver = _FederationDriver(client)
        super().__init__(
            driver,
            group=group or PartitionGroup(name="federation",
                                          max_partitions=64),
            policy=policy,
            interval_s=interval_s)
