"""Federated multi-cluster tier: K independent partitioned clusters
behind a cross-cluster client, a what-if federation scheduler, and a
cluster-granularity rebalancer. Federation is an optimizer, never a
single point of failure — every cell keeps scheduling locally when
this layer is down."""

from kubernetes_tpu.federation.client import (
    FederatedClusterClient,
    HomeMap,
)
from kubernetes_tpu.federation.ledger import (
    CapacityLedger,
    ClusterCapacity,
)
from kubernetes_tpu.federation.rebalancer import ClusterRebalancer
from kubernetes_tpu.federation.scheduler import (
    GANG_NAME_LABEL,
    REMOTE_CLUSTER_PENALTY,
    SATURATION_PENALTY,
    FederationPolicy,
    FederationScheduler,
    FederationUnavailable,
    Placement,
    PlacementUnit,
    group_units,
)

__all__ = [
    "CapacityLedger",
    "ClusterCapacity",
    "ClusterRebalancer",
    "FederatedClusterClient",
    "FederationPolicy",
    "FederationScheduler",
    "FederationUnavailable",
    "GANG_NAME_LABEL",
    "HomeMap",
    "Placement",
    "PlacementUnit",
    "REMOTE_CLUSTER_PENALTY",
    "SATURATION_PENALTY",
    "group_units",
]
