"""Cross-cluster client: route / fan-in / merge at cluster granularity.

``RestClusterClient`` routes each object to the partition that owns
its hash slot, fans list/watch over every partition, and merges the
results behind one store-shaped surface. This module is the same shape
one level up: a :class:`FederatedClusterClient` routes each CREATE to
the cluster the federation scheduler chose, fans list/watch over every
live cluster, and remembers the route so deletes and failover find the
object again. The replay engine (and anything else speaking the store
surface) drives a whole federation exactly like one cluster.

Robustness contracts:

- **never lost**: a unit no live cluster fits falls back to its home
  cluster and PENDS there (its own scheduler binds it when capacity
  frees) — routing never drops a pod;
- **gang continuity**: the first chunk carrying a gang member decides
  the gang's cluster; later chunks route to the recorded home, so a
  gang can never straddle clusters across chunk boundaries;
- **failover**: ``failover_cluster(cid)`` re-creates the dead cell's
  registered pods (unbound copies, same NAMES — the chaos suites'
  lost-pod invariant is name-based) on survivors and stops only the
  dead cell's watch, so relists stay confined to the dead cluster;
- **degradation**: when the federation scheduler is down (or raises),
  routing falls back to home-cluster hashing — each cell keeps
  scheduling locally; federation is an optimizer, never a SPOF.
"""

from __future__ import annotations

import copy
import threading
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import shallow_copy
from kubernetes_tpu.client.restcluster import elect_trace_uid
from kubernetes_tpu.federation.ledger import CapacityLedger
from kubernetes_tpu.federation.scheduler import (
    GANG_NAME_LABEL,
    FederationScheduler,
    FederationUnavailable,
)
from kubernetes_tpu.harness.burst import create_chunk


class HomeMap:
    """Namespace → home-cluster affinity, deterministic by default
    (crc32 hash over the registered clusters) and rewritable by the
    ``ClusterRebalancer``: ``split`` releases a namespace to free
    placement (the spread set), ``move`` pins it to a new home."""

    def __init__(self, clusters: Sequence[int],
                 pin: Optional[Dict[str, int]] = None):
        self._clusters = sorted(clusters)
        self.pin = dict(pin or {})
        self.spread: set = set()
        self.overrides: Dict[str, int] = {}

    def home_of(self, namespace: str) -> Optional[int]:
        ns = namespace or "default"
        if ns in self.spread:
            return None
        if ns in self.overrides:
            return self.overrides[ns]
        if ns in self.pin:
            return self.pin[ns]
        if not self._clusters:
            return None
        return self._clusters[
            zlib.crc32(ns.encode()) % len(self._clusters)]


def _unbound_copy(pod):
    """A re-creatable copy with the bind cleared (the simulator's
    scale-down discipline): shallow copy + fresh spec so the original
    object is never mutated."""
    p = shallow_copy(pod)
    p.spec = copy.copy(pod.spec)
    p.spec.node_name = ""
    return p


class _FederatedWatchHandle:
    """One stop() over the per-cluster watch handles."""

    def __init__(self, client: "FederatedClusterClient", key: int):
        self._client = client
        self._key = key

    def stop(self) -> None:
        self._client._stop_watch_group(self._key)


class FederatedClusterClient:
    """Store-shaped client over K clusters. ``clusters`` maps cluster
    id → any store-surface client (``ClusterStore`` in-process,
    ``RestClusterClient`` against a spawned cell)."""

    def __init__(self, clusters: Dict[int, object],
                 scheduler: FederationScheduler,
                 ledger: CapacityLedger,
                 home_map: Optional[HomeMap] = None):
        self.clusters = dict(clusters)
        self.scheduler = scheduler
        self.ledger = ledger
        self.home_map = home_map or HomeMap(sorted(self.clusters))
        for cid in self.clusters:
            ledger.register(cid)
        self._lock = threading.Lock()
        # (namespace, name) → cluster id, the route registry
        self._route: Dict[Tuple[str, str], int] = {}
        # (namespace, name) → unbound copy, the failover inventory
        self._inventory: Dict[Tuple[str, str], object] = {}
        self._gang_home: Dict[str, int] = {}
        # watch fan-out bookkeeping: group key → {cid: handle}
        self._watch_groups: Dict[int, Dict[int, object]] = {}
        self._watch_seq = 0
        # counters (the diag/bench surface)
        self.placements = 0
        self.spilled = 0
        self.fallback_placements = 0
        self.failovers = 0
        self.failover_replaced = 0

    # ------------------------------------------------------------------
    # routing helpers

    def _fallback_home(self, namespace: str) -> int:
        """Degradation-mode routing: the namespace's home if alive,
        else a deterministic hash over the live clusters — every
        client elects the same survivor without coordination."""
        live = self.ledger.live_clusters() or sorted(self.clusters)
        home = self.home_map.home_of(namespace)
        if home is not None and home in live:
            return home
        ns = namespace or "default"
        return live[zlib.crc32(b"fed:" + ns.encode()) % len(live)]

    def route_of(self, namespace: str, name: str) -> Optional[int]:
        with self._lock:
            return self._route.get((namespace or "default", name))

    # ------------------------------------------------------------------
    # store surface: create

    def create_pods(self, pods: Sequence) -> List:
        """Route one create chunk across the federation. Gangs whose
        home is already recorded ride straight there (continuity);
        the rest go through the federation scheduler, falling back to
        home hashing when the layer is down or errors."""
        pods = list(pods)
        routed: Dict[int, List] = {}
        to_place: List = []
        live = set(self.ledger.live_clusters())
        with self._lock:
            for pod in pods:
                gang = (pod.metadata.labels or {}).get(
                    GANG_NAME_LABEL, "")
                cid = self._gang_home.get(gang) if gang else None
                if cid is not None and cid in live:
                    routed.setdefault(cid, []).append(pod)
                else:
                    to_place.append(pod)
        # gang-continuity routes bypass the scheduler, so reserve their
        # capacity here (scheduler/fallback paths reserve their own)
        for cid, group in routed.items():
            self.ledger.note_admitted(cid, group)
        if to_place:
            for cid, placed in self._place(to_place).items():
                routed.setdefault(cid, []).extend(placed)
        created: List = []
        stranded: List = []
        for cid, group in sorted(routed.items()):
            for acid, sent in self._send(cid, group).items():
                created.extend(sent)
                with self._lock:
                    # liveness re-checked INSIDE the registry lock:
                    # ``failover_cluster`` marks dead strictly before
                    # its sweep takes this lock, so a route recorded
                    # after the sweep must observe the death here —
                    # the create-vs-failover race cannot strand a pod
                    # on a dead cell unnoticed
                    alive = self.ledger.alive(acid)
                    for pod in sent:
                        key = (pod.metadata.namespace or "default",
                               pod.metadata.name)
                        if not alive:
                            stranded.append(pod)
                            continue
                        self._route[key] = acid
                        self._inventory[key] = _unbound_copy(pod)
                        gang = (pod.metadata.labels or {}).get(
                            GANG_NAME_LABEL, "")
                        if gang:
                            self._gang_home[gang] = acid
                    self.placements += len(sent)
        if stranded:
            # the cell died between routing and registration (its
            # failover sweep predates these routes): rescue now —
            # re-place unbound copies on the survivors
            self.create_pods([_unbound_copy(p) for p in stranded])
        return created

    def _send(self, cid: int, group: List) -> Dict[int, List]:
        """Deliver one routed group, surviving a cell that dies
        between routing and send: mark it dead and re-route the group
        onto survivors (a second failure propagates — the engine's
        send_errors surface owns it). Returns {actual cid: pods}."""
        try:
            create_chunk(self.clusters[cid], group)
            return {cid: group}
        except Exception:  # noqa: BLE001 — the cell died mid-send
            self.ledger.mark_dead(cid)
            rerouted: Dict[int, List] = {}
            for pod in group:
                alt = self._fallback_home(
                    pod.metadata.namespace or "default")
                rerouted.setdefault(alt, []).append(pod)
            for alt, g in rerouted.items():
                create_chunk(self.clusters[alt], g)
                self.ledger.note_admitted(alt, g)
            with self._lock:
                self.fallback_placements += len(group)
            return rerouted

    def _place(self, pods: List) -> Dict[int, List]:
        """Scheduler placement with the degradation fallback; opens a
        ``fed.route`` span around the cross-cluster decision so the
        downstream per-cluster client's ``X-Ktpu-Trace`` parents under
        it (attribution across the hop)."""
        from kubernetes_tpu.observability import get_tracer

        uid = elect_trace_uid(
            p.metadata.uid or f"{p.metadata.namespace}/{p.metadata.name}"
            for p in pods)
        routed: Dict[int, List] = {}
        try:
            with get_tracer().span("fed.route", trace=uid or "",
                                   pods=len(pods)):
                placements = self.scheduler.place(
                    pods, trace_uid=uid or "")
            for pl in placements:
                cid = pl.cluster
                if cid is None:
                    # no live cluster fits: park at home, where the
                    # unit pends until capacity frees — never dropped
                    cid = pl.home if pl.home is not None \
                        else self._fallback_home(pl.unit.namespace)
                if pl.spilled:
                    self.spilled += len(pl.unit.pods)
                routed.setdefault(cid, []).extend(pl.unit.pods)
            return routed
        except Exception as e:  # noqa: BLE001 — ANY scheduler failure
            # degrades to home routing; federation is never a SPOF
            if not isinstance(e, FederationUnavailable):
                import logging

                logging.getLogger(__name__).warning(
                    "federation place failed (%s); home fallback", e)
            by_home: Dict[int, List] = {}
            for pod in pods:
                cid = self._fallback_home(
                    pod.metadata.namespace or "default")
                by_home.setdefault(cid, []).append(pod)
            with self._lock:
                self.fallback_placements += len(pods)
            for cid, group in by_home.items():
                self.ledger.note_admitted(cid, group)
            return by_home

    # ------------------------------------------------------------------
    # store surface: delete / read / watch

    def delete_pod(self, namespace: str, name: str) -> None:
        key = (namespace or "default", name)
        with self._lock:
            cid = self._route.get(key)
            self._inventory.pop(key, None)
        if cid is None:
            return
        self.clusters[cid].delete_pod(namespace, name)

    def delete_pods(self, keys: Sequence[Tuple[str, str]]) -> None:
        by_cid: Dict[int, List[Tuple[str, str]]] = {}
        with self._lock:
            for ns, name in keys:
                key = (ns or "default", name)
                cid = self._route.get(key)
                self._inventory.pop(key, None)
                if cid is not None:
                    by_cid.setdefault(cid, []).append((ns, name))
        for cid, group in by_cid.items():
            self.clusters[cid].delete_pods(group)

    def list_pods(self) -> List:
        out: List = []
        for cid in self.ledger.live_clusters():
            try:
                out.extend(self.clusters[cid].list_pods())
            except Exception:  # noqa: BLE001 — a cell dying mid-list
                pass           # is the chaos family's normal weather
        return out

    def list_nodes(self) -> List:
        out: List = []
        for cid in self.ledger.live_clusters():
            try:
                out.extend(self.clusters[cid].list_nodes())
            except Exception:  # noqa: BLE001
                pass
        return out

    def watch(self, fn: Callable, batch_fn: Optional[Callable] = None):
        """Fan the watch over every live cluster; the returned handle
        stops them all. Per-cluster handles stay addressable so
        ``failover_cluster`` can stop ONLY the dead cell's stream
        (relists confined to the dead cluster)."""
        with self._lock:
            self._watch_seq += 1
            key = self._watch_seq
            group: Dict[int, object] = {}
            self._watch_groups[key] = group
        for cid in self.ledger.live_clusters():
            group[cid] = self.clusters[cid].watch(fn, batch_fn=batch_fn)
        return _FederatedWatchHandle(self, key)

    def _stop_watch_group(self, key: int) -> None:
        with self._lock:
            group = self._watch_groups.pop(key, {})
        for handle in group.values():
            try:
                handle.stop()
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------
    # failover (the cluster-loss chaos path)

    def failover_cluster(self, cid: int,
                         progress: Optional[Callable] = None) -> int:
        """Re-place every pod registered to a dead cluster onto the
        survivors: unbound copies, SAME names (name-keyed lost
        accounting counts the rescue), routed through the federation
        scheduler with the dead column disabled. Stops the dead cell's
        watch streams first so surviving streams never relist. Returns
        the number of pods re-created."""
        import time

        from kubernetes_tpu.observability import get_tracer

        t0 = time.monotonic()
        self.ledger.mark_dead(cid)
        with self._lock:
            for group in self._watch_groups.values():
                handle = group.pop(cid, None)
                if handle is not None:
                    try:
                        handle.stop()
                    except Exception:  # noqa: BLE001 — the cell is
                        pass           # dead; its stream may be too
            orphans = [
                self._inventory[key]
                for key, owner in self._route.items()
                if owner == cid and key in self._inventory
            ]
            # drop the dead routes; create_pods re-records survivors
            for key, owner in list(self._route.items()):
                if owner == cid:
                    del self._route[key]
            for gang, owner in list(self._gang_home.items()):
                if owner == cid:
                    del self._gang_home[gang]
        if progress:
            progress(f"federation: failover cluster {cid}, "
                     f"{len(orphans)} pods to re-place")
        replaced = 0
        if orphans:
            replaced = len(self.create_pods(orphans))
        with self._lock:
            self.failovers += 1
            self.failover_replaced += replaced
        get_tracer().record(
            "fed.failover", t0, trace=f"seam:fed-{cid}",
            cluster=cid, replaced=replaced)
        return replaced

    # ------------------------------------------------------------------
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "placements": self.placements,
                "spilled": self.spilled,
                "fallback_placements": self.fallback_placements,
                "failovers": self.failovers,
                "failover_replaced": self.failover_replaced,
            }
