"""Watch-event handlers: the state-ingestion path (reference
``pkg/scheduler/eventhandlers.go:364-467 addAllEventHandlers``): unassigned
pods feed the queue, assigned pods feed the cache (plus affinity wakeups),
and node/PV/PVC/Service/StorageClass/CSINode events trigger targeted queue
moves. Change-type detection for node updates mirrors
``nodeSchedulingPropertiesChange`` (:469)."""

from __future__ import annotations

from kubernetes_tpu.api.types import FAILED, SUCCEEDED, Node, Pod
from kubernetes_tpu.apiserver.store import ADDED, DELETED, MODIFIED, Event
from kubernetes_tpu.scheduler import events as ev
# gang (coscheduling) group label; a new member activates unschedulable
# siblings via the queue's gang wakeup
from kubernetes_tpu.scheduler.framework.plugins.coscheduling import (
    GROUP_NAME_LABEL as GANG_GROUP_LABEL,
)


def assigned(pod: Pod) -> bool:
    return bool(pod.spec.node_name)


def schedulable(pod: Pod) -> bool:
    """Mirrors the pod informer's field selector (scheduler.go:652-658):
    terminal-phase pods are invisible to the scheduler."""
    return pod.status.phase not in (SUCCEEDED, FAILED)


class EventHandlers:
    def __init__(self, scheduler):
        self.sched = scheduler

    def responsible_for(self, pod: Pod) -> bool:
        """Profile match + (multi-replica mode) pod-hash queue
        ownership: a pending pod belongs to exactly one replica's
        queue. Assigned-pod events are NOT filtered here — every
        replica caches every bound pod, whoever bound it, or the
        capacity its siblings consumed would be invisible."""
        if pod.spec.scheduler_name not in self.sched.profiles:
            return False
        shard = self.sched.pod_shard
        return shard is None or shard(pod)

    def caches_node(self, name: str) -> bool:
        """Node-pool sharding (multi-replica mode): a replica given a
        disjoint node pool caches — and therefore solves over — only
        its own nodes, so concurrent replicas cannot conflict on
        capacity by construction."""
        shard = self.sched.node_shard
        return shard is None or shard(name)

    # ------------------------------------------------------------------
    def handle_many(self, events) -> None:
        """Batched watch delivery (the store's ``_dispatch_many``): runs
        of homogeneous pod events collapse to one lock acquisition on the
        cache/queue side, while ordering relative to any other event kind
        is preserved by flushing the pending run first. The two runs that
        matter at throughput scale are bind transitions (commit) and
        unassigned adds (admission)."""
        sched = self.sched
        # snapshot-staleness anchor: one max() over the batch, recorded
        # only AFTER the whole batch is applied (below) — "newest event
        # reflected" must never run ahead of what the cache holds
        newest = max((e.ts for e in events if getattr(e, "ts", 0.0)),
                     default=0.0)
        bind_run = []    # Pods newly assigned (MODIFIED, old unassigned)
        add_run = []     # unassigned schedulable ADDED pods
        delete_run = []  # assigned DELETED pods (mass preemption)
        node_run = []    # ADDED nodes (relist replay / mass registration)

        def flush():
            if node_run:
                # a relist replaying N nodes must cost ONE queue wakeup,
                # not N move-alls over every pending pod
                for n in node_run:
                    sched.cache.add_node(n)
                sched.queue.move_all_to_active_or_backoff_queue(
                    ev.NODE_ADD
                )
                node_run.clear()
            if bind_run:
                sched.cache.add_pods(bind_run)
                sched.queue.delete_many(bind_run)
                sched.queue.assigned_pods_updated(bind_run)
                bind_run.clear()
            if add_run:
                sched.queue.add_many(add_run)
                groups = {
                    g for p in add_run
                    if (g := p.metadata.labels.get(GANG_GROUP_LABEL))
                }
                sched.queue.gang_members_added(groups)
                add_run.clear()
            if delete_run:
                for p in delete_run:
                    sched.cache.remove_pod(p)
                    if p.metadata.labels.get(GANG_GROUP_LABEL):
                        for fwk in sched.profiles.values():
                            gang = fwk.get_plugin("Coscheduling")
                            if gang is not None:
                                gang.note_member_deleted(p)
                # ONE wake-up for the whole run: a per-victim move-all
                # is what made bulk preemption O(victims x pending)
                sched.queue.move_all_to_active_or_backoff_queue(
                    ev.ASSIGNED_POD_DELETE
                )
                delete_run.clear()

        runs = (bind_run, add_run, delete_run, node_run)

        def run_for(target):
            if any(r for r in runs if r is not target):
                flush()
            return target

        for event in events:
            if event.kind == "Node" and event.type == ADDED:
                if not self.caches_node(event.obj.name):
                    continue   # another replica's node pool
                run_for(node_run).append(event.obj)
                continue
            if event.kind == "Pod":
                pod = event.obj
                if (
                    event.type == MODIFIED
                    and assigned(pod)
                    and event.old_obj is not None
                    and not assigned(event.old_obj)
                ):
                    run_for(bind_run).append(pod)
                    continue
                if (
                    event.type == ADDED
                    and not assigned(pod)
                    and schedulable(pod)
                    and self.responsible_for(pod)
                ):
                    run_for(add_run).append(pod)
                    continue
                if event.type == DELETED and assigned(pod):
                    run_for(delete_run).append(pod)
                    continue
            flush()
            self._handle_one(event)
        flush()
        if newest:
            sched.cache.note_event_ts(newest)

    def handle(self, event: Event) -> None:
        self._handle_one(event)
        ts = getattr(event, "ts", 0.0)
        if ts:
            self.sched.cache.note_event_ts(ts)

    def _handle_one(self, event: Event) -> None:
        kind = event.kind
        if kind == "Pod":
            self._handle_pod(event)
        elif kind == "Node":
            self._handle_node(event)
        elif kind == "Service":
            self._move(event, {
                ADDED: ev.SERVICE_ADD, MODIFIED: ev.SERVICE_UPDATE,
                DELETED: ev.SERVICE_DELETE,
            })
        elif kind == "PersistentVolume":
            self._storage_mutated()
            self._move(event, {ADDED: ev.PV_ADD, MODIFIED: ev.PV_UPDATE})
        elif kind == "PersistentVolumeClaim":
            self._storage_mutated()
            self._move(event, {ADDED: ev.PVC_ADD, MODIFIED: ev.PVC_UPDATE})
        elif kind == "StorageClass":
            self._storage_mutated()
            self._move(event, {ADDED: ev.STORAGE_CLASS_ADD})
        elif kind == "CSINode":
            self._storage_mutated()
            self._move(event, {ADDED: ev.CSI_NODE_ADD, MODIFIED: ev.CSI_NODE_UPDATE})

    def _storage_mutated(self) -> None:
        """Storage objects (PV/PVC/StorageClass/CSINode) feed the batch
        sidecar's device mirror (volume masks, attach columns); ANY
        mutation — including DELETED, which has no queue-move event
        (deletion never helps a pending pod) — must invalidate the
        mirror like a cache mutation would. Services are excluded: the
        encoder reads no Service state."""
        self.sched.cache.note_external_mutation()

    def _move(self, event: Event, mapping) -> None:
        name = mapping.get(event.type)
        if name:
            self.sched.queue.move_all_to_active_or_backoff_queue(name)

    # ------------------------------------------------------------------
    def _handle_pod(self, event: Event) -> None:
        sched = self.sched
        pod: Pod = event.obj
        old: Pod = event.old_obj

        if event.type == ADDED:
            if assigned(pod):
                sched.cache.add_pod(pod)
                sched.queue.assigned_pod_added(pod)
            elif schedulable(pod) and self.responsible_for(pod):
                sched.queue.add(pod)
                group = pod.metadata.labels.get(GANG_GROUP_LABEL)
                if group:
                    sched.queue.gang_members_added({group})
        elif event.type == MODIFIED:
            if assigned(pod):
                if old is not None and not assigned(old):
                    # bind transition: confirm the assume, leave the queue
                    sched.cache.add_pod(pod)
                    sched.queue.delete(pod)
                else:
                    sched.cache.update_pod(old or pod, pod)
                sched.queue.assigned_pod_updated(pod)
            elif schedulable(pod) and self.responsible_for(pod):
                if not self._skip_pod_update(old, pod):
                    sched.queue.update(old, pod)
        elif event.type == DELETED:
            if assigned(pod):
                sched.cache.remove_pod(pod)
                sched.queue.move_all_to_active_or_backoff_queue(
                    ev.ASSIGNED_POD_DELETE
                )
                # a deleted bound gang member releases its Permit
                # arrival slot (a re-created gang must re-gate)
                if pod.metadata.labels.get(GANG_GROUP_LABEL):
                    for fwk in sched.profiles.values():
                        gang = fwk.get_plugin("Coscheduling")
                        if gang is not None:
                            gang.note_member_deleted(pod)
            else:
                sched.queue.delete(pod)
                # a Permit-parked pod must be rejected so its assumed
                # resources and gang slot are released (reference
                # deletePodFromSchedulingQueue → fwk.RejectWaitingPod)
                for fwk in sched.profiles.values():
                    fwk.reject_waiting_pod(pod.uid)

    def _skip_pod_update(self, old: Pod, new: Pod) -> bool:
        """Reference skipPodUpdate: an update to an *assumed* pod that only
        touches server-side fields must not churn the queue."""
        if old is None:
            return False
        if not self.sched.cache.is_assumed_pod(new):
            return False
        return (
            old.spec == new.spec
            and old.metadata.labels == new.metadata.labels
        )

    # ------------------------------------------------------------------
    def _handle_node(self, event: Event) -> None:
        sched = self.sched
        node: Node = event.obj
        old: Node = event.old_obj
        if not self.caches_node(node.name):
            return   # another replica's node pool (multi-replica mode)
        if event.type == ADDED:
            sched.cache.add_node(node)
            sched.queue.move_all_to_active_or_backoff_queue(ev.NODE_ADD)
        elif event.type == MODIFIED:
            sched.cache.update_node(old or node, node)
            change = self._node_scheduling_properties_change(old, node)
            if change:
                sched.queue.move_all_to_active_or_backoff_queue(change)
        elif event.type == DELETED:
            sched.cache.remove_node(node)

    @staticmethod
    def _node_scheduling_properties_change(old: Node, new: Node):
        """eventhandlers.go:469: only changes that could make pending pods
        schedulable wake the queue."""
        if old is None:
            return ev.NODE_ADD
        if old.spec.unschedulable != new.spec.unschedulable:
            return ev.NODE_SPEC_UNSCHEDULABLE_CHANGE
        if old.status.allocatable != new.status.allocatable:
            return ev.NODE_ALLOCATABLE_CHANGE
        if old.metadata.labels != new.metadata.labels:
            return ev.NODE_LABEL_CHANGE
        if old.spec.taints != new.spec.taints:
            return ev.NODE_TAINT_CHANGE
        return None
