"""Algorithm providers (reference
``pkg/scheduler/algorithmprovider/registry.go:71-150``): the default
per-extension-point plugin wiring, plus the ClusterAutoscaler variant that
swaps LeastAllocated for MostAllocated (:152-161) and feature-gate tweaks
(:163 applyFeatureGates)."""

from kubernetes_tpu.config.types import PluginEntry, Plugins, PluginSet


def default_plugins(feature_gates=None) -> Plugins:
    p = Plugins()
    p.queue_sort = PluginSet(enabled=[PluginEntry("PrioritySort")])
    p.pre_filter = PluginSet(
        enabled=[
            PluginEntry("NodeResourcesFit"),
            PluginEntry("NodePorts"),
            PluginEntry("PodTopologySpread"),
            PluginEntry("InterPodAffinity"),
            PluginEntry("VolumeBinding"),
        ]
    )
    p.filter = PluginSet(
        enabled=[
            PluginEntry("NodeUnschedulable"),
            PluginEntry("NodeName"),
            PluginEntry("TaintToleration"),
            PluginEntry("NodeAffinity"),
            PluginEntry("NodePorts"),
            PluginEntry("NodeResourcesFit"),
            PluginEntry("VolumeRestrictions"),
            PluginEntry("EBSLimits"),
            PluginEntry("GCEPDLimits"),
            PluginEntry("NodeVolumeLimits"),
            PluginEntry("AzureDiskLimits"),
            PluginEntry("VolumeBinding"),
            PluginEntry("VolumeZone"),
            PluginEntry("PodTopologySpread"),
            PluginEntry("InterPodAffinity"),
        ]
    )
    p.post_filter = PluginSet(enabled=[PluginEntry("DefaultPreemption")])
    p.pre_score = PluginSet(
        enabled=[
            PluginEntry("InterPodAffinity"),
            PluginEntry("PodTopologySpread"),
            PluginEntry("TaintToleration"),
        ]
    )
    p.score = PluginSet(
        enabled=[
            PluginEntry("NodeResourcesBalancedAllocation", 1),
            PluginEntry("ImageLocality", 1),
            PluginEntry("InterPodAffinity", 1),
            PluginEntry("NodeResourcesLeastAllocated", 1),
            PluginEntry("NodeAffinity", 1),
            PluginEntry("NodePreferAvoidPods", 10000),
            PluginEntry("PodTopologySpread", 2),
            PluginEntry("TaintToleration", 1),
            # device-mesh adjacency for multi-chip gangs (scores 0 for
            # every pod without a ktpu.io/mesh-block label, so the
            # entry is free for non-mesh workloads)
            PluginEntry("MeshLocality", 1),
        ]
    )
    p.reserve = PluginSet(enabled=[PluginEntry("VolumeBinding")])
    p.pre_bind = PluginSet(enabled=[PluginEntry("VolumeBinding")])
    p.bind = PluginSet(enabled=[PluginEntry("DefaultBinder")])

    # legacy default spreading unless DefaultPodTopologySpread migrates it
    if feature_gates is None or not feature_gates.enabled(
        "DefaultPodTopologySpread"
    ):
        p.pre_score.enabled.append(PluginEntry("SelectorSpread"))
        p.score.enabled.append(PluginEntry("SelectorSpread", 1))
    return p


def cluster_autoscaler_plugins(feature_gates=None) -> Plugins:
    """Bin-packing variant (registry.go:152-161)."""
    p = default_plugins(feature_gates)
    p.score.enabled = [
        PluginEntry("NodeResourcesMostAllocated", e.weight)
        if e.name == "NodeResourcesLeastAllocated"
        else e
        for e in p.score.enabled
    ]
    return p


def gang_scheduling_plugins(feature_gates=None) -> Plugins:
    """Defaults + the out-of-tree coscheduling wiring (SURVEY.md
    section 6: gang scheduling is a Permit-phase pattern, registered the
    way out-of-tree plugins merge into the framework): gang-aware queue
    sort (identical to PrioritySort for non-gang pods), gang-backoff
    PreFilter, and the Permit gate. BASELINE config #5's profile."""
    p = default_plugins(feature_gates)
    p.queue_sort = PluginSet(enabled=[PluginEntry("CoschedulingSort")])
    p.pre_filter.enabled.append(PluginEntry("Coscheduling"))
    p.permit = PluginSet(enabled=[PluginEntry("Coscheduling")])
    return p


PROVIDERS = {
    "DefaultProvider": default_plugins,
    "ClusterAutoscalerProvider": cluster_autoscaler_plugins,
    "GangSchedulingProvider": gang_scheduling_plugins,
}
